"""core/v1 object model: Pod, Service, ConfigMap, Secret, Event.

The subset of k8s.io/api/core/v1 the operator constructs and inspects
(reference: pkg/controller/mpi_job_controller.go object builders at
:1335-1674 and pod phase checks at :840-858, :1143-1164).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from .meta import ObjectMeta

# Kubernetes IntOrString (probe/service ports accept 8080 or "http");
# codegen maps this union to x-kubernetes-int-or-string.
IntOrString = Union[int, str]

# Pod phases (k8s.io/api/core/v1 PodPhase)
POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"

CONDITION_TRUE = "True"
CONDITION_FALSE = "False"
CONDITION_UNKNOWN = "Unknown"

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"

# Secret types / keys (corev1.SecretTypeSSHAuth, corev1.SSHAuthPrivateKey)
SECRET_TYPE_SSH_AUTH = "kubernetes.io/ssh-auth"
SSH_AUTH_PRIVATE_KEY = "ssh-privatekey"

CLUSTER_IP_NONE = "None"
DNS_CLUSTER_FIRST_WITH_HOST_NET = "ClusterFirstWithHostNet"

RESTART_POLICY_ALWAYS = "Always"
RESTART_POLICY_ON_FAILURE = "OnFailure"
RESTART_POLICY_NEVER = "Never"


@dataclass
class ObjectFieldSelector:
    field_path: str = ""
    api_version: str = ""


@dataclass
class KeySelector:
    """configMapKeyRef / secretKeyRef shape."""
    name: str = ""
    key: str = ""
    optional: Optional[bool] = None


@dataclass
class ResourceFieldSelector:
    container_name: str = ""
    resource: str = ""
    divisor: str = ""


@dataclass
class FileKeySelector:
    """env valueFrom.fileKeyRef (k8s 1.34 env-from-file)."""
    key: str = ""
    path: str = ""
    volume_name: str = ""
    optional: Optional[bool] = None


@dataclass
class EnvVarSource:
    field_ref: Optional[ObjectFieldSelector] = None
    resource_field_ref: Optional[ResourceFieldSelector] = None
    config_map_key_ref: Optional[KeySelector] = None
    secret_key_ref: Optional[KeySelector] = None
    file_key_ref: Optional[FileKeySelector] = None


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""
    value_from: Optional[EnvVarSource] = None


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: Optional[bool] = None
    sub_path: str = ""
    sub_path_expr: str = ""
    mount_propagation: Optional[str] = None
    recursive_read_only: Optional[str] = None


@dataclass
class KeyToPath:
    key: str = ""
    path: str = ""
    mode: Optional[int] = None


@dataclass
class ConfigMapVolumeSource:
    name: str = ""
    items: List[KeyToPath] = field(default_factory=list)
    default_mode: Optional[int] = None
    optional: Optional[bool] = None


@dataclass
class SecretVolumeSource:
    secret_name: str = ""
    items: List[KeyToPath] = field(default_factory=list)
    default_mode: Optional[int] = None
    optional: Optional[bool] = None


@dataclass
class EmptyDirVolumeSource:
    medium: str = ""
    size_limit: str = ""


@dataclass
class HostPathVolumeSource:
    path: str = ""
    type: str = ""


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str = ""
    read_only: Optional[bool] = None


# --- full corev1 volume-source surface -------------------------------------
# Every volume type the reference CRD admits (controller-gen embeds the
# whole k8s PodSpec; /root/reference/manifests/base/
# kubeflow.org_mpijobs.yaml volumes[] schema).  With structural
# no-preserve-unknown schemas, any source missing here would be silently
# pruned on admission — codegen/crd_parity.py enforces the full list.

@dataclass
class AWSElasticBlockStoreVolumeSource:
    volume_id: str = ""
    fs_type: str = ""
    partition: Optional[int] = None
    read_only: Optional[bool] = None


@dataclass
class AzureDiskVolumeSource:
    disk_name: str = ""
    disk_uri: str = ""
    caching_mode: str = ""
    fs_type: str = ""
    kind: str = ""
    read_only: Optional[bool] = None


@dataclass
class AzureFileVolumeSource:
    secret_name: str = ""
    share_name: str = ""
    read_only: Optional[bool] = None


@dataclass
class CephFSVolumeSource:
    monitors: List[str] = field(default_factory=list)
    path: str = ""
    user: str = ""
    secret_file: str = ""
    secret_ref: Optional["LocalObjectReference"] = None
    read_only: Optional[bool] = None


@dataclass
class CinderVolumeSource:
    volume_id: str = ""
    fs_type: str = ""
    read_only: Optional[bool] = None
    secret_ref: Optional["LocalObjectReference"] = None


@dataclass
class CSIVolumeSource:
    driver: str = ""
    read_only: Optional[bool] = None
    fs_type: str = ""
    volume_attributes: Dict[str, str] = field(default_factory=dict)
    node_publish_secret_ref: Optional["LocalObjectReference"] = None


@dataclass
class DownwardAPIVolumeFile:
    path: str = ""
    field_ref: Optional[ObjectFieldSelector] = None
    resource_field_ref: Optional[ResourceFieldSelector] = None
    mode: Optional[int] = None


@dataclass
class DownwardAPIVolumeSource:
    items: List[DownwardAPIVolumeFile] = field(default_factory=list)
    default_mode: Optional[int] = None


@dataclass
class TypedLocalObjectReference:
    api_group: Optional[str] = None
    kind: str = ""
    name: str = ""


@dataclass
class TypedObjectReference:
    api_group: Optional[str] = None
    kind: str = ""
    name: str = ""
    namespace: Optional[str] = None


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: List[str] = field(default_factory=list)
    selector: Optional[dict] = None          # LabelSelector
    resources: Optional["ResourceRequirements"] = None
    volume_name: str = ""
    storage_class_name: Optional[str] = None
    volume_mode: Optional[str] = None
    data_source: Optional[TypedLocalObjectReference] = None
    data_source_ref: Optional[TypedObjectReference] = None
    volume_attributes_class_name: Optional[str] = None


@dataclass
class PersistentVolumeClaimTemplate:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[PersistentVolumeClaimSpec] = None


@dataclass
class EphemeralVolumeSource:
    volume_claim_template: Optional[PersistentVolumeClaimTemplate] = None


@dataclass
class FCVolumeSource:
    target_wwns: List[str] = field(default_factory=list)
    lun: Optional[int] = None
    fs_type: str = ""
    read_only: Optional[bool] = None
    wwids: List[str] = field(default_factory=list)


@dataclass
class FlexVolumeSource:
    driver: str = ""
    fs_type: str = ""
    secret_ref: Optional["LocalObjectReference"] = None
    read_only: Optional[bool] = None
    options: Dict[str, str] = field(default_factory=dict)


@dataclass
class FlockerVolumeSource:
    dataset_name: str = ""
    dataset_uuid: str = ""


@dataclass
class GCEPersistentDiskVolumeSource:
    pd_name: str = ""
    fs_type: str = ""
    partition: Optional[int] = None
    read_only: Optional[bool] = None


@dataclass
class GitRepoVolumeSource:
    repository: str = ""
    revision: str = ""
    directory: str = ""


@dataclass
class GlusterfsVolumeSource:
    endpoints: str = ""
    path: str = ""
    read_only: Optional[bool] = None


@dataclass
class ImageVolumeSource:
    reference: str = ""
    pull_policy: str = ""


@dataclass
class ISCSIVolumeSource:
    target_portal: str = ""
    iqn: str = ""
    lun: Optional[int] = None
    iscsi_interface: str = ""
    fs_type: str = ""
    read_only: Optional[bool] = None
    portals: List[str] = field(default_factory=list)
    chap_auth_discovery: Optional[bool] = None
    chap_auth_session: Optional[bool] = None
    secret_ref: Optional["LocalObjectReference"] = None
    initiator_name: Optional[str] = None


@dataclass
class NFSVolumeSource:
    server: str = ""
    path: str = ""
    read_only: Optional[bool] = None


@dataclass
class PhotonPersistentDiskVolumeSource:
    pd_id: str = ""
    fs_type: str = ""


@dataclass
class PortworxVolumeSource:
    volume_id: str = ""
    fs_type: str = ""
    read_only: Optional[bool] = None


@dataclass
class ClusterTrustBundleProjection:
    name: Optional[str] = None
    signer_name: Optional[str] = None
    label_selector: Optional[dict] = None    # LabelSelector
    optional: Optional[bool] = None
    path: str = ""


@dataclass
class PodCertificateProjection:
    signer_name: str = ""
    key_type: str = ""
    max_expiration_seconds: Optional[int] = None
    credential_bundle_path: str = ""
    key_path: str = ""
    certificate_chain_path: str = ""
    user_annotations: Dict[str, str] = field(default_factory=dict)


@dataclass
class SecretProjection:
    name: str = ""
    items: List[KeyToPath] = field(default_factory=list)
    optional: Optional[bool] = None


@dataclass
class ConfigMapProjection:
    name: str = ""
    items: List[KeyToPath] = field(default_factory=list)
    optional: Optional[bool] = None


@dataclass
class DownwardAPIProjection:
    items: List[DownwardAPIVolumeFile] = field(default_factory=list)


@dataclass
class ServiceAccountTokenProjection:
    audience: str = ""
    expiration_seconds: Optional[int] = None
    path: str = ""


@dataclass
class VolumeProjection:
    secret: Optional[SecretProjection] = None
    config_map: Optional[ConfigMapProjection] = None
    downward_api: Optional[DownwardAPIProjection] = None
    service_account_token: Optional[ServiceAccountTokenProjection] = None
    cluster_trust_bundle: Optional[ClusterTrustBundleProjection] = None
    pod_certificate: Optional[PodCertificateProjection] = None


@dataclass
class ProjectedVolumeSource:
    sources: List[VolumeProjection] = field(default_factory=list)
    default_mode: Optional[int] = None


@dataclass
class QuobyteVolumeSource:
    registry: str = ""
    volume: str = ""
    read_only: Optional[bool] = None
    user: str = ""
    group: str = ""
    tenant: str = ""


@dataclass
class RBDVolumeSource:
    monitors: List[str] = field(default_factory=list)
    image: str = ""
    fs_type: str = ""
    pool: str = ""
    user: str = ""
    keyring: str = ""
    secret_ref: Optional["LocalObjectReference"] = None
    read_only: Optional[bool] = None


@dataclass
class ScaleIOVolumeSource:
    gateway: str = ""
    system: str = ""
    secret_ref: Optional["LocalObjectReference"] = None
    ssl_enabled: Optional[bool] = None
    protection_domain: str = ""
    storage_pool: str = ""
    storage_mode: str = ""
    volume_name: str = ""
    fs_type: str = ""
    read_only: Optional[bool] = None


@dataclass
class StorageOSVolumeSource:
    volume_name: str = ""
    volume_namespace: str = ""
    fs_type: str = ""
    read_only: Optional[bool] = None
    secret_ref: Optional["LocalObjectReference"] = None


@dataclass
class VsphereVirtualDiskVolumeSource:
    volume_path: str = ""
    fs_type: str = ""
    storage_policy_name: str = ""
    storage_policy_id: str = ""


@dataclass
class Volume:
    name: str = ""
    config_map: Optional[ConfigMapVolumeSource] = None
    secret: Optional[SecretVolumeSource] = None
    empty_dir: Optional[EmptyDirVolumeSource] = None
    host_path: Optional[HostPathVolumeSource] = None
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    azure_disk: Optional[AzureDiskVolumeSource] = None
    azure_file: Optional[AzureFileVolumeSource] = None
    cephfs: Optional[CephFSVolumeSource] = None
    cinder: Optional[CinderVolumeSource] = None
    csi: Optional[CSIVolumeSource] = None
    downward_api: Optional[DownwardAPIVolumeSource] = None
    ephemeral: Optional[EphemeralVolumeSource] = None
    fc: Optional[FCVolumeSource] = None
    flex_volume: Optional[FlexVolumeSource] = None
    flocker: Optional[FlockerVolumeSource] = None
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    git_repo: Optional[GitRepoVolumeSource] = None
    glusterfs: Optional[GlusterfsVolumeSource] = None
    image: Optional[ImageVolumeSource] = None
    iscsi: Optional[ISCSIVolumeSource] = None
    nfs: Optional[NFSVolumeSource] = None
    photon_persistent_disk: Optional[PhotonPersistentDiskVolumeSource] = None
    portworx_volume: Optional[PortworxVolumeSource] = None
    projected: Optional[ProjectedVolumeSource] = None
    quobyte: Optional[QuobyteVolumeSource] = None
    rbd: Optional[RBDVolumeSource] = None
    scale_io: Optional[ScaleIOVolumeSource] = None
    storageos: Optional[StorageOSVolumeSource] = None
    vsphere_volume: Optional[VsphereVirtualDiskVolumeSource] = None


@dataclass
class ResourceClaim:
    name: str = ""
    request: str = ""


@dataclass
class ResourceRequirements:
    limits: dict = field(default_factory=dict)
    requests: dict = field(default_factory=dict)
    claims: List[ResourceClaim] = field(default_factory=list)


@dataclass
class ContainerPort:
    name: str = ""
    container_port: int = 0
    protocol: str = ""
    host_ip: str = ""
    host_port: Optional[int] = None


# --- probe / lifecycle handlers (corev1.Probe, corev1.Lifecycle) ----------
# Reference CRD surface: manifests/base/kubeflow.org_mpijobs.yaml
# (livenessProbe/readinessProbe/startupProbe, lifecycle) — absent from the
# round-3 schema, so user probe configs were silently pruned on admission.

@dataclass
class ExecAction:
    command: List[str] = field(default_factory=list)


@dataclass
class HTTPHeader:
    name: str = ""
    value: str = ""


@dataclass
class HTTPGetAction:
    path: str = ""
    port: Optional[IntOrString] = None
    host: str = ""
    scheme: str = ""
    http_headers: List[HTTPHeader] = field(default_factory=list)


@dataclass
class TCPSocketAction:
    port: Optional[IntOrString] = None
    host: str = ""


@dataclass
class GRPCAction:
    port: int = 0
    service: str = ""


@dataclass
class SleepAction:
    seconds: int = 0


@dataclass
class Probe:
    exec: Optional[ExecAction] = None
    http_get: Optional[HTTPGetAction] = None
    tcp_socket: Optional[TCPSocketAction] = None
    grpc: Optional[GRPCAction] = None
    initial_delay_seconds: Optional[int] = None
    timeout_seconds: Optional[int] = None
    period_seconds: Optional[int] = None
    success_threshold: Optional[int] = None
    failure_threshold: Optional[int] = None
    termination_grace_period_seconds: Optional[int] = None


@dataclass
class LifecycleHandler:
    exec: Optional[ExecAction] = None
    http_get: Optional[HTTPGetAction] = None
    tcp_socket: Optional[TCPSocketAction] = None
    sleep: Optional[SleepAction] = None


@dataclass
class Lifecycle:
    post_start: Optional[LifecycleHandler] = None
    pre_stop: Optional[LifecycleHandler] = None
    stop_signal: Optional[str] = None


@dataclass
class ConfigMapEnvSource:
    name: str = ""
    optional: Optional[bool] = None


@dataclass
class SecretEnvSource:
    name: str = ""
    optional: Optional[bool] = None


@dataclass
class EnvFromSource:
    prefix: str = ""
    config_map_ref: Optional[ConfigMapEnvSource] = None
    secret_ref: Optional[SecretEnvSource] = None


@dataclass
class VolumeDevice:
    name: str = ""
    device_path: str = ""


@dataclass
class ContainerResizePolicy:
    resource_name: str = ""
    restart_policy: str = ""


@dataclass
class ContainerRestartRuleOnExitCodes:
    operator: str = ""
    values: List[int] = field(default_factory=list)


@dataclass
class ContainerRestartRule:
    action: str = ""
    exit_codes: Optional[ContainerRestartRuleOnExitCodes] = None


@dataclass
class Container:
    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    working_dir: str = ""
    env: List[EnvVar] = field(default_factory=list)
    env_from: List[EnvFromSource] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    volume_devices: List[VolumeDevice] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    image_pull_policy: str = ""
    security_context: Optional[dict] = None
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    startup_probe: Optional[Probe] = None
    lifecycle: Optional[Lifecycle] = None
    termination_message_path: str = ""
    termination_message_policy: str = ""
    resize_policy: List[ContainerResizePolicy] = field(default_factory=list)
    restart_policy: str = ""  # sidecar ("Always") for init containers
    restart_policy_rules: List[ContainerRestartRule] = field(
        default_factory=list)
    stdin: Optional[bool] = None
    stdin_once: Optional[bool] = None
    tty: Optional[bool] = None


@dataclass
class EphemeralContainer(Container):
    """Debug container injected into a running pod (kubectl debug).

    The kube API models this as EphemeralContainerCommon (every Container
    field) + targetContainerName; dataclass inheritance gives the same
    shape.  Reference CRD schema:
    manifests/base/kubeflow.org_mpijobs.yaml:2674 (/root/reference)."""
    target_container_name: str = ""


@dataclass
class PodDNSConfig:
    nameservers: list = field(default_factory=list)
    searches: list = field(default_factory=list)
    options: list = field(default_factory=list)


@dataclass
class Toleration:
    key: str = ""
    operator: str = ""
    value: str = ""
    effect: str = ""
    toleration_seconds: Optional[int] = None


@dataclass
class LocalObjectReference:
    name: str = ""


# --- pod-level scheduling/runtime surface ----------------------------------
# Reference CRD: topologySpreadConstraints, runtimeClassName,
# readinessGates, overhead, preemptionPolicy, hostAliases — absent from
# the round-3 schema (silent admission-prune hazard).

@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = ""
    label_selector: Optional[dict] = None
    min_domains: Optional[int] = None
    match_label_keys: List[str] = field(default_factory=list)
    node_affinity_policy: str = ""
    node_taints_policy: str = ""


@dataclass
class PodReadinessGate:
    condition_type: str = ""


@dataclass
class HostAlias:
    ip: str = ""
    hostnames: List[str] = field(default_factory=list)


@dataclass
class PodOS:
    name: str = ""


@dataclass
class PodResourceClaim:
    name: str = ""
    resource_claim_name: Optional[str] = None
    resource_claim_template_name: Optional[str] = None


@dataclass
class PodWorkloadRef:
    """spec.workloadRef (k8s Workload-aware scheduling; reference CRD
    manifests/base/kubeflow.org_mpijobs.yaml:8632)."""
    name: str = ""
    pod_group: str = ""
    pod_group_replica_key: str = ""


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    ephemeral_containers: List[EphemeralContainer] = field(
        default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    restart_policy: str = ""
    hostname: str = ""
    subdomain: str = ""
    host_network: bool = False
    host_pid: Optional[bool] = None
    host_ipc: Optional[bool] = None
    share_process_namespace: Optional[bool] = None
    dns_policy: str = ""
    dns_config: Optional[PodDNSConfig] = None
    node_selector: dict = field(default_factory=dict)
    node_name: str = ""
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(
        default_factory=list)
    scheduling_gates: list = field(default_factory=list)
    scheduler_name: str = ""
    runtime_class_name: Optional[str] = None
    priority_class_name: str = ""
    priority: Optional[int] = None
    preemption_policy: Optional[str] = None
    readiness_gates: List[PodReadinessGate] = field(default_factory=list)
    overhead: dict = field(default_factory=dict)
    host_aliases: List[HostAlias] = field(default_factory=list)
    service_account_name: str = ""
    automount_service_account_token: Optional[bool] = None
    image_pull_secrets: List[LocalObjectReference] = field(
        default_factory=list)
    affinity: Optional[dict] = None
    security_context: Optional[dict] = None
    termination_grace_period_seconds: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    enable_service_links: Optional[bool] = None
    set_hostname_as_fqdn: Optional[bool] = None
    os: Optional[PodOS] = None
    host_users: Optional[bool] = None
    hostname_override: Optional[str] = None
    service_account: str = ""  # deprecated alias of service_account_name
    resource_claims: List[PodResourceClaim] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None
    workload_ref: Optional[PodWorkloadRef] = None


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class ContainerStateTerminated:
    exit_code: int = 0
    reason: str = ""
    message: str = ""


@dataclass
class ContainerState:
    terminated: Optional[ContainerStateTerminated] = None


@dataclass
class ContainerStatus:
    name: str = ""
    state: Optional[ContainerState] = None
    ready: bool = False
    restart_count: int = 0


@dataclass
class PodStatus:
    phase: str = ""
    conditions: List[PodCondition] = field(default_factory=list)
    reason: str = ""
    message: str = ""
    container_statuses: List[ContainerStatus] = field(default_factory=list)
    pod_ip: str = ""
    host_ip: str = ""


@dataclass
class Pod:
    api_version: str = "v1"
    kind: str = "Pod"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: Optional[int] = None
    protocol: str = ""


@dataclass
class ServiceSpec:
    cluster_ip: str = ""
    selector: dict = field(default_factory=dict)
    publish_not_ready_addresses: bool = False
    ports: List[ServicePort] = field(default_factory=list)


@dataclass
class Service:
    api_version: str = "v1"
    kind: str = "Service"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)


@dataclass
class ConfigMap:
    api_version: str = "v1"
    kind: str = "ConfigMap"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict = field(default_factory=dict)
    binary_data: dict = field(default_factory=dict)


@dataclass
class Secret:
    api_version: str = "v1"
    kind: str = "Secret"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    type: str = ""
    data: dict = field(default_factory=dict)  # str -> bytes


@dataclass
class ObjectReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    namespace: str = ""
    uid: str = ""


@dataclass
class Event:
    api_version: str = "v1"
    kind: str = "Event"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    type: str = ""
    reason: str = ""
    message: str = ""
    count: int = 1
    # Aggregation window (client-go EventAggregator semantics): repeats
    # of the same (object, type, reason, message) bump count and
    # last_timestamp on one Event instead of creating N objects.
    first_timestamp: Optional[datetime.datetime] = None
    last_timestamp: Optional[datetime.datetime] = None


def pod_running_and_ready(pod: Pod) -> bool:
    """isPodRunningAndReady equivalent (WaitForWorkersReady gating,
    reference: mpi_job_controller.go countReadyWorkerPods / workersReady)."""
    if pod.status.phase != POD_RUNNING:
        return False
    for cond in pod.status.conditions:
        if cond.type == "Ready" and cond.status == CONDITION_TRUE:
            return True
    return False
