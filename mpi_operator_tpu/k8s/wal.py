"""Write-ahead log + snapshot store for the durable apiserver.

The persistence substrate behind ``ApiServer(wal_dir=...)``
(docs/RESILIENCE.md "Durable apiserver"): every mutating verb appends
ONE self-describing record keyed by the global etcd-style revision;
LEADER-BASED GROUP COMMIT makes records durable (the first barrier-ing
writer serializes + fsyncs the whole pending buffer — one disk barrier
acknowledges every concurrent writer, so the PR 7 sharded write path
keeps its storm throughput); and periodic snapshots bound replay time
by rolling the log onto a fresh segment.

Record format (one JSON object per line):

    {"rv": <int revision>, "verb": create|update|delete,
     "ts": <injectable-clock timestamp>,
     "obj": <registry.encode() of the FULL post-write object,
             including the assigned resourceVersion — apiVersion/kind/
             namespace/name live inside it>}

``verb`` is the REPLAY shape, not the API verb: update and
patch_status both append ``update`` (the record carries the full
post-write object, so replay is a pure install — idempotent under the
per-object revision guard the apiserver applies, which is what makes
fuzzy snapshots safe).

Durability contract: records are appended in REVISION ORDER (the
apiserver couples revision assignment and buffer append under one
lock), and each commit covers a strict PREFIX of that order — so the
durable set is always revision-prefix-closed, and an acknowledged
write (one whose verb returned) can never be durable while an earlier
revision is not.  ``crash()`` simulates power loss in-process: the
un-fsynced tail is truncated away and parked waiters get the error
their real client would (the write was never acknowledged, so losing
it is correct).

Torn-tail recovery: only the FINAL record of the FINAL segment may be
torn (appends are sequential); a trailing line that fails to parse or
lacks its newline is dropped and counted.  A torn line anywhere else
is real corruption and fails replay loudly.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Iterator, List, Optional, Tuple

SEGMENT_PREFIX = "wal-"
SNAPSHOT_PREFIX = "snapshot-"
_TMP_SUFFIX = ".tmp"


class WalCorruptionError(RuntimeError):
    """A WAL segment or snapshot is damaged somewhere other than the
    legal torn-tail position — replay refuses to guess."""


class WalCrashedError(RuntimeError):
    """The log was crashed while this writer awaited durability; the
    write was NOT acknowledged and may not survive replay."""


def _segment_name(index: int) -> str:
    return f"{SEGMENT_PREFIX}{index:08d}.log"


def _snapshot_name(index: int) -> str:
    return f"{SNAPSHOT_PREFIX}{index:08d}.json"


def _parse_index(name: str, prefix: str, suffix: str) -> Optional[int]:
    if not (name.startswith(prefix) and name.endswith(suffix)):
        return None
    body = name[len(prefix):-len(suffix)]
    return int(body) if body.isdigit() else None


class WriteAheadLog:
    """Append-only segmented log with leader-based group commit.

    Thread-safe: any number of writers call :meth:`append` +
    :meth:`barrier`; the first barrier to find no flush in flight
    becomes the committing leader (see :meth:`barrier`).  All I/O is
    off the append path — ``append`` only buffers, so it is safe to
    call while holding the apiserver's revision lock (that coupling is
    what keeps append order == revision order).
    """

    def __init__(self, wal_dir: str, fsync: bool = True,
                 counters: Optional[dict] = None,
                 on_commit: Optional[Callable[[int], None]] = None):
        self.dir = wal_dir
        os.makedirs(wal_dir, exist_ok=True)
        self.fsync_enabled = fsync
        # Optional shared-registry mirrors ("appends"/"fsyncs"/
        # "snapshots" -> Counter-shaped objects with .inc()); the
        # instance totals below stay authoritative for benches.
        self._counters = counters or {}
        # Called (flusher thread, no WAL lock held) with the durable
        # sequence after every fsync: the apiserver's post-commit watch
        # delivery hook — watchers must never observe a write a crash
        # could still roll back.
        self._on_commit = on_commit
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._buffer: List[dict] = []       # records awaiting write+fsync
        self._appended_seq = 0              # last seq handed to a writer
        self._durable_seq = 0               # last seq covered by an fsync
        self._crashed = False
        self._closed = False
        self._flushing = False              # a leader's I/O is in flight
        # Telemetry (instance-exact for benches; the apiserver mirrors
        # into the shared registry).
        self.appends_total = 0
        self.fsyncs_total = 0
        self.bytes_total = 0
        self.snapshots_total = 0
        self.torn_records_dropped = 0
        # Resume onto the newest existing segment (respawn path); a
        # fresh dir starts segment 1.
        segs = self.segments()
        self._segment = segs[-1] if segs else 1
        path = os.path.join(self.dir, _segment_name(self._segment))
        if segs:
            # A crash can leave torn final-record bytes that fstat
            # would count as durable: appending after them would weld
            # the next record onto the partial line — turning a LEGAL
            # torn tail into mid-log corruption on the next replay (or
            # silently swallowing the new record if the merged line
            # stayed last).  Trim back to the last intact-record
            # boundary BEFORE opening for append.
            self.torn_records_dropped += truncate_torn_tail(path)
        # Raw fd + os.write + os.fdatasync: every syscall is a GIL
        # release/reacquire round trip, brutal on a loaded single-core
        # host — the buffered write/flush/fsync triple costs one more
        # than needed, and fdatasync skips the metadata barrier the
        # record stream doesn't need.
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        self._write_offset = os.fstat(self._fd).st_size
        self._durable_offset = self._write_offset

    # -- append / durability ----------------------------------------------
    def append(self, record) -> int:
        """Buffer one record; returns its commit sequence (monotonic).
        Caller guarantees records arrive in revision order (the
        apiserver appends under its revision lock).  ``record`` may be
        a dict, or a zero-arg callable returning one — invoked by the
        committing leader at write time, so expensive encoding runs off
        the append path (the referenced object must be frozen from
        append on, which the store's replace-don't-mutate discipline
        guarantees)."""
        with self._cond:
            if self._crashed or self._closed:
                raise WalCrashedError("write-ahead log is closed")
            self._appended_seq += 1
            self.appends_total += 1
            self._buffer.append(record)
            return self._appended_seq

    def appended_seq(self) -> int:
        """Sequence of the most recently appended record (a just-
        appended record's seq is <= this snapshot)."""
        with self._cond:
            return self._appended_seq

    def barrier(self, seq: Optional[int] = None) -> None:
        """Block until ``seq`` (default: everything appended so far) is
        durable.  Passing the caller's own append seq both narrows the
        wait and enables the lock-free fast path below.

        LEADER-BASED GROUP COMMIT: the first barrier to find no flush
        in flight becomes the leader — it takes the whole pending
        buffer and does serialize+write+fsync itself (no thread
        hand-off, no context switch in the uncontended case); every
        other barrier parks on the condition and is satisfied by the
        leader's single fsync.  Records appended while a leader's I/O
        is in flight accumulate for the NEXT leader — that pile-up IS
        the amortization that keeps the PR 7 storm write path fast."""
        if seq is not None and self._durable_seq >= seq:
            # Dirty read is safe: _durable_seq is a monotonically
            # increasing int published under the lock — a stale value
            # only sends us through the locked slow path, never past an
            # uncommitted record.
            return
        commit_seq = None
        with self._cond:
            want = self._appended_seq if seq is None else seq
            while self._durable_seq < want:
                if self._crashed or self._closed:
                    raise WalCrashedError(
                        "apiserver crashed before this write committed")
                if self._buffer and not self._flushing:
                    commit_seq = self._flush_as_leader_locked()
                else:
                    self._cond.wait(timeout=0.5)
        if commit_seq is not None and self._on_commit is not None:
            self._on_commit(commit_seq)

    def _flush_as_leader_locked(self) -> Optional[int]:
        """Called with the condition held: claim the pending buffer,
        release the lock for the I/O, publish durability, wake the
        group.  Returns the committed sequence (None when the crash
        flag aborted publication)."""
        self._flushing = True
        batch = self._buffer
        self._buffer = []
        seq = self._appended_seq
        self._cond.release()
        committed = None
        written = 0
        failed = True
        try:
            # No sort_keys: record key order is the builders' insertion
            # order, already deterministic — sorting here costs real
            # time on every storm write.
            lines = b"".join(
                json.dumps(r() if callable(r) else r,
                           separators=(",", ":")).encode() + b"\n"
                for r in batch)
            view = memoryview(lines)
            while written < len(lines):
                # os.write may write short (signals); an unchecked short
                # write would silently diverge the offset accounting.
                written += os.write(self._fd, view[written:])
            if self.fsync_enabled:
                os.fdatasync(self._fd)
            failed = False
            self.fsyncs_total += 1
            self.bytes_total += written
            mirror = self._counters.get("fsyncs")
            if mirror is not None:
                mirror.inc()
            mirror = self._counters.get("appends")
            if mirror is not None:
                # Mirrored per BATCH, not per append: the registry
                # counter's lock would otherwise sit on every write's
                # critical path.
                mirror.inc(len(batch))
            committed = seq
        finally:
            self._cond.acquire()
            self._flushing = False
            self._write_offset += written
            if failed and not self._crashed:
                # FAIL-STOP: the claimed batch is gone and durability
                # can no longer be promised (ENOSPC, dead disk...).
                # Without this, the leader's exception surfaces to ONE
                # caller while every parked follower waits forever for
                # an acknowledgement that can never come.
                self._crashed = True
            if committed is not None and not self._crashed:
                self._durable_seq = max(self._durable_seq, committed)
                self._durable_offset = self._write_offset
            else:
                committed = None  # crash raced the fsync: never acked
            # Wake exactly the satisfied waiters plus ONE candidate to
            # lead the next batch — FIFO order means the oldest waiters
            # are the satisfied ones, and a notify_all herd would park-
            # and-rewake every unsatisfied follower per flush (real
            # money on a loaded single core).  The 0.5s wait timeout
            # backstops any miscount.
            self._cond.notify(len(batch) + 1)
        return committed

    def durable_sizes(self) -> dict:
        """{segment index: durable byte length} — the torn-truncation
        boundary tests replay against (crash-prefix property test).
        Drives a flush of anything still pending."""
        self.barrier()
        with self._cond:
            out = {}
            for seg in self.segments():
                path = os.path.join(self.dir, _segment_name(seg))
                out[seg] = (self._durable_offset
                            if seg == self._segment
                            else os.path.getsize(path))
            return out

    # -- snapshots / segments ---------------------------------------------
    def roll_segment(self) -> int:
        """Start a fresh segment; returns the NEW segment index.
        Pending un-flushed records simply land in the new segment —
        the replay guard makes snapshot/segment overlap idempotent, so
        the roll never has to drain a hot log."""
        with self._cond:
            if self._crashed or self._closed:
                raise WalCrashedError("write-ahead log is closed")
            while self._flushing:
                self._cond.wait(timeout=0.1)
                if self._crashed or self._closed:
                    raise WalCrashedError("write-ahead log is closed")
            os.close(self._fd)
            self._segment += 1
            path = os.path.join(self.dir, _segment_name(self._segment))
            self._fd = os.open(path,
                               os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                               0o644)
            self._write_offset = 0
            self._durable_offset = 0
            return self._segment

    def durable_seq(self) -> int:
        """Last committed sequence (dirty read: a monotonically
        increasing int published under the lock — stale only ever
        UNDER-reports)."""
        return self._durable_seq

    def commit_snapshot(self, base_segment: int, payload: dict) -> None:
        """Atomically install a snapshot covering every segment below
        ``base_segment``, then prune those segments and older
        snapshots (their records are all reflected in the payload).
        Refuses after a crash: a snapshot committed post-power-cut
        would resurrect writes whose records the crash truncated away
        (callers barrier the captured state durable FIRST, so an
        aborted snapshot loses nothing)."""
        with self._cond:
            if self._crashed or self._closed:
                raise WalCrashedError("write-ahead log is closed")
        name = _snapshot_name(base_segment)
        tmp = os.path.join(self.dir, name + _TMP_SUFFIX)
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True, separators=(",", ":"))
            f.flush()
            if self.fsync_enabled:
                os.fsync(f.fileno())
        with self._cond:
            if self._crashed:
                # Crash landed while the payload was being written:
                # abandon the tmp file — never install, never prune.
                return
            os.replace(tmp, os.path.join(self.dir, name))
        self.snapshots_total += 1
        mirror = self._counters.get("snapshots")
        if mirror is not None:
            mirror.inc()
        for seg in self.segments():
            if seg < base_segment:
                self._remove(_segment_name(seg))
        for snap in self.snapshot_indexes():
            if snap < base_segment:
                self._remove(_snapshot_name(snap))

    def _remove(self, name: str) -> None:
        try:
            os.remove(os.path.join(self.dir, name))
        except OSError:
            pass  # already gone: pruning is best-effort

    def segments(self) -> List[int]:
        out = []
        for name in os.listdir(self.dir):
            idx = _parse_index(name, SEGMENT_PREFIX, ".log")
            if idx is not None:
                out.append(idx)
        return sorted(out)

    def snapshot_indexes(self) -> List[int]:
        out = []
        for name in os.listdir(self.dir):
            idx = _parse_index(name, SNAPSHOT_PREFIX, ".json")
            if idx is not None:
                out.append(idx)
        return sorted(out)

    # -- lifecycle ---------------------------------------------------------
    def crash(self) -> None:
        """Abrupt process death: the un-fsynced tail (buffered records
        AND written-but-not-yet-fsynced bytes) is LOST — the file is
        truncated back to the last durable offset, exactly what a power
        cut would leave — and every parked writer is released with
        :class:`WalCrashedError` (its write was never acknowledged)."""
        with self._cond:
            if self._crashed:
                return
            self._crashed = True
            self._buffer = []
            self._cond.notify_all()
            # An in-flight leader still owns the file handle: wait for
            # it to re-acquire and bail (its publish is suppressed by
            # the crash flag).
            while self._flushing:
                self._cond.wait(timeout=0.1)
            durable_offset = self._durable_offset
        try:
            os.close(self._fd)
        except OSError:
            pass
        path = os.path.join(self.dir, _segment_name(self._segment))
        with open(path, "rb+") as f:
            f.truncate(durable_offset)

    def close(self) -> None:
        """Graceful shutdown: drain + fsync everything, then stop."""
        commit_seq = None
        with self._cond:
            if self._crashed or self._closed:
                return
            while self._flushing:
                self._cond.wait(timeout=0.1)
            if self._buffer:
                commit_seq = self._flush_as_leader_locked()
            self._closed = True
            self._cond.notify_all()
        if commit_seq is not None and self._on_commit is not None:
            self._on_commit(commit_seq)
        try:
            os.close(self._fd)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Replay-side loading (free functions: the replaying ApiServer reads the
# directory BEFORE constructing its own WriteAheadLog handle)
# ---------------------------------------------------------------------------

def load_snapshot(wal_dir: str) -> Tuple[Optional[dict], int]:
    """(newest parsable snapshot payload or None, base segment to
    replay from).  A torn snapshot (crash mid-write leaves only the
    .tmp; a corrupt committed file should be impossible but is handled)
    falls back to the previous snapshot — segments are only pruned
    AFTER the newer snapshot committed, so the older one still has its
    full record suffix on disk."""
    if not os.path.isdir(wal_dir):
        return None, 1
    for idx in reversed([i for i in _snapshots(wal_dir)]):
        path = os.path.join(wal_dir, _snapshot_name(idx))
        try:
            with open(path) as f:
                return json.load(f), idx
        except (OSError, ValueError):
            continue
    segs = _segments(wal_dir)
    return None, (segs[0] if segs else 1)


def _segments(wal_dir: str) -> List[int]:
    return sorted(i for i in (
        _parse_index(n, SEGMENT_PREFIX, ".log")
        for n in os.listdir(wal_dir)) if i is not None)


def _snapshots(wal_dir: str) -> List[int]:
    return sorted(i for i in (
        _parse_index(n, SNAPSHOT_PREFIX, ".json")
        for n in os.listdir(wal_dir)) if i is not None)


def _parse_record(line: bytes) -> dict:
    """Decode + validate ONE log line — the single record-validity
    predicate shared by replay and the respawn-time torn-tail
    truncation.  The two must agree byte-for-byte: if truncation kept
    a line replay drops, the respawned log would append after it and
    weld it into mid-log corruption; if it dropped a line replay
    accepts, an acknowledged write would vanish.  Raises ValueError on
    anything replay refuses."""
    record = json.loads(line)
    if not isinstance(record, dict) or "rv" not in record:
        raise ValueError("not a WAL record")
    return record


def truncate_torn_tail(path: str) -> int:
    """Trim a final segment back to its last intact-record boundary,
    dropping exactly the records :func:`iter_records` legally drops: a
    trailing line with no newline, or — only when the tail's newline is
    intact — a final line whose payload fails to parse (partial page
    flush).  Returns the number of records dropped.  Damage anywhere
    else is left in place for replay to refuse loudly — truncating it
    here would silently discard acknowledged writes."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return 0
    keep = len(data)
    dropped = 0
    if data and not data.endswith(b"\n"):
        keep = data.rfind(b"\n") + 1
        dropped += 1
    tail_ok = False
    if keep:
        # The (possibly new) final newline-terminated line may itself
        # be a torn payload (partial page flush) — the other legal
        # final-record tear.
        nl = data.rfind(b"\n", 0, keep - 1)
        last = data[nl + 1:keep - 1]
        if last:
            try:
                _parse_record(last)
                tail_ok = True
            except ValueError:
                keep = nl + 1
                dropped += 1
    if not dropped:
        return 0
    # Only the SINGLE final record of a sequential-append crash may
    # legally tear.  Two torn records, or a would-be new tail whose
    # last non-empty line is unparseable (replay skips empty lines but
    # still refuses garbage before them), is corruption iter_records
    # refuses loudly — leave the file untouched (tail included) so it
    # still does, never launder it into a legal-looking single tear.
    if dropped > 1:
        return 0
    if keep and not tail_ok:
        end = keep - 1                   # position of the final newline
        while end > 0 and data[end - 1:end] == b"\n":
            end -= 1
        nl = data.rfind(b"\n", 0, end)
        prev = data[nl + 1:end]
        if prev:
            try:
                _parse_record(prev)
            except ValueError:
                return 0
    with open(path, "rb+") as f:
        f.truncate(keep)
    return dropped


def iter_records(wal_dir: str, base_segment: int,
                 on_torn: Optional[Callable[[str], None]] = None,
                 ) -> Iterator[dict]:
    """Yield every intact record from ``base_segment`` on, in append
    (== revision) order.  The final record of the final segment may be
    torn (dropped, reported via ``on_torn``); anything else raises
    :class:`WalCorruptionError`."""
    if not os.path.isdir(wal_dir):
        return
    segs = [s for s in _segments(wal_dir) if s >= base_segment]
    for pos, seg in enumerate(segs):
        path = os.path.join(wal_dir, _segment_name(seg))
        with open(path, "rb") as f:
            data = f.read()
        lines = data.split(b"\n")
        # A complete file ends with a newline -> final split entry is
        # empty.  A non-empty final entry is a torn tail.
        torn_tail = lines[-1]
        lines = lines[:-1]
        last_segment = pos == len(segs) - 1
        for i, line in enumerate(lines):
            if not line:
                continue
            try:
                record = _parse_record(line)
            except ValueError as exc:
                if last_segment and i == len(lines) - 1 and not torn_tail:
                    # Newline present but the payload itself is torn
                    # (partial page flush): legal final-record tear.
                    if on_torn is not None:
                        on_torn(f"{_segment_name(seg)}: dropped torn "
                                f"final record ({exc})")
                    continue
                raise WalCorruptionError(
                    f"{_segment_name(seg)} line {i + 1}: {exc}") from exc
            yield record
        if torn_tail:
            if not last_segment:
                raise WalCorruptionError(
                    f"{_segment_name(seg)}: mid-log segment ends in a "
                    f"torn record")
            if on_torn is not None:
                on_torn(f"{_segment_name(seg)}: dropped torn final "
                        f"record (no newline)")
