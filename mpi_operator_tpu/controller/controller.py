"""MPIJobController — the level-triggered reconcile loop.

Re-architecture of /root/reference/pkg/controller/mpi_job_controller.go
(:223-1325): workqueue-driven sync of one MPIJob into a headless Service,
ConfigMap (hostfile + discover_hosts.sh), SSH Secret (MPI impls), N worker
Pods, one launcher Job and an optional PodGroup, plus the status/condition
engine, suspend/resume and cleanup.  The controller only ever writes API
objects — pods bootstrap their own process group from injected env (JAX
coordination service over ICI/DCN, or mpirun/SSH for MPI parity), exactly
like the reference never touches the data plane.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from ..api import constants
from ..api.defaults import set_defaults_mpijob
from ..api.types import MPIJob, worker_replicas
from ..api.validation import validate_mpijob
from ..k8s import batch, core
from ..k8s.apiserver import (ApiError, Clientset, is_already_exists,
                             is_conflict, is_not_found)
from ..k8s.informers import InformerFactory
from ..k8s.meta import Clock, deep_copy, get_controller_of
from ..k8s.selectors import match_label_selector, match_labels
from ..k8s.workqueue import (PRIORITY_HIGH, PRIORITY_LOW,
                             ShardedRateLimitingQueue)
from ..telemetry import flight
from ..telemetry.metrics import record_build_info
from ..telemetry.trace import annotation_context, default_tracer, span
from . import builders, metrics as metrics_pkg, status as status_pkg
from .events import Recorder
from .metrics import new_operator_metrics
from .status import (MPI_JOB_EVICT_REASON, MPI_JOB_FAILED_REASON,
                     MPI_JOB_RESUMED_REASON, MPI_JOB_RUNNING_REASON,
                     MPI_JOB_SUCCEEDED_REASON, MPI_JOB_SUSPENDED_REASON,
                     MPI_JOB_CREATED_REASON, get_condition,
                     initialize_replica_statuses, is_finished,
                     update_job_conditions)

logger = logging.getLogger("mpi_operator_tpu.controller")

# Event reasons (mpi_job_controller.go:60-116)
ERR_RESOURCE_EXISTS = "ErrResourceExists"
MESSAGE_RESOURCE_EXISTS = ('Resource "%s" of Kind "%s" already exists and is'
                           ' not managed by MPIJob')
VALIDATION_ERROR = "ValidationError"
EVENT_MESSAGE_LIMIT = 1024

JOB_BACKOFF_LIMIT_EXCEEDED_REASON = "BackoffLimitExceeded"


def truncate_message(message: str) -> str:
    """truncateMessage (:1830-1837)."""
    if len(message) <= EVENT_MESSAGE_LIMIT:
        return message
    return message[:EVENT_MESSAGE_LIMIT - 3] + "..."


def managed_by_external_controller(managed_by: Optional[str]) -> Optional[str]:
    """managedByExternalController (:1839-1844)."""
    if managed_by is not None and managed_by != constants.KUBEFLOW_JOB_CONTROLLER:
        return managed_by
    return None


def is_clean_up_pods(clean_pod_policy: Optional[str]) -> bool:
    """isCleanUpPods (:1765-1770)."""
    return clean_pod_policy in (constants.CLEAN_POD_POLICY_ALL,
                                constants.CLEAN_POD_POLICY_RUNNING)


def is_controlled_by(obj, job: MPIJob) -> bool:
    ref = get_controller_of(obj)
    return ref is not None and ref.uid == job.metadata.uid


class MPIJobController:
    """NewMPIJobController equivalent (:268-462)."""

    def __init__(self, clientset: Clientset,
                 informer_factory: Optional[InformerFactory] = None,
                 pod_group_ctrl=None,
                 recorder=None,
                 clock: Optional[Clock] = None,
                 cluster_domain: str = "",
                 namespace: Optional[str] = None,
                 metrics: Optional[dict] = None,
                 shards: Optional[int] = None,
                 fair_queueing: Optional[bool] = None):
        self.client = clientset
        self.clock = clock or Clock()
        self.cluster_domain = cluster_domain
        self.namespace = namespace
        self.pod_group_ctrl = pod_group_ctrl
        self.metrics = metrics or new_operator_metrics()
        # Hand-rolled metrics dicts (tests, embedders) may predate the
        # telemetry histograms; backfill them so the hot-path
        # instrumentation below never branches.
        metrics_pkg.backfill_telemetry_metrics(self.metrics)
        self.recorder = recorder or Recorder(
            clientset, registry=self.metrics.get("registry"))

        factory = informer_factory or InformerFactory(clientset, namespace)
        self.factory = factory
        self.mpi_job_informer = factory.mpi_jobs()
        self.pod_informer = factory.pods()
        self.service_informer = factory.services()
        self.config_map_informer = factory.config_maps()
        self.secret_informer = factory.secrets()
        self.job_informer = factory.jobs()
        if pod_group_ctrl is not None:
            self.pod_group_informer = pod_group_ctrl.informer(factory)
        else:
            self.pod_group_informer = None

        # Sharded workqueue: keys route by stable namespace/name hash to
        # N independent shards, one sync worker each — no two shards can
        # ever sync the same job concurrently (docs/PERF.md "Sharded
        # control plane").  Priority + fairness dispatch inside each
        # shard keeps 1-pod jobs from starving behind a 10k-pod gang.
        if shards is None:
            shards = int(os.environ.get("MPI_OPERATOR_SHARDS", "4") or 4)
        if fair_queueing is None:
            fair_queueing = os.environ.get(
                "MPI_OPERATOR_FAIR_QUEUE", "1").lower() not in ("0", "false")
        self.queue = ShardedRateLimitingQueue(shards, fair=fair_queueing)
        record_build_info(shards=self.queue.num_shards)
        # First-enqueue wall time per pending key: the causal trace's
        # workqueue-wait segment (emitted at dequeue in _timed_sync).
        # First add wins — the queue dedups pending keys the same way.
        self._enqueue_wall: dict = {}
        # Jobs at or under this worker-pod count enqueue in the
        # high-priority class (served ahead of gangs, round-robin).
        self.small_job_pods = int(os.environ.get(
            "MPI_OPERATOR_SMALL_JOB_PODS", "64") or 64)
        self._workers: list[threading.Thread] = []
        self._stop = threading.Event()
        # Shard-routing invariant: keys currently in flight, key -> shard
        # index.  A key seen in flight on two shards (impossible unless
        # routing breaks) counts into shard_violations.
        self._inflight: dict = {}
        self._inflight_lock = threading.Lock()
        # OrphanPod warnings already emitted, keyed (launcher uid, pod
        # identity): one aggregated event per collision instead of one
        # per sync (the Recorder would otherwise absorb a steady
        # re-emission every reconcile).
        self._orphan_warned: set = set()
        # Foreign-kind sync handlers sharing this controller's sharded
        # queue (serve + train jobs coexist on one fair control plane):
        # keys of the form "<prefix>:<ns>/<name>" dispatch to the
        # registered handler instead of sync_handler.  MPIJob keys never
        # contain ":", so the namespaces cannot collide.
        self._kind_handlers: dict = {}

        # Event handlers (:392-457): MPIJob changes enqueue directly; owned
        # objects route through handle_object.
        self.mpi_job_informer.add_event_handler(
            on_add=self._add_mpi_job,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=lambda obj: None)
        for informer in (self.pod_informer, self.service_informer,
                         self.config_map_informer, self.secret_informer,
                         self.job_informer):
            informer.add_event_handler(
                on_add=self.handle_object,
                on_update=self._handle_object_update,
                on_delete=self.handle_object)
        if self.pod_group_informer is not None:
            self.pod_group_informer.add_event_handler(
                on_add=self.handle_object,
                on_update=self._handle_object_update,
                on_delete=self.handle_object)

    # ------------------------------------------------------------------
    # Queue plumbing
    # ------------------------------------------------------------------
    def _add_mpi_job(self, obj) -> None:
        """addMPIJob (:1236-1242)."""
        self.enqueue(obj)

    def enqueue(self, job) -> None:
        """enqueueMPIJob (:1247-1255).

        Diverges from the reference deliberately: the reference calls
        AddRateLimited here, which counts every watch event as a
        *failure* in the per-item exponential limiter — during an
        apiserver error burst the event storm (status churn, pod
        flapping) inflates the backoff toward its 1000s cap even though
        no sync failed, so recovery after the burst heals is delayed by
        minutes.  Event-driven adds go through the dedup'd sharded
        queue (with hot-key coalescing); only actual sync errors
        (_run_worker) pay the failure backoff."""
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        if len(self._enqueue_wall) > 65536:
            self._enqueue_wall.clear()  # bounded; a lost wait is one span
        self._enqueue_wall.setdefault(key, time.time())
        self.queue.add(key, priority=self._priority_of(job))

    def _priority_of(self, job) -> int:
        """Fairness class by job size: small jobs dispatch ahead of
        gangs, so a 1-pod job's reconcile latency is bounded by one
        in-flight sync rather than every queued gang sync."""
        try:
            pods = worker_replicas(job) or 0
        except (AttributeError, KeyError, TypeError, ValueError):
            # Malformed spec: sync_handler surfaces the real error;
            # classification just needs a lane.
            return PRIORITY_HIGH
        return PRIORITY_HIGH if pods <= self.small_job_pods \
            else PRIORITY_LOW

    def _priority_of_key(self, key: str) -> Optional[int]:
        """Priority for a bare queue key (failure requeues): the queue
        retires an item's priority class once it fully drains, so a
        rate-limited re-add must re-state it or a failing gang would
        re-enter in the high class, ahead of the small jobs the
        fairness layer protects.  None (job gone from the cache) lets
        the queue default apply."""
        prefix, sep, _ = key.partition(":")
        if sep and prefix in self._kind_handlers:
            # Registered foreign kinds (ServeJobs) are small and
            # latency-sensitive; their controllers enqueue HIGH, and a
            # failure requeue must not demote them behind gang syncs.
            return PRIORITY_HIGH
        ns, _, name = key.partition("/")
        job = self.mpi_job_informer.lister.get(ns, name)
        return self._priority_of(job) if job is not None else None

    def handle_object(self, obj) -> None:
        """handleObject (:1262-1312): find the owning MPIJob and enqueue
        it; pods owned by a (launcher) Job hop one level up."""
        ref = get_controller_of(obj)
        if ref is None:
            return
        if ref.api_version == "batch/v1" and ref.kind == "Job":
            job_obj = self.job_informer.lister.get(obj.metadata.namespace,
                                                   ref.name)
            if job_obj is None:
                return
            ref = get_controller_of(job_obj)
            if ref is None:
                return
        if (ref.kind != constants.KIND
                or ref.api_version != constants.GROUP_VERSION):
            return
        mpi_job = self.mpi_job_informer.lister.get(obj.metadata.namespace,
                                                   ref.name)
        if mpi_job is None:
            return
        self.enqueue(mpi_job)

    def _handle_object_update(self, old, new) -> None:
        """handleObjectUpdate (:1314-1324): skip resync no-ops."""
        if (old is not None and new.metadata.resource_version
                == old.metadata.resource_version):
            return
        self.handle_object(new)

    # ------------------------------------------------------------------
    # Run loop
    # ------------------------------------------------------------------
    def run(self, threadiness: Optional[int] = None) -> None:
        """Run (:465-503): start informers, wait for sync, spawn ONE
        sync worker per workqueue shard.  ``threadiness`` (legacy name)
        sets the shard count — the queue reshards before any worker
        starts, so the one-worker-per-shard invariant always holds."""
        if threadiness is not None \
                and threadiness != self.queue.num_shards:
            self.queue.reshard(threadiness)
        self.factory.start_all()
        if not self.factory.wait_for_cache_sync():
            raise RuntimeError("failed to wait for caches to sync")
        for i in range(self.queue.num_shards):
            t = threading.Thread(target=self._run_worker, args=(i,),
                                 daemon=True, name=f"mpijob-shard-{i}")
            t.start()
            self._workers.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shutdown()
        for t in self._workers:
            t.join(timeout=2)
        self.factory.stop_all()

    def _run_worker(self, shard: int = 0) -> None:
        """runWorker/processNextWorkItem (:505-561), bound to one queue
        shard.  Per-shard sync counters plus the in-flight map prove
        the routing invariant: the same job never syncs concurrently on
        two shards."""
        q = self.queue.shards[shard]
        label = str(shard)
        depth = self.metrics.get("workqueue_depth")
        shard_syncs = self.metrics.get("shard_syncs")
        violations = self.metrics.get("shard_violations")
        while not self._stop.is_set():
            key, shutdown = q.get(timeout=0.2)
            if shutdown:
                return
            if key is None:
                continue
            if depth is not None:
                depth.labels(label).observe(len(q))
            owner = self.queue.shard_for(key)
            with self._inflight_lock:
                other = self._inflight.get(key)
                self._inflight[key] = shard
            if (other is not None or owner != shard) \
                    and violations is not None:
                violations.inc()
                flight.record("controller", "shard_violation", job=key,
                              shard=shard, owner=owner, also_on=other)
            try:
                self._timed_sync(key)
                q.forget(key)
            except Exception as exc:  # requeue with backoff
                if is_conflict(exc):
                    # Expected under informer staleness: the next sync on a
                    # fresh cache converges (ref :1169-1188 rationale).
                    logger.debug("conflict syncing %s, requeueing", key)
                else:
                    logger.warning("error syncing %s: %s", key, exc)
                    flight.record("controller", "sync_error", job=key,
                                  error=f"{type(exc).__name__}: {exc}")
                    if not isinstance(exc, ApiError):
                        # A non-API failure is the panic analogue: a
                        # controller bug, not cluster weather.  Black-box
                        # it (once per exception type per process — a
                        # crash-looping sync must not fill the disk).
                        ns, _, name = key.partition("/")
                        flight.dump_bundle(
                            f"sync-panic-{type(exc).__name__}",
                            registry=self.metrics.get("registry"),
                            clientset=self.client, namespace=ns,
                            job_name=name,
                            once_key=f"sync-panic-{type(exc).__name__}")
                q.add_rate_limited(key, priority=self._priority_of_key(key))
            finally:
                with self._inflight_lock:
                    if self._inflight.get(key) == shard:
                        self._inflight.pop(key, None)
                q.done(key)
                if shard_syncs is not None:
                    shard_syncs.labels(label).inc()

    def register_kind_handler(self, prefix: str, handler) -> None:
        """Let another controller (e.g. ServeJobController) ride this
        controller's sharded workqueue: its keys enqueue as
        "<prefix>:<ns>/<name>" and sync through `handler`."""
        if ":" in prefix or "/" in prefix:
            raise ValueError(f"invalid kind prefix {prefix!r}")
        self._kind_handlers[prefix] = handler

    def _timed_sync(self, key: str) -> None:
        """sync_handler wrapped in the reconcile-latency histogram and a
        trace span (errors land on the span before the requeue path).
        Prefixed keys dispatch to their registered foreign-kind handler
        (register_kind_handler).

        Causal tracing: the dequeue closes the workqueue-wait interval
        opened in enqueue(); both the ``queue_wait`` span and the
        ``reconcile`` span parent EXPLICITLY to the job's carried
        context (the watch-event → workqueue hop severs thread-local
        parenting — docs/OBSERVABILITY.md "Causal tracing")."""
        hist = self.metrics.get("reconcile_seconds")
        handler = self.sync_handler
        enqueued = self._enqueue_wall.pop(key, None)
        ctx = None
        prefix, sep, rest = key.partition(":")
        if sep and prefix in self._kind_handlers:
            handler, key = self._kind_handlers[prefix], rest
        else:
            ns, _, name = key.partition("/")
            cached = self.mpi_job_informer.lister.get(ns, name)
            if cached is not None:
                ctx = annotation_context(cached)
        if ctx is not None and enqueued is not None:
            now = time.time()
            default_tracer().emit("queue_wait", ts=enqueued,
                                  dur=now - enqueued, ctx=ctx, job=key)
        with span("reconcile", ctx=ctx, job=key):
            if hist is not None:
                with hist.time():
                    handler(key)
            else:
                handler(key)

    # ------------------------------------------------------------------
    # The sync
    # ------------------------------------------------------------------
    def sync_handler(self, key: str) -> None:
        """syncHandler (:567-741)."""
        namespace, _, name = key.partition("/")
        shared = self.mpi_job_informer.lister.get(namespace, name)
        if shared is None:
            logger.debug("MPIJob has been deleted: %s", key)
            # Drop the job's info series with it: a departed job must
            # disappear from the next scrape, not linger at 1 forever
            # (stale-series leak; the obsplane TSDB would retain the
            # ghost and staleness-bound alerts would still see it).
            from .builders import LAUNCHER_SUFFIX
            self.metrics["job_info"].remove(
                f"{name}{LAUNCHER_SUFFIX}", namespace)
            return
        # NEVER modify informer cache objects (:591-594).
        mpi_job = deep_copy(shared)
        set_defaults_mpijob(mpi_job)
        # Snapshot BEFORE any mutation: the end-of-sync persistence guard
        # must see every condition set during this sync (the reference
        # diffs against the pristine lister object, :1196-1199).
        pristine_status = deep_copy(mpi_job.status)

        manager = managed_by_external_controller(
            mpi_job.spec.run_policy.managed_by)
        if manager is not None:
            logger.debug("Skipping MPIJob managed by %s", manager)
            return

        if mpi_job.metadata.deletion_timestamp is not None:
            return

        errs = validate_mpijob(mpi_job)
        if errs:
            msg = truncate_message(
                "Found validation errors: " + "; ".join(map(str, errs)))
            self.recorder.event(mpi_job, core.EVENT_TYPE_WARNING,
                                VALIDATION_ERROR, msg)
            return  # do not requeue

        if not mpi_job.status.conditions:
            msg = (f"MPIJob {namespace}/{name} is created.")
            update_job_conditions(mpi_job, constants.JOB_CREATED,
                                  core.CONDITION_TRUE,
                                  MPI_JOB_CREATED_REASON, msg, self.clock)
            self.recorder.event(mpi_job, core.EVENT_TYPE_NORMAL,
                                "MPIJobCreated", msg)
            self.metrics["jobs_created"].inc()

        # Terminal + CompletionTime set -> clean up per policy (:625-633).
        if is_finished(mpi_job.status) and mpi_job.status.completion_time is not None:
            if is_clean_up_pods(mpi_job.spec.run_policy.clean_pod_policy):
                self._clean_up_worker_pods(mpi_job)
                self._update_status(mpi_job)
            return

        # Queue-gated admission (sched/, docs/SCHEDULING.md): a job
        # naming a LocalQueue creates NOTHING — no pods, no launcher,
        # no Service — until the gang scheduler admits it (all-or-
        # nothing placement; never a partial gang).  Eviction flips the
        # gate shut again, so a preempted gang's pods are not recreated
        # behind the scheduler's back.  Jobs without the queue label
        # are untouched by any of this.
        if self._admission_gated(mpi_job):
            from .status import MPI_JOB_QUEUED_REASON
            msg = (f"MPIJob {namespace}/{name} is queued: waiting for"
                   f" gang admission")
            if update_job_conditions(mpi_job, constants.JOB_QUEUED,
                                     core.CONDITION_TRUE,
                                     MPI_JOB_QUEUED_REASON, msg,
                                     self.clock):
                self.recorder.event(mpi_job, core.EVENT_TYPE_NORMAL,
                                    "MPIJobQueued", msg)
            self._update_status(mpi_job)
            return

        if mpi_job.status.start_time is None and not self._suspended(mpi_job):
            mpi_job.status.start_time = self.clock.now()

        launcher = self._get_launcher_job(mpi_job)

        workers: list = []
        done = launcher is not None and batch.is_job_finished(launcher)
        if not done:
            self._get_or_create_service(mpi_job, builders.new_job_service(mpi_job))
            config = self._get_or_create_config_map(mpi_job)
            if config is None:
                raise RuntimeError("getting or creating ConfigMap")
            if builders.uses_ssh(mpi_job):
                self._get_or_create_ssh_auth_secret(mpi_job)

            if not self._suspended(mpi_job):
                if self.pod_group_ctrl is not None:
                    pod_group = self._get_or_create_pod_group(mpi_job)
                    if pod_group is None:
                        raise RuntimeError("getting or creating PodGroup")
                    self._sync_pod_group_feedback(mpi_job, pod_group)
                self._maybe_gang_restart(mpi_job)
                workers = self._get_or_create_workers(mpi_job)
            if launcher is None:
                at_startup = (mpi_job.spec.launcher_creation_policy
                              == constants.LAUNCHER_CREATION_AT_STARTUP)
                if at_startup or self._count_ready_workers(workers) == len(workers):
                    try:
                        launcher = self._create_or_adopt(
                            "Job",
                            lambda: self.client.jobs(namespace).create(
                                builders.new_launcher_job(
                                    mpi_job, self.pod_group_ctrl,
                                    self.recorder, self.cluster_domain)),
                            lambda: self.client.jobs(namespace).get(
                                builders.launcher_name(mpi_job)))
                        if not is_controlled_by(launcher, mpi_job):
                            raise self._resource_exists_error(
                                mpi_job, launcher.metadata.name, "Job")
                    except Exception as exc:
                        self.recorder.eventf(
                            mpi_job, core.EVENT_TYPE_WARNING,
                            MPI_JOB_FAILED_REASON,
                            "launcher pod created failed: %s", exc)
                        raise
                else:
                    logger.debug("Waiting for workers %s to start.", key)

        # Suspend/resume alignment of the launcher Job (:690-724).
        if launcher is not None:
            if not self._suspended(mpi_job) and bool(launcher.spec.suspend):
                launcher_copy = deep_copy(launcher)
                # Clear StartTime via the status subresource first: a Job
                # template is immutable once StartTime is set (:693-703).
                if launcher_copy.status.start_time is not None:
                    launcher_copy.status.start_time = None
                    launcher_copy = self.client.jobs(namespace).update_status(
                        launcher_copy)
                desired = builders.new_launcher_pod_template(
                    mpi_job, self.pod_group_ctrl, self.recorder,
                    self.cluster_domain)
                builders.sync_launcher_scheduling_directives(launcher_copy,
                                                             desired)
                launcher_copy.spec.suspend = False
                launcher = self.client.jobs(namespace).update(launcher_copy)
            elif self._suspended(mpi_job) and not bool(launcher.spec.suspend):
                launcher_copy = deep_copy(launcher)
                launcher_copy.spec.suspend = True
                launcher = self.client.jobs(namespace).update(launcher_copy)

        if self._suspended(mpi_job):
            self._clean_up_worker_pods(mpi_job)

        self._update_mpi_job_status(mpi_job, launcher, workers,
                                    old_status=pristine_status)

    # ------------------------------------------------------------------
    # get-or-create helpers
    # ------------------------------------------------------------------
    def _suspended(self, job: MPIJob) -> bool:
        return bool(job.spec.run_policy.suspend)

    def _admission_gated(self, job: MPIJob) -> bool:
        """True when the job is queue-managed (QUEUE_NAME_LABEL) and
        the gang scheduler has not (or no longer) admitted it."""
        from ..sched.api import job_queue_name
        if not job_queue_name(job):
            return False
        cond = get_condition(job.status, constants.JOB_ADMITTED)
        return cond is None or cond.status != core.CONDITION_TRUE

    def _create_or_adopt(self, kind: str, create_fn, get_fn):
        """Create an owned object, adopting the live one on
        AlreadyExists instead of failing the sync.  This is the
        controller-restart recovery contract (docs/RESILIENCE.md): a
        respawned controller's informer caches may lag the objects its
        previous incarnation just wrote, and the level-triggered sync
        must converge on the apiserver's truth — never create a
        duplicate, never error-loop on its own prior work.  The caller
        still ownership-checks the returned object (a foreign
        same-named object stays a hard ErrResourceExists)."""
        try:
            return create_fn()
        except Exception as exc:
            if not is_already_exists(exc):
                raise
            live = get_fn()
            adoptions = self.metrics.get("restart_adoptions")
            if adoptions is not None:
                adoptions.inc()
            meta = getattr(live, "metadata", None)
            flight.record("controller", "adopted_existing", kind=kind,
                          name=getattr(meta, "name", ""))
            return live

    def _resource_exists_error(self, job: MPIJob, name: str, kind: str):
        msg = MESSAGE_RESOURCE_EXISTS % (name, kind)
        self.recorder.event(job, core.EVENT_TYPE_WARNING,
                            ERR_RESOURCE_EXISTS, msg)
        return RuntimeError(msg)

    def _get_launcher_job(self, job: MPIJob):
        """getLauncherJob (:758-779)."""
        launcher = self.job_informer.lister.get(
            job.metadata.namespace, builders.launcher_name(job))
        if launcher is None:
            return None
        if not is_controlled_by(launcher, job):
            raise self._resource_exists_error(job, launcher.metadata.name,
                                              "Job")
        return launcher

    def _get_or_create_service(self, job: MPIJob, new_svc):
        """getOrCreateService (:913-936)."""
        svc = self.service_informer.lister.get(job.metadata.namespace,
                                               new_svc.metadata.name)
        if svc is None:
            svc = self._create_or_adopt(
                "Service",
                lambda: self.client.services(
                    job.metadata.namespace).create(new_svc),
                lambda: self.client.services(
                    job.metadata.namespace).get(new_svc.metadata.name))
        if not is_controlled_by(svc, job):
            raise self._resource_exists_error(job, svc.metadata.name,
                                              "Service")
        if (svc.spec.selector != new_svc.spec.selector
                or svc.spec.publish_not_ready_addresses
                != new_svc.spec.publish_not_ready_addresses):
            svc = deep_copy(svc)
            svc.spec.selector = new_svc.spec.selector
            svc.spec.publish_not_ready_addresses = \
                new_svc.spec.publish_not_ready_addresses
            return self.client.services(job.metadata.namespace).update(svc)
        return svc

    def _worker_pods(self, job: MPIJob) -> list:
        """Worker pods of this job, served from the pod informer's
        owner-uid index (hash lookup) instead of a namespace scan; the
        selector filter keeps out other owned pod flavors (e.g.
        launcher-as-worker naming collisions).  Returned objects are
        SHARED cache snapshots — never mutate."""
        selector = builders.worker_selector(job.metadata.name)
        return [p for p in self.pod_informer.lister.by_owner(
                    job.metadata.uid)
                if match_labels(selector, p.metadata.labels)]

    def _get_running_worker_pods(self, job: MPIJob) -> list:
        """getRunningWorkerPods (:840-858)."""
        return [p for p in self._worker_pods(job)
                if p.status.phase == core.POD_RUNNING]

    def _get_or_create_config_map(self, job: MPIJob):
        """getOrCreateConfigMap (:875-911).  The hostfile covers the
        EFFECTIVE worker count (elastic resize), and discover_hosts.sh
        regenerates from running pods — the in-pod membership substrate
        (bootstrap/elastic.py) sees a resize as hosts appearing or
        leaving this script."""
        new_cm = builders.new_config_map(job, self._effective_workers(job),
                                         self.cluster_domain)
        running = self._get_running_worker_pods(job)
        builders.update_discover_hosts_in_config_map(new_cm, job, running,
                                                     self.cluster_domain)
        cm = self.config_map_informer.lister.get(
            job.metadata.namespace, job.metadata.name + builders.CONFIG_SUFFIX)
        if cm is None:
            cm = self._create_or_adopt(
                "ConfigMap",
                lambda: self.client.config_maps(
                    job.metadata.namespace).create(new_cm),
                lambda: self.client.config_maps(
                    job.metadata.namespace).get(new_cm.metadata.name))
        if not is_controlled_by(cm, job):
            raise self._resource_exists_error(job, cm.metadata.name,
                                              "ConfigMap")
        if cm.data != new_cm.data:
            cm = deep_copy(cm)
            cm.data = new_cm.data
            return self.client.config_maps(job.metadata.namespace).update(cm)
        return cm

    def _get_or_create_ssh_auth_secret(self, job: MPIJob):
        """getOrCreateSSHAuthSecret (:940-969): recreate only when the key
        *names* drift (key material is preserved across syncs)."""
        secret = self.secret_informer.lister.get(
            job.metadata.namespace,
            job.metadata.name + builders.SSH_AUTH_SECRET_SUFFIX)
        if secret is None:
            built = builders.new_ssh_auth_secret(job)
            secret = self._create_or_adopt(
                "Secret",
                lambda: self.client.secrets(
                    job.metadata.namespace).create(built),
                lambda: self.client.secrets(
                    job.metadata.namespace).get(built.metadata.name))
        if not is_controlled_by(secret, job):
            raise self._resource_exists_error(job, secret.metadata.name,
                                              "Secret")
        new_secret = builders.new_ssh_auth_secret(job)
        if sorted(secret.data.keys()) != sorted(new_secret.data.keys()):
            secret = deep_copy(secret)
            secret.data = new_secret.data
            return self.client.secrets(job.metadata.namespace).update(secret)
        return secret

    def _get_or_create_pod_group(self, job: MPIJob):
        """getOrCreatePodGroups (:782-807)."""
        ctrl = self.pod_group_ctrl
        new_pg = ctrl.new_pod_group(job)
        pg = ctrl.get_pod_group(job.metadata.namespace, new_pg.metadata.name)
        if pg is None:
            return ctrl.create_pod_group(new_pg)
        if not is_controlled_by(pg, job):
            raise self._resource_exists_error(job, pg.metadata.name,
                                              "PodGroup")
        if not ctrl.pg_specs_equal(pg, new_pg):
            return ctrl.update_pod_group(pg, new_pg)
        return pg

    def _sync_pod_group_feedback(self, job: MPIJob, pg) -> None:
        """Close the gang-scheduling loop: PodGroup status (Volcano
        status.phase / scheduler-plugins phase + Unschedulable
        condition) becomes an MPIJob WorkersGated condition and Events,
        so an unsatisfiable gang is visible on the job itself instead
        of only on N Pending pods.  Silent PodGroups (no phase yet — no
        gang scheduler running) change nothing."""
        scheduled, reason, message = \
            self.pod_group_ctrl.pod_group_scheduled(pg)
        if scheduled is None:
            return
        current = get_condition(job.status, constants.JOB_WORKERS_GATED)
        if not scheduled:
            changed = update_job_conditions(
                job, constants.JOB_WORKERS_GATED, core.CONDITION_TRUE,
                reason, message, self.clock)
            if changed:
                self.recorder.eventf(job, core.EVENT_TYPE_NORMAL, reason,
                                     "workers gated by gang scheduler: %s",
                                     message)
        elif current is not None \
                and current.status == core.CONDITION_TRUE:
            update_job_conditions(
                job, constants.JOB_WORKERS_GATED, core.CONDITION_FALSE,
                reason, message, self.clock)
            self.recorder.eventf(job, core.EVENT_TYPE_NORMAL, reason,
                                 "gang satisfied: %s", message)

    def _delete_pod_group(self, job: MPIJob) -> None:
        """deletePodGroups (:810-837)."""
        ctrl = self.pod_group_ctrl
        pg = ctrl.get_pod_group(job.metadata.namespace, job.metadata.name)
        if pg is None:
            return
        if not is_controlled_by(pg, job):
            raise self._resource_exists_error(job, pg.metadata.name,
                                              "PodGroup")
        ctrl.delete_pod_group(job.metadata.namespace, job.metadata.name)

    def _maybe_gang_restart(self, job: MPIJob) -> None:
        """RestartPolicy=ExitCode as slice repair (SURVEY §7 hard part c).

        jax.distributed cannot re-form a group around a single restarted
        member — an in-place container restart leaves the rejoining rank
        wedged in initialize while the rest of the gang is mid-training.
        So with restartPolicy: ExitCode (pods run with Never, making
        failures visible) a RETRYABLE worker failure (exit 128-255:
        signals, preemption) deletes the WHOLE worker gang so the next
        sync recreates it and the group re-forms from the workload's
        checkpoint; a PERMANENT failure (1-127) fails the MPIJob.  The
        reference declares this surface but maps it to Never and stops
        (mpi_job_controller.go:1722-1728); here it is implemented.  Gang
        restarts are bounded by runPolicy.backoffLimit via an annotation
        counter."""
        spec = job.worker_spec
        if spec is None or \
                spec.restart_policy != constants.RESTART_POLICY_EXIT_CODE:
            return
        if is_finished(job.status):
            return  # terminal: no repair, no re-emitted failure events
        pods = self._worker_pods(job)
        failed = [p for p in pods
                  if p.status.phase == core.POD_FAILED
                  and is_controlled_by(p, job)
                  and p.status.reason != "Evicted"]  # evict path owns those
        if not failed:
            return
        # The lister can be stale: a pod this controller already deleted in
        # a previous gang restart may still be cached (watch streams carry
        # no cross-kind ordering), and acting on it would double-count the
        # restart against backoffLimit.  Confirm each failure against the
        # live API (same uid, still Failed) before acting.
        live_failed = []
        for p in failed:
            try:
                live = self.client.pods(p.metadata.namespace).get(
                    p.metadata.name)
            except Exception as exc:
                if is_not_found(exc):
                    continue  # already deleted: handled
                raise
            if live.metadata.uid == p.metadata.uid \
                    and live.status.phase == core.POD_FAILED:
                live_failed.append(live)
        failed = live_failed
        if not failed:
            return

        def exit_code(pod) -> int:
            for cs in pod.status.container_statuses:
                if cs.state is not None and cs.state.terminated is not None:
                    return cs.state.terminated.exit_code
            return 1  # unknown terminal state: treat as permanent

        permanent = [p for p in failed
                     if exit_code(p) < constants.RETRYABLE_EXIT_CODE_MIN]
        if permanent:
            p = permanent[0]
            msg = (f"worker {p.metadata.name} failed permanently with exit"
                   f" code {exit_code(p)} (restartPolicy: ExitCode)")
            update_job_conditions(job, constants.JOB_FAILED,
                                  core.CONDITION_TRUE,
                                  MPI_JOB_FAILED_REASON, msg, self.clock)
            self.recorder.event(job, core.EVENT_TYPE_WARNING,
                                MPI_JOB_FAILED_REASON, msg)
            self._black_box_failure(job, MPI_JOB_FAILED_REASON)
            return

        restarts = int(job.metadata.annotations.get(
            constants.GANG_RESTART_COUNT_ANNOTATION, "0"))
        limit = job.spec.run_policy.backoff_limit
        if limit is not None and restarts >= limit:
            msg = (f"worker gang restarted {restarts} times, "
                   f"backoffLimit {limit} reached")
            update_job_conditions(job, constants.JOB_FAILED,
                                  core.CONDITION_TRUE,
                                  JOB_BACKOFF_LIMIT_EXCEEDED_REASON, msg,
                                  self.clock)
            self.recorder.event(job, core.EVENT_TYPE_WARNING,
                                JOB_BACKOFF_LIMIT_EXCEEDED_REASON, msg)
            self._black_box_failure(job, JOB_BACKOFF_LIMIT_EXCEEDED_REASON)
            return

        msg = (f"worker {failed[0].metadata.name} exited with retryable code"
               f" {exit_code(failed[0])}; restarting the worker gang"
               f" (restart {restarts + 1})")
        self.recorder.event(job, core.EVENT_TYPE_NORMAL, "GangRestart", msg)
        gang_restarts = self.metrics.get("gang_restarts")
        if gang_restarts is not None:
            gang_restarts.inc()
        for pod in pods:
            if is_controlled_by(pod, job):
                try:
                    self.client.pods(pod.metadata.namespace).delete(
                        pod.metadata.name)
                except Exception as exc:
                    if not is_not_found(exc):
                        raise
        # Persist the counter on the stored object (spec path, not status).
        # Conflict-retried: the pods are already gone, so losing this write
        # to a concurrent status update would lose the restart accounting
        # (and with it the backoffLimit bound) while the restart proceeds.
        for _ in range(5):
            stored = self.client.mpi_jobs(job.metadata.namespace).get(
                job.metadata.name)
            stored.metadata.annotations[
                constants.GANG_RESTART_COUNT_ANNOTATION] = str(restarts + 1)
            try:
                updated = self.client.mpi_jobs(
                    job.metadata.namespace).update(stored)
            except Exception as exc:
                if is_conflict(exc):
                    continue
                raise
            # Keep the in-flight copy current so the end-of-sync status
            # write does not hit an optimistic-concurrency conflict.
            job.metadata.annotations = updated.metadata.annotations
            job.metadata.resource_version = updated.metadata.resource_version
            break
        else:
            # Losing the counter would let a crash-looping gang restart
            # past backoffLimit invisibly; surface the failure so the
            # sync requeues rather than proceeding unaccounted.
            raise RuntimeError(
                "persisting gang-restart count: conflicts exhausted")

    def _effective_workers(self, job: MPIJob) -> int:
        """The worker count this sync reconciles to: the spec count,
        overridden by the gang scheduler's elastic-resize contract
        (settled gang-workers / in-flight grow target; during a drain
        the OLD size is held so departing workers keep their flush
        window — sched/elastic.py, docs/SCHEDULING.md "Elastic
        gangs").  Identical to the spec count for every non-elastic
        job."""
        from ..sched.elastic import controller_workers
        return controller_workers(job)

    def _get_or_create_workers(self, job: MPIJob) -> list:
        """getOrCreateWorker (:982-1042)."""
        workers: list = []
        spec = job.worker_spec
        if spec is None:
            return workers
        replicas = self._effective_workers(job)

        # Scale-down: remove pods whose index >= replicas (:998-1014).
        # The label is padded by one under runLauncherAsWorker
        # (builders.worker_replica_index_label), so un-pad before comparing
        # — the reference compares the padded label directly and deletes a
        # still-valid worker; we fix that here.
        pad = 1 if job.spec.run_launcher_as_worker else 0
        pods = self._worker_pods(job)
        if len(pods) > replicas:
            for pod in pods:
                index_str = pod.metadata.labels.get(constants.REPLICA_INDEX_LABEL)
                if index_str is None:
                    continue
                try:
                    index = int(index_str) - pad
                except ValueError:
                    continue
                if index >= replicas:
                    try:
                        self.client.pods(pod.metadata.namespace).delete(
                            pod.metadata.name)
                    except Exception as exc:
                        # Stale informer cache: a prior sync (or the
                        # elastic drain) already deleted it — converged.
                        if not is_not_found(exc):
                            raise

        for i in range(replicas):
            pod = self.pod_informer.lister.get(job.metadata.namespace,
                                               builders.worker_name(job, i))
            if pod is None:
                try:
                    pod = self._create_or_adopt(
                        "Pod",
                        lambda i=i: self.client.pods(
                            job.metadata.namespace).create(
                                builders.new_worker(
                                    job, i, self.pod_group_ctrl,
                                    self.cluster_domain)),
                        lambda i=i: self.client.pods(
                            job.metadata.namespace).get(
                                builders.worker_name(job, i)))
                except Exception as exc:
                    self.recorder.eventf(job, core.EVENT_TYPE_WARNING,
                                         MPI_JOB_FAILED_REASON,
                                         "worker pod created failed: %s", exc)
                    raise
            if not is_controlled_by(pod, job):
                raise self._resource_exists_error(job, pod.metadata.name,
                                                  "Pod")
            workers.append(pod)
        return workers

    def _count_ready_workers(self, workers: list) -> int:
        """countReadyWorkerPods (:860-871)."""
        return sum(1 for p in workers
                   if any(c.type == "Ready" and c.status == core.CONDITION_TRUE
                          for c in p.status.conditions))

    def _delete_worker_pods(self, job: MPIJob) -> None:
        """deleteWorkerPods (:1052-1092).  The deletion range covers
        the LARGEST worker index this job may ever have had (spec,
        settled elastic size, in-flight resize target) — cleanup after
        a grow must reach the grown indices."""
        from ..sched.elastic import max_workers_seen
        spec = job.worker_spec
        if spec is None:
            return
        for i in range(max_workers_seen(job)):
            name = builders.worker_name(job, i)
            pod = self.pod_informer.lister.get(job.metadata.namespace, name)
            if pod is None:
                continue
            if not is_controlled_by(pod, job):
                raise self._resource_exists_error(job, pod.metadata.name,
                                                  "Pod")
            # CleanPodPolicyRunning keeps terminated pods (:1077-1084).
            if (job.spec.run_policy.clean_pod_policy
                    == constants.CLEAN_POD_POLICY_RUNNING
                    and pod.status.phase not in (core.POD_RUNNING,
                                                 core.POD_PENDING)):
                continue
            try:
                self.client.pods(job.metadata.namespace).delete(name)
            except Exception as exc:
                if not is_not_found(exc):
                    raise

    def _clean_up_worker_pods(self, job: MPIJob) -> None:
        """cleanUpWorkerPods (:743-755)."""
        self._delete_worker_pods(job)
        initialize_replica_statuses(job, constants.REPLICA_TYPE_WORKER)
        if self.pod_group_ctrl is not None:
            self._delete_pod_group(job)
        job.status.replica_statuses[constants.REPLICA_TYPE_WORKER].active = 0

    # ------------------------------------------------------------------
    # Status
    # ------------------------------------------------------------------
    def _launcher_pods(self, launcher) -> list:
        """jobPods (:1694-1710): selector-matching pods controlled by the
        launcher Job, strictly by ownership (metav1.IsControlledBy).  An
        orphaned selector-matching pod is NOT adopted — it is excluded and
        a warning event is emitted (once per (launcher, pod), not per
        sync) so the collision is visible without the Recorder absorbing
        a re-emission storm.

        Both lookups are index buckets: owned pods by owner-uid, orphan
        candidates from the (rare) ownerless bucket — the namespace-wide
        scan + per-pod deepcopy the original did every sync is gone."""
        out = self.pod_informer.lister.by_owner(launcher.metadata.uid)
        selector = launcher.spec.selector
        if selector is not None:
            for p in self.pod_informer.lister.ownerless(
                    launcher.metadata.namespace):
                if not match_label_selector(selector, p.metadata.labels):
                    continue
                key = (launcher.metadata.uid, p.metadata.uid
                       or f"{p.metadata.namespace}/{p.metadata.name}")
                if key in self._orphan_warned:
                    continue
                if len(self._orphan_warned) > 4096:
                    self._orphan_warned.clear()  # bounded; re-warn is fine
                self._orphan_warned.add(key)
                self.recorder.event(
                    launcher, core.EVENT_TYPE_WARNING, "OrphanPod",
                    f"pod {p.metadata.namespace}/{p.metadata.name} matches "
                    f"the launcher selector but has no controller owner; "
                    f"not adopting it")
        return out

    def _update_mpi_job_status(self, job: MPIJob, launcher, workers: list,
                               old_status=None) -> None:
        """updateMPIJobStatus (:1094-1200)."""
        if old_status is None:
            old_status = deep_copy(job.status)

        if self._suspended(job):
            if update_job_conditions(job, constants.JOB_SUSPENDED,
                                     core.CONDITION_TRUE,
                                     MPI_JOB_SUSPENDED_REASON,
                                     "MPIJob suspended", self.clock):
                self.recorder.event(job, core.EVENT_TYPE_NORMAL,
                                    "MPIJobSuspended", "MPIJob suspended")
        elif get_condition(job.status, constants.JOB_SUSPENDED) is not None:
            if update_job_conditions(job, constants.JOB_SUSPENDED,
                                     core.CONDITION_FALSE,
                                     MPI_JOB_RESUMED_REASON,
                                     "MPIJob resumed", self.clock):
                self.recorder.event(job, core.EVENT_TYPE_NORMAL,
                                    "MPIJobResumed", "MPIJob resumed")
                job.status.start_time = self.clock.now()

        launcher_pods_cnt = 0
        if launcher is not None:
            launcher_pods = self._launcher_pods(launcher)
            launcher_pods_cnt = sum(
                1 for p in launcher_pods if p.status.phase == core.POD_RUNNING)
            initialize_replica_statuses(job, constants.REPLICA_TYPE_LAUNCHER)
            launcher_status = job.status.replica_statuses[
                constants.REPLICA_TYPE_LAUNCHER]
            launcher_status.failed = launcher.status.failed
            if batch.is_job_succeeded(launcher):
                launcher_status.succeeded = 1
                msg = (f"MPIJob {job.metadata.namespace}/"
                       f"{job.metadata.name} successfully completed.")
                self.recorder.event(job, core.EVENT_TYPE_NORMAL,
                                    MPI_JOB_SUCCEEDED_REASON, msg)
                if job.status.completion_time is None:
                    job.status.completion_time = launcher.status.completion_time
                update_job_conditions(job, constants.JOB_SUCCEEDED,
                                      core.CONDITION_TRUE,
                                      MPI_JOB_SUCCEEDED_REASON, msg,
                                      self.clock)
                self.metrics["jobs_successful"].inc()
            elif batch.job_condition_status(launcher, batch.JOB_FAILED) \
                    == core.CONDITION_TRUE:
                self._update_failed_status(job, launcher, launcher_pods)
            else:
                launcher_status.active = launcher_pods_cnt
            self.metrics["job_info"].with_label_values(
                launcher.metadata.name, job.metadata.namespace).set(1)

        running = 0
        evict = 0
        initialize_replica_statuses(job, constants.REPLICA_TYPE_WORKER)
        worker_status = job.status.replica_statuses[constants.REPLICA_TYPE_WORKER]
        for pod in workers:
            if pod.status.phase == core.POD_FAILED:
                worker_status.failed += 1
                if pod.status.reason == "Evicted":
                    evict += 1
            elif pod.status.phase == core.POD_SUCCEEDED:
                worker_status.succeeded += 1
            elif pod.status.phase == core.POD_RUNNING:
                running += 1
                worker_status.active += 1
        if evict > 0:
            msg = f"{evict}/{len(workers)} workers are evicted"
            update_job_conditions(job, constants.JOB_FAILED,
                                  core.CONDITION_TRUE, MPI_JOB_EVICT_REASON,
                                  msg, self.clock)
            self.recorder.event(job, core.EVENT_TYPE_WARNING,
                                MPI_JOB_EVICT_REASON, msg)
            self._black_box_failure(job, MPI_JOB_EVICT_REASON)

        if self._suspended(job):
            msg = (f"MPIJob {job.metadata.namespace}/{job.metadata.name}"
                   f" is suspended.")
            update_job_conditions(job, constants.JOB_RUNNING,
                                  core.CONDITION_FALSE,
                                  MPI_JOB_SUSPENDED_REASON, msg, self.clock)
        elif is_finished(job.status):
            # Terminal: never re-emit Running=True (:1169-1188); backfill
            # Running=False at completionTime if it was never observed.
            if get_condition(job.status, constants.JOB_RUNNING) is None:
                msg = (f"MPIJob {job.metadata.namespace}/{job.metadata.name}"
                       f" is finished but Running condition was never set.")
                from ..api.types import JobCondition
                when = job.status.completion_time or self.clock.now()
                job.status.conditions.append(JobCondition(
                    type=constants.JOB_RUNNING, status=core.CONDITION_FALSE,
                    reason=MPI_JOB_RUNNING_REASON, message=msg,
                    last_update_time=when, last_transition_time=when))
        elif launcher is not None and launcher_pods_cnt >= 1 \
                and running == len(workers):
            msg = (f"MPIJob {job.metadata.namespace}/{job.metadata.name}"
                   f" is running.")
            first_run = (get_condition(old_status, constants.JOB_RUNNING)
                         is None)
            changed = update_job_conditions(job, constants.JOB_RUNNING,
                                            core.CONDITION_TRUE,
                                            MPI_JOB_RUNNING_REASON, msg,
                                            self.clock)
            self.recorder.eventf(job, core.EVENT_TYPE_NORMAL, "MPIJobRunning",
                                 "MPIJob %s/%s is running",
                                 job.metadata.namespace, job.metadata.name)
            if changed and first_run:
                self._observe_first_step(job)

        if old_status != job.status:
            self._update_status(job)

    def _observe_first_step(self, job: MPIJob) -> None:
        """Time-to-first-step at the control plane's resolution: job
        create → first FULL-gang Running flip (workload-side traces
        refine this with real distributed-init/compile/first-step spans
        when the pod exports them).  One summary span per job lifecycle
        + the ``mpi_operator_trace_ttfs_seconds`` histogram — the soak
        scorecard's ttfs_p99 source (docs/OBSERVABILITY.md)."""
        created = job.metadata.creation_timestamp
        if created is None:
            return
        ttfs = (self.clock.now() - created).total_seconds()
        if ttfs < 0:
            return
        hist = self.metrics.get("trace_ttfs")
        if hist is not None:
            hist.observe(ttfs)
        default_tracer().emit(
            "time_to_first_step", ts=created.timestamp(), dur=ttfs,
            ctx=annotation_context(job),
            job=f"{job.metadata.namespace}/{job.metadata.name}")

    def _update_failed_status(self, job: MPIJob, launcher, launcher_pods) -> None:
        """updateMPIJobFailedStatus (:1202-1233)."""
        failed_cond = None
        for c in launcher.status.conditions:
            if c.type == batch.JOB_FAILED:
                failed_cond = c
                break
        reason = (failed_cond.reason if failed_cond else "") or MPI_JOB_FAILED_REASON
        msg = (failed_cond.message if failed_cond else "") or (
            f"MPIJob {job.metadata.namespace}/{job.metadata.name} has failed")
        if reason == JOB_BACKOFF_LIMIT_EXCEEDED_REASON:
            failed_pods = [p for p in launcher_pods
                           if p.status.phase == core.POD_FAILED]
            last = None
            for p in failed_pods:
                if last is None or (last.metadata.creation_timestamp
                                    and p.metadata.creation_timestamp
                                    and last.metadata.creation_timestamp
                                    < p.metadata.creation_timestamp):
                    last = p
            if last is not None:
                reason += "/" + last.status.reason
                msg += ": " + last.status.message
                msg = truncate_message(msg)
        self.recorder.event(job, core.EVENT_TYPE_WARNING, reason, msg)
        if job.status.completion_time is None:
            job.status.completion_time = self.clock.now()
        update_job_conditions(job, constants.JOB_FAILED, core.CONDITION_TRUE,
                              reason, msg, self.clock)
        self.metrics["jobs_failed"].inc()
        self._black_box_failure(job, reason)

    def _black_box_failure(self, job: MPIJob, reason: str) -> None:
        """A dead gang is exactly when the scattered evidence (events,
        pod phases, chaos faults, worker sidecars) must be frozen into
        one artifact: black-box the failure, once per job uid."""
        flight.record("controller", "job_failed",
                      job=f"{job.metadata.namespace}/{job.metadata.name}",
                      reason=reason)
        flight.dump_bundle(
            f"job-failed-{job.metadata.name}",
            registry=self.metrics.get("registry"),
            clientset=self.client, namespace=job.metadata.namespace,
            job_name=job.metadata.name,
            once_key=f"job-failed-{job.metadata.uid or job.metadata.name}")

    def _update_status(self, job: MPIJob) -> None:
        """doUpdateJobStatus (:1327-1330).  Deliberately does NOT stamp a
        per-sync timestamp: a finished job must converge to a no-op write
        or the MODIFIED watch event would re-enqueue it forever.

        No-op writes are suppressed CLIENT-side: the desired status is
        diffed against the informer-cached snapshot and an unchanged
        status skips the UPDATE call entirely (the apiserver would
        absorb it, but the round-trip, action log and fault-injection
        surface are not free at N-hundred-jobs scale)."""
        cached = self.mpi_job_informer.lister.get(job.metadata.namespace,
                                                  job.metadata.name)
        if cached is not None and cached.status == job.status:
            suppressed = self.metrics.get("status_writes_suppressed")
            if suppressed is not None:
                suppressed.inc()
            return
        self.client.mpi_jobs(job.metadata.namespace).update_status(job)
