"""Event recorder — the user-facing audit trail.

Equivalent of client-go tools/record as used by the reference
(recorder creation at mpi_job_controller.go:303-308; FakeRecorder in the
unit fixture).  Events land in the API server as v1 Event objects.
"""

from __future__ import annotations

import threading
import uuid

from ..k8s.apiserver import Clientset
from ..k8s.core import Event, ObjectReference
from ..k8s.meta import ObjectMeta


class Recorder:
    def __init__(self, clientset: Clientset, component: str = "mpi-job-controller"):
        self._cs = clientset
        self.component = component

    def event(self, obj, event_type: str, reason: str, message: str) -> None:
        ev = Event(
            metadata=ObjectMeta(
                name=f"{obj.metadata.name}.{uuid.uuid4().hex[:10]}",
                namespace=obj.metadata.namespace or "default"),
            involved_object=ObjectReference(
                api_version=obj.api_version, kind=obj.kind,
                name=obj.metadata.name, namespace=obj.metadata.namespace,
                uid=obj.metadata.uid),
            type=event_type, reason=reason, message=message)
        try:
            self._cs.events(ev.metadata.namespace).create(ev)
        except Exception:
            pass  # events are best-effort, like the real recorder

    def eventf(self, obj, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)


class FakeRecorder:
    """Captures events for assertions (record.NewFakeRecorder analogue)."""

    def __init__(self):
        self.events: list[str] = []
        self._lock = threading.Lock()

    def event(self, obj, event_type: str, reason: str, message: str) -> None:
        with self._lock:
            self.events.append(f"{event_type} {reason} {message}")

    def eventf(self, obj, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)
