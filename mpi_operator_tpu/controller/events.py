"""Event recorder — the user-facing audit trail.

Equivalent of client-go tools/record as used by the reference
(recorder creation at mpi_job_controller.go:303-308; FakeRecorder in the
unit fixture).  Events land in the API server as v1 Event objects.

Two hardenings over the plain recorder:

- **Aggregation** (client-go EventAggregator semantics): repeats of the
  same ``(involved object, type, reason, message)`` bump ``count`` /
  ``last_timestamp`` on ONE Event instead of minting a fresh
  uuid-named object per call — an event storm (chaos
  ``api_error_burst``, a crash-looping gang) no longer floods the
  apiserver registry.  Retained events are capped per namespace;
  the oldest (by last-seen) are pruned past the cap.
- **Narrowed failure handling**: only apiserver/transport errors are
  best-effort-swallowed (counted in
  ``mpi_operator_events_dropped_total``); programming errors (a
  malformed job object from a sim path) propagate to the caller
  instead of vanishing in a bare ``except``.
"""

from __future__ import annotations

import datetime
import threading
import uuid

from ..k8s.apiserver import (TRANSPORT_ERRORS, ApiError, Clientset,
                             is_conflict, is_not_found)
from ..k8s.core import Event, ObjectReference
from ..k8s.meta import ObjectMeta
from ..telemetry.flight import record as flight_record
from ..telemetry.metrics import Counter

# Transport-shaped failures events are allowed to swallow
# (k8s.apiserver.TRANSPORT_ERRORS, the shared project-wide tuple).

# client-go's default spam cap is 25 events/object burst + token
# refill; here a simple per-namespace retention cap keeps the sim
# registry bounded under storms.
DEFAULT_NAMESPACE_EVENT_CAP = 256


class Recorder:
    def __init__(self, clientset: Clientset,
                 component: str = "mpi-job-controller",
                 registry=None,
                 namespace_event_cap: int = DEFAULT_NAMESPACE_EVENT_CAP):
        self._cs = clientset
        self.component = component
        self.namespace_event_cap = namespace_event_cap
        self._lock = threading.Lock()
        # (ns, kind, name, type, reason, message) -> aggregated Event name
        self._agg: dict = {}
        if registry is not None and hasattr(registry, "counter"):
            self.dropped = registry.counter(
                "mpi_operator_events_dropped_total",
                "Events dropped on apiserver/transport errors")
            self.aggregated = registry.counter(
                "mpi_operator_events_aggregated_total",
                "Event emissions folded into an existing Event's count")
        else:
            self.dropped = Counter(
                "mpi_operator_events_dropped_total",
                "Events dropped on apiserver/transport errors")
            self.aggregated = Counter(
                "mpi_operator_events_aggregated_total",
                "Event emissions folded into an existing Event's count")

    @staticmethod
    def _now() -> datetime.datetime:
        return datetime.datetime.now(datetime.timezone.utc)

    def event(self, obj, event_type: str, reason: str, message: str) -> None:
        namespace = obj.metadata.namespace or "default"
        flight_record("controller", "event", object=f"{namespace}/"
                      f"{obj.metadata.name}", type=event_type,
                      reason=reason, message=message)
        key = (namespace, obj.kind, obj.metadata.name, event_type, reason,
               message)
        with self._lock:
            existing_name = self._agg.get(key)
        now = self._now()
        if existing_name is not None and self._bump(namespace,
                                                    existing_name, now):
            self.aggregated.inc()
            return
        ev = Event(
            metadata=ObjectMeta(
                name=f"{obj.metadata.name}.{uuid.uuid4().hex[:10]}",
                namespace=namespace),
            involved_object=ObjectReference(
                api_version=obj.api_version, kind=obj.kind,
                name=obj.metadata.name, namespace=obj.metadata.namespace,
                uid=obj.metadata.uid or ""),
            type=event_type, reason=reason, message=message,
            count=1, first_timestamp=now, last_timestamp=now)
        try:
            created = self._cs.events(namespace).create(ev)
        except TRANSPORT_ERRORS:
            self.dropped.inc()  # best-effort, like the real recorder
            return
        with self._lock:
            self._agg[key] = created.metadata.name
            # The aggregation index must not outgrow the registry it
            # indexes: evict oldest keys past 8x the namespace cap.
            while len(self._agg) > 8 * self.namespace_event_cap:
                self._agg.pop(next(iter(self._agg)))
        self._prune(namespace)

    def _bump(self, namespace: str, name: str,
              now: datetime.datetime) -> bool:
        """Fold a repeat into the existing Event; returns False when the
        aggregate target is gone (pruned/deleted) so the caller creates
        a fresh one."""
        for _ in range(3):  # conflict-retry: status writers race us
            try:
                stored = self._cs.events(namespace).get(name)
                stored.count += 1
                stored.last_timestamp = now
                self._cs.events(namespace).update(stored)
                return True
            except TRANSPORT_ERRORS as exc:
                if is_not_found(exc):
                    return False
                if is_conflict(exc):
                    continue
                self.dropped.inc()
                return True  # transport failure: drop the repeat quietly
        self.dropped.inc()
        return True

    def _prune(self, namespace: str) -> None:
        """Cap retained events per namespace: oldest-by-last-seen go.

        The cap is enforced on every emit, but the expensive path (list
        every Event + sort) is amortized: an O(1) ``count`` probe gates
        per create, and when it fires the prune sweeps DOWN past the
        cap by a quarter, so the next list is ~cap/4 creates away
        instead of one.  Steady-state event emission then costs one
        count instead of deep-copying the whole event registry per
        event (at 10k jobs in one namespace that list was hundreds of
        milliseconds inside every reconcile that emitted an event)."""
        try:
            count = getattr(self._cs.server, "count", None) \
                if hasattr(self._cs, "server") else None
            if count is not None and count("v1", "Event", namespace) \
                    <= self.namespace_event_cap:
                return
            events = self._cs.events(namespace).list()
            target = self.namespace_event_cap
            if count is not None and len(events) > target:
                target = max(1, target - max(1, target // 4))
            excess = len(events) - target
            if excess <= 0:
                return
            epoch = datetime.datetime(1970, 1, 1,
                                      tzinfo=datetime.timezone.utc)
            events.sort(key=lambda e: (e.last_timestamp
                                       or e.metadata.creation_timestamp
                                       or epoch))
            for ev in events[:excess]:
                try:
                    self._cs.events(namespace).delete(ev.metadata.name)
                except TRANSPORT_ERRORS:
                    pass
        except TRANSPORT_ERRORS:
            pass  # retention is best-effort; next create retries

    def eventf(self, obj, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)


class FakeRecorder:
    """Captures events for assertions (record.NewFakeRecorder analogue)."""

    def __init__(self):
        self.events: list[str] = []
        self._lock = threading.Lock()

    def event(self, obj, event_type: str, reason: str, message: str) -> None:
        with self._lock:
            self.events.append(f"{event_type} {reason} {message}")

    def eventf(self, obj, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)
