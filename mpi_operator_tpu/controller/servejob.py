"""ServeJobController — reconcile a ServeJob into N serving replica pods.

Inference as a first-class operator workload (no reference counterpart;
the reference is training-only): a ServeJob's template is stamped into
``<name>-serve-<i>`` replica pods with

- **readiness gating**: the Available condition tracks Ready replicas,
  so the router only ever discovers replicas whose server actually
  binds (the replica runner flips Ready after the HTTP endpoint is up);
- **rolling replacement**: a template change computes a new template
  hash; stale-hash pods are replaced ONE at a time, and only while every
  other in-range replica is Ready (maxUnavailable=1), so a config roll
  never drops the fleet below N-1 serving replicas;
- **failure replacement**: Failed replicas are deleted and recreated
  (serving replicas always restart — there is no run-to-completion);
- **autoscaler actuation**: the queue-driven autoscaler
  (serving/autoscaler.py) writes ``status.desired_replicas`` through the
  status subresource; this controller clamps it into the spec's
  autoscale bounds and owns every pod create/delete — scaling is a
  status write, never a side channel.

The controller can run standalone (own sharded workqueue + workers) or
ride an MPIJobController's queue via ``mpi_controller=`` (keys enqueue
as ``serve:<ns>/<name>`` through `register_kind_handler`), so serve and
train jobs coexist on one fair, sharded control plane (docs/PERF.md
"Sharded control plane").
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Optional

from ..api import constants
from ..api.defaults import set_defaults_servejob
from ..api.types import ServeJob, serve_effective_replicas
from ..api.validation import validate_servejob
from ..k8s import core
from ..k8s.apiserver import (Clientset, is_already_exists, is_conflict,
                             is_not_found)
from ..k8s.core import Pod, pod_running_and_ready
from ..k8s.informers import InformerFactory
from ..k8s.meta import Clock, ObjectMeta, deep_copy, new_controller_ref, to_dict
from ..k8s.selectors import match_labels
from ..k8s.workqueue import PRIORITY_HIGH, ShardedRateLimitingQueue
from ..telemetry import flight
from .controller import VALIDATION_ERROR, truncate_message
from .events import Recorder
from .status import set_condition, new_condition

logger = logging.getLogger("mpi_operator_tpu.controller.servejob")

SERVE_KEY_PREFIX = "serve"

SERVE_AVAILABLE_REASON = "ReplicasReady"
SERVE_PROGRESSING_REASON = "ReplicaSetProgressing"
SERVE_SCALED_REASON = "FleetScaled"


def serve_template_hash(job: ServeJob) -> str:
    """Stable content hash of the pod template; drives rolling replica
    replacement (a changed template changes the hash, stale-hash pods
    are rolled)."""
    wire = json.dumps(to_dict(job.spec.template), sort_keys=True,
                      default=str)
    return hashlib.blake2b(wire.encode(), digest_size=5).hexdigest()


def replica_name(job: ServeJob, index: int) -> str:
    return f"{job.metadata.name}-serve-{index}"


def serve_selector(job_name: str) -> dict:
    return {constants.JOB_NAME_LABEL: job_name,
            constants.REPLICA_TYPE_LABEL:
                constants.REPLICA_TYPE_SERVE.lower()}


def new_replica_pod(job: ServeJob, index: int, template_hash: str) -> Pod:
    template = job.spec.template
    labels = dict(template.metadata.labels or {})
    labels.update(serve_selector(job.metadata.name))
    labels[constants.REPLICA_INDEX_LABEL] = str(index)
    labels[constants.SERVE_TEMPLATE_HASH_LABEL] = template_hash
    labels[constants.OPERATOR_NAME_LABEL] = constants.OPERATOR_NAME
    return Pod(
        metadata=ObjectMeta(
            name=replica_name(job, index),
            namespace=job.metadata.namespace,
            labels=labels,
            annotations=dict(template.metadata.annotations or {}),
            owner_references=[new_controller_ref(
                job, constants.SERVE_GROUP_VERSION, constants.SERVE_KIND)]),
        spec=deep_copy(template.spec))


class ServeJobController:
    def __init__(self, clientset: Clientset,
                 informer_factory: Optional[InformerFactory] = None,
                 recorder=None, clock: Optional[Clock] = None,
                 namespace: Optional[str] = None,
                 metrics_registry=None,
                 shards: Optional[int] = None,
                 mpi_controller=None):
        self.client = clientset
        self.clock = clock or Clock()
        self.namespace = namespace
        from ..telemetry.metrics import Registry
        self.registry = metrics_registry or Registry()
        self.metrics = {
            "registry": self.registry,
            "syncs": self.registry.counter(
                "mpi_operator_servejob_syncs_total",
                "ServeJob reconcile passes"),
            "replicas_desired": self.registry.gauge(
                "mpi_operator_servejob_replicas_desired",
                "Effective replica target of the last reconcile"
                " (autoscaler-steered, bound-clamped)"),
            "replicas_ready": self.registry.gauge(
                "mpi_operator_servejob_replicas_ready",
                "Ready serving replicas at the last reconcile"),
            "rolled_replicas": self.registry.counter(
                "mpi_operator_servejob_replicas_rolled_total",
                "Stale-template replicas replaced by the rolling"
                " update path"),
        }
        self.recorder = recorder or Recorder(clientset,
                                             registry=self.registry)
        factory = informer_factory or InformerFactory(clientset, namespace)
        self.factory = factory
        self.serve_job_informer = factory.serve_jobs()
        self.pod_informer = factory.pods()

        # Queue: shared (ride the MPIJob controller's sharded fair
        # queue; serve keys carry the "serve:" prefix) or standalone.
        self._mpi_controller = mpi_controller
        if mpi_controller is not None:
            mpi_controller.register_kind_handler(SERVE_KEY_PREFIX,
                                                 self.sync_handler)
            self.queue = mpi_controller.queue
        else:
            if shards is None:
                shards = int(os.environ.get("MPI_OPERATOR_SHARDS", "2")
                             or 2)
            self.queue = ShardedRateLimitingQueue(shards)
        self._workers: list = []
        self._stop = threading.Event()

        self.serve_job_informer.add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=lambda obj: None)
        self.pod_informer.add_event_handler(
            on_add=self._handle_pod,
            on_update=lambda old, new: self._handle_pod(new),
            on_delete=self._handle_pod)

    # -- queue plumbing ----------------------------------------------------
    def _key(self, namespace: str, name: str) -> str:
        return (f"{SERVE_KEY_PREFIX}:{namespace}/{name}"
                if self._mpi_controller is not None
                else f"{namespace}/{name}")

    def enqueue(self, job) -> None:
        self.queue.add(
            self._key(job.metadata.namespace, job.metadata.name),
            priority=PRIORITY_HIGH)

    def _handle_pod(self, pod) -> None:
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind == constants.SERVE_KIND:
                job = self.serve_job_informer.lister.get(
                    pod.metadata.namespace, ref.name)
                if job is not None:
                    self.enqueue(job)
                return

    # -- run loop ----------------------------------------------------------
    def run(self) -> None:
        self.factory.start_all()
        if not self.factory.wait_for_cache_sync():
            raise RuntimeError("failed to wait for caches to sync")
        if self._mpi_controller is not None:
            return  # the MPIJob controller's shard workers drive us
        for i in range(self.queue.num_shards):
            t = threading.Thread(target=self._run_worker, args=(i,),
                                 daemon=True, name=f"servejob-shard-{i}")
            t.start()
            self._workers.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._mpi_controller is None:
            self.queue.shutdown()
        for t in self._workers:
            t.join(timeout=2)
        self.factory.stop_all()

    def _run_worker(self, shard: int) -> None:
        q = self.queue.shards[shard]
        while not self._stop.is_set():
            key, shutdown = q.get(timeout=0.2)
            if shutdown:
                return
            if key is None:
                continue
            try:
                self.sync_handler(key)
                q.forget(key)
            except Exception as exc:
                if is_conflict(exc):
                    logger.debug("conflict syncing %s, requeueing", key)
                else:
                    logger.warning("error syncing ServeJob %s: %s",
                                   key, exc)
                    flight.record("controller", "sync_error", job=key,
                                  error=f"{type(exc).__name__}: {exc}")
                q.add_rate_limited(key)
            finally:
                q.done(key)

    # -- the sync ----------------------------------------------------------
    def _replica_pods(self, job: ServeJob) -> list:
        """Owned serving pods (shared cache snapshots — never mutate),
        from the owner-uid index bucket."""
        selector = serve_selector(job.metadata.name)
        return [p for p in self.pod_informer.lister.by_owner(
                    job.metadata.uid)
                if match_labels(selector, p.metadata.labels)]

    @staticmethod
    def _index_of(pod) -> Optional[int]:
        try:
            return int(pod.metadata.labels.get(
                constants.REPLICA_INDEX_LABEL, ""))
        except ValueError:
            return None

    def sync_handler(self, key: str) -> None:
        namespace, _, name = key.partition("/")
        shared = self.serve_job_informer.lister.get(namespace, name)
        if shared is None:
            logger.debug("ServeJob has been deleted: %s", key)
            return
        self.metrics["syncs"].inc()
        job = deep_copy(shared)
        set_defaults_servejob(job)
        pristine_status = deep_copy(job.status)
        if job.metadata.deletion_timestamp is not None:
            return
        errs = validate_servejob(job)
        if errs:
            self.recorder.event(
                job, core.EVENT_TYPE_WARNING, VALIDATION_ERROR,
                truncate_message("Found validation errors: "
                                 + "; ".join(map(str, errs))))
            return  # do not requeue

        desired = serve_effective_replicas(job)
        template_hash = serve_template_hash(job)
        self.metrics["replicas_desired"].set(desired)

        pods = self._replica_pods(job)
        in_range: dict = {}
        for pod in pods:
            idx = self._index_of(pod)
            if idx is None or idx >= desired:
                # Scale-down (or an unparseable index: not ours to keep).
                self._delete_pod(pod)
                continue
            in_range[idx] = pod

        # Failed replicas restart unconditionally: delete, then the
        # create loop below recreates the index in this same sync (the
        # in-process DELETE is synchronous, so the create gets a fresh
        # uid — which the uid-keyed replica runner relies on to swap
        # servers).
        for idx, pod in list(in_range.items()):
            if pod.status.phase == core.POD_FAILED:
                self._delete_pod(pod)
                self.recorder.eventf(
                    job, core.EVENT_TYPE_NORMAL, "ReplicaRestart",
                    "replica %s failed; recreating", pod.metadata.name)
                del in_range[idx]

        # Rolling replacement, maxUnavailable=1: replace ONE stale-hash
        # pod per sync, and only while every other in-range replica is
        # Ready — a template roll never takes the fleet below N-1.
        stale = sorted(
            idx for idx, pod in in_range.items()
            if pod.metadata.labels.get(constants.SERVE_TEMPLATE_HASH_LABEL)
            != template_hash)
        if stale and len(in_range) == desired:
            victim = stale[0]
            others_ready = all(pod_running_and_ready(pod)
                               for idx, pod in in_range.items()
                               if idx != victim)
            if others_ready:
                self._delete_pod(in_range[victim])
                del in_range[victim]
                self.metrics["rolled_replicas"].inc()
                self.recorder.eventf(
                    job, core.EVENT_TYPE_NORMAL, "ReplicaRollout",
                    "rolling replica %d to template %s", victim,
                    template_hash)

        for idx in range(desired):
            if idx not in in_range:
                try:
                    in_range[idx] = self.client.pods(namespace).create(
                        new_replica_pod(job, idx, template_hash))
                except Exception as exc:
                    if not is_already_exists(exc):
                        raise
                    # Informer staleness: a prior sync's create has not
                    # landed in the cache yet; the watch event re-syncs.
                    continue

        ready = sum(1 for pod in in_range.values()
                    if pod_running_and_ready(pod))
        updated = sum(
            1 for pod in in_range.values()
            if pod.metadata.labels.get(constants.SERVE_TEMPLATE_HASH_LABEL)
            == template_hash)
        self.metrics["replicas_ready"].set(ready)

        job.status.replicas = len(in_range)
        job.status.updated_replicas = updated
        job.status.template_hash = template_hash
        if job.status.ready_replicas != ready and desired > 0:
            self.recorder.eventf(
                job, core.EVENT_TYPE_NORMAL, SERVE_SCALED_REASON,
                "%d/%d replicas ready", ready, desired)
        job.status.ready_replicas = ready
        available = desired > 0 and ready >= desired
        set_condition(job.status, new_condition(
            constants.SERVE_AVAILABLE,
            core.CONDITION_TRUE if available else core.CONDITION_FALSE,
            SERVE_AVAILABLE_REASON,
            f"{ready}/{desired} replicas ready", self.clock))
        progressing = ready < desired or updated < desired \
            or len(in_range) != desired
        set_condition(job.status, new_condition(
            constants.SERVE_PROGRESSING,
            core.CONDITION_TRUE if progressing else core.CONDITION_FALSE,
            SERVE_PROGRESSING_REASON,
            f"{updated}/{desired} replicas at template {template_hash}",
            self.clock))

        if job.status != pristine_status:
            self._update_status(job)

    def _delete_pod(self, pod) -> None:
        try:
            self.client.pods(pod.metadata.namespace).delete(
                pod.metadata.name)
        except Exception as exc:
            if not is_not_found(exc):
                raise

    def _update_status(self, job: ServeJob) -> None:
        """Client-side no-op suppression, like the MPIJob controller's
        _update_status: unchanged status skips the round-trip."""
        cached = self.serve_job_informer.lister.get(
            job.metadata.namespace, job.metadata.name)
        if cached is not None and cached.status == job.status:
            return
        self.client.serve_jobs(job.metadata.namespace).update_status(job)
