"""Gang-scheduling adapters (PodGroupControl).

Parity with /root/reference/pkg/controller/podgroup.go: a
`PodGroupControl` interface with Volcano (scheduling.volcano.sh/v1beta1)
and scheduler-plugins coscheduling (scheduling.x-k8s.io/v1alpha1)
implementations, the priority-ordered minResources math (calPGMinResource,
:337-388), minAvailable (:392-397) and priorityClass resolution
(:403-416).

TPU-native note: on GKE a whole TPU pod-slice is inherently
gang-scheduled by the node pool; PodGroup minMember maps to
hosts-per-slice so multislice jobs over DCN wait for every slice's hosts.
"""

from __future__ import annotations

from typing import Optional

from ..api import constants
from ..api.types import MPIJob, worker_replicas
from ..k8s.apiserver import Clientset, is_not_found
from ..k8s.meta import ObjectMeta, new_controller_ref
from ..k8s.quantity import format_quantity, parse_quantity
from ..k8s.scheduling import (SCHED_PLUGINS_POD_GROUP_LABEL,
                              SchedPluginsPodGroup, SchedPluginsPodGroupSpec,
                              VOLCANO_POD_GROUP_NAME_ANNOTATION,
                              VolcanoPodGroup, VolcanoPodGroupSpec)

GANG_SCHEDULER_VOLCANO = "volcano"
GANG_SCHEDULER_SCHED_PLUGINS_DEFAULT = "scheduler-plugins-scheduler"

VOLCANO_QUEUE_NAME_ANNOTATION = "scheduling.volcano.sh/queue-name"


def calculate_min_available(job: MPIJob) -> int:
    """calculateMinAvailable (:392-397)."""
    policy = job.spec.run_policy.scheduling_policy
    if policy is not None and policy.min_available is not None:
        return policy.min_available
    return worker_replicas(job) + 1


def calculate_priority_class_name(job: MPIJob) -> str:
    """calculatePriorityClassName (:403-416)."""
    policy = job.spec.run_policy.scheduling_policy
    if policy is not None and policy.priority_class:
        return policy.priority_class
    launcher = job.launcher_spec
    if launcher is not None and launcher.template.spec.priority_class_name:
        return launcher.template.spec.priority_class_name
    worker = job.worker_spec
    if worker is not None and worker.template.spec.priority_class_name:
        return worker.template.spec.priority_class_name
    return ""


def _add_resources(min_resources: dict, resources, replicas: int) -> None:
    """addResources (:420-443): requests win; limits fill gaps."""
    if resources is None:
        return
    merged = dict(resources.requests or {})
    for name, lim in (resources.limits or {}).items():
        merged.setdefault(name, lim)
    for name, quantity in merged.items():
        q = parse_quantity(quantity) * replicas
        if name in min_resources:
            q += parse_quantity(min_resources[name])
        min_resources[name] = format_quantity(q)


def cal_pg_min_resource(min_member: Optional[int], job: MPIJob,
                        priority_class_lister=None) -> Optional[dict]:
    """calPGMinResource (:337-388): sum container resources over the first
    minMember replicas in descending priority order; same-priority ties
    treat workers as lower priority."""
    order = []
    for rtype, replica in job.spec.mpi_replica_specs.items():
        priority = 0
        pc_name = replica.template.spec.priority_class_name
        if pc_name and priority_class_lister is not None:
            pc = priority_class_lister(pc_name)
            if pc is not None:
                priority = pc
        order.append({"priority": priority, "type": rtype,
                      "replicas": replica.replicas,
                      "template": replica.template})
    order.sort(key=lambda rp: rp["priority"], reverse=True)

    replicas = order[0]["replicas"] or 0
    if len(order) > 1:
        replicas += order[1]["replicas"] or 0
    if min_member is not None and replicas > min_member:
        if len(order) > 1 and order[0]["priority"] == order[1]["priority"]:
            w_index = next((i for i, rp in enumerate(order)
                            if rp["type"] == constants.REPLICA_TYPE_WORKER),
                           -1)
            if w_index == -1:
                return None
            order[w_index]["replicas"] = min_member - 1
        else:
            order[1]["replicas"] = min_member - 1

    min_resources: dict = {}
    for rp in order:
        if rp["replicas"] is None:
            continue
        for container in rp["template"].spec.containers:
            _add_resources(min_resources, container.resources, rp["replicas"])
    return min_resources


class _BasePodGroupCtrl:
    """Shared get/create/update/delete against the bundled clientset."""

    api_version: str
    scheduler_name: str

    def __init__(self, clientset: Clientset, priority_class_lister=None):
        self.client = clientset
        self.priority_class_lister = priority_class_lister
        self._informer = None

    def _resource_client(self, namespace: str):
        raise NotImplementedError

    def informer(self, factory):
        raise NotImplementedError

    def get_pod_group(self, namespace: str, name: str):
        if self._informer is not None:
            return self._informer.lister.get(namespace, name)
        try:
            return self._resource_client(namespace).get(name)
        except Exception as exc:
            if is_not_found(exc):
                return None
            raise

    def create_pod_group(self, pg):
        return self._resource_client(pg.metadata.namespace).create(pg)

    def update_pod_group(self, old, new):
        from ..k8s.meta import deep_copy
        merged = deep_copy(old)
        merged.spec = deep_copy(new.spec)
        return self._resource_client(old.metadata.namespace).update(merged)

    def delete_pod_group(self, namespace: str, name: str) -> None:
        try:
            self._resource_client(namespace).delete(name)
        except Exception as exc:
            if not is_not_found(exc):
                raise

    def pg_specs_equal(self, a, b) -> bool:
        return a.spec == b.spec

    def calculate_pg_min_resources(self, min_member: Optional[int],
                                   job: MPIJob) -> Optional[dict]:
        """calculatePGMinResources (:176-186, :317-326)."""
        policy = job.spec.run_policy.scheduling_policy
        if policy is not None and policy.min_resources is not None:
            return policy.min_resources
        if min_member == 0:
            return None
        return cal_pg_min_resource(min_member, job,
                                   self.priority_class_lister)

    # Scheduler phases meaning "the gang is placed" (subclass constant).
    _SCHEDULED_PHASES: tuple = ()

    def pod_group_scheduled(self, pg):
        """Consume PodGroup *status* back into the control loop
        (round-3: the reference's gang e2e verifies pods gate on the
        PodGroup; here the controller additionally surfaces that state
        as an MPIJob condition).

        Returns ``(scheduled, reason, message)`` — ``scheduled`` is
        ``None`` when the scheduler has not reported a phase yet (no
        gang scheduler running; don't flap conditions on silence),
        else True/False.
        """
        status = pg.status or {}
        phase = status.get("phase", "")
        if not phase:
            return None, "", ""
        if phase in self._SCHEDULED_PHASES:
            return True, "PodGroupScheduled", f"PodGroup phase {phase}"
        message = f"PodGroup phase {phase}"
        for cond in status.get("conditions", []) or []:
            if cond.get("type") == "Unschedulable":
                message = cond.get("message") or message
                break
        return False, "PodGroupPending", message


class VolcanoCtrl(_BasePodGroupCtrl):
    """VolcanoCtrl (:68-194)."""

    scheduler_name = GANG_SCHEDULER_VOLCANO
    # Volcano phases: Pending -> Inqueue -> Running (Unknown on error);
    # Running means minMember pods are placed.
    _SCHEDULED_PHASES = ("Running", "Completed")

    def _resource_client(self, namespace: str):
        return self.client.volcano_pod_groups(namespace)

    def informer(self, factory):
        self._informer = factory.volcano_pod_groups()
        return self._informer

    def new_pod_group(self, job: MPIJob) -> VolcanoPodGroup:
        """newPodGroup (:109-137): queue from annotation, overridden by
        schedulingPolicy.queue; scheduleTimeoutSeconds not passed."""
        min_member = calculate_min_available(job)
        queue = job.metadata.annotations.get(VOLCANO_QUEUE_NAME_ANNOTATION, "")
        policy = job.spec.run_policy.scheduling_policy
        if policy is not None and policy.queue:
            queue = policy.queue
        return VolcanoPodGroup(
            metadata=ObjectMeta(
                name=job.metadata.name, namespace=job.metadata.namespace,
                owner_references=[new_controller_ref(
                    job, constants.GROUP_VERSION, constants.KIND)]),
            spec=VolcanoPodGroupSpec(
                min_member=min_member,
                queue=queue,
                priority_class_name=calculate_priority_class_name(job),
                min_resources=self.calculate_pg_min_resources(min_member, job)
                or {}))

    def decorate_pod_template(self, template, job_name: str) -> None:
        """decoratePodTemplateSpec (:159-169)."""
        template.spec.scheduler_name = self.scheduler_name
        template.metadata.annotations = dict(template.metadata.annotations)
        template.metadata.annotations[VOLCANO_POD_GROUP_NAME_ANNOTATION] = job_name


class SchedulerPluginsCtrl(_BasePodGroupCtrl):
    """SchedulerPluginsCtrl (:197-334)."""

    # scheduler-plugins phases: Pending/PreScheduling/Scheduling ->
    # Scheduled -> Running -> Finished (Unschedulable on failure).
    _SCHEDULED_PHASES = ("Scheduled", "Running", "Finished")

    def __init__(self, clientset: Clientset, priority_class_lister=None,
                 scheduler_name: str = GANG_SCHEDULER_SCHED_PLUGINS_DEFAULT):
        super().__init__(clientset, priority_class_lister)
        self.scheduler_name = scheduler_name

    def _resource_client(self, namespace: str):
        return self.client.sched_plugins_pod_groups(namespace)

    def informer(self, factory):
        self._informer = factory.sched_plugins_pod_groups()
        return self._informer

    def new_pod_group(self, job: MPIJob) -> SchedPluginsPodGroup:
        """newPodGroup (:241-272): priorityClass/queue not passed;
        scheduleTimeoutSeconds defaults to 0."""
        timeout = 0
        policy = job.spec.run_policy.scheduling_policy
        if policy is not None and policy.schedule_timeout_seconds is not None:
            timeout = policy.schedule_timeout_seconds
        min_member = calculate_min_available(job)
        return SchedPluginsPodGroup(
            metadata=ObjectMeta(
                name=job.metadata.name, namespace=job.metadata.namespace,
                owner_references=[new_controller_ref(
                    job, constants.GROUP_VERSION, constants.KIND)]),
            spec=SchedPluginsPodGroupSpec(
                min_member=min_member,
                min_resources=self.calculate_pg_min_resources(min_member, job)
                or {},
                schedule_timeout_seconds=timeout))

    def decorate_pod_template(self, template, job_name: str) -> None:
        """decoratePodTemplateSpec (:294-303)."""
        template.spec.scheduler_name = self.scheduler_name
        template.metadata.labels = dict(template.metadata.labels)
        template.metadata.labels[SCHED_PLUGINS_POD_GROUP_LABEL] = job_name


def new_pod_group_ctrl(name: str, clientset: Clientset,
                       priority_class_lister=None,
                       scheduler_name: Optional[str] = None):
    """Factory mirroring the server's gang-scheduler selection
    (mpi_job_controller.go:319-327): 'volcano' or any other non-empty name
    selects scheduler-plugins with that scheduler name."""
    if not name:
        return None
    if name == GANG_SCHEDULER_VOLCANO:
        return VolcanoCtrl(clientset, priority_class_lister)
    return SchedulerPluginsCtrl(clientset, priority_class_lister,
                                scheduler_name or name)
