"""Job condition state machine.

Parity with /root/reference/pkg/controller/mpi_job_controller_status.go:
Created -> Running -> {Succeeded, Failed}, plus Suspended and Restarting;
transition-time preservation on unchanged status; Running/Restarting
mutual exclusion; Running forced False on terminal conditions
(filterOutCondition, :122-144).
"""

from __future__ import annotations

from typing import Optional

from ..api import constants
from ..api.types import JobCondition, JobStatus, MPIJob, ReplicaStatus
from ..k8s.core import CONDITION_FALSE, CONDITION_TRUE
from ..k8s.meta import Clock

# Event/condition reasons (mpi_job_controller_status.go:24-39)
MPI_JOB_CREATED_REASON = "MPIJobCreated"
MPI_JOB_SUCCEEDED_REASON = "MPIJobSucceeded"
MPI_JOB_RUNNING_REASON = "MPIJobRunning"
MPI_JOB_SUSPENDED_REASON = "MPIJobSuspended"
MPI_JOB_RESUMED_REASON = "MPIJobResumed"
MPI_JOB_FAILED_REASON = "MPIJobFailed"
MPI_JOB_EVICT_REASON = "MPIJobEvicted"

# Gang-scheduler admission reasons (sched/, docs/SCHEDULING.md).
MPI_JOB_QUEUED_REASON = "MPIJobQueued"
MPI_JOB_ADMITTED_REASON = "MPIJobAdmitted"
MPI_JOB_PREEMPTED_REASON = "MPIJobPreempted"
MPI_JOB_SPOT_RECLAIMED_REASON = "MPIJobSpotReclaimed"


def initialize_replica_statuses(job: MPIJob, rtype: str) -> None:
    """initializeMPIJobStatuses (:42-48)."""
    job.status.replica_statuses[rtype] = ReplicaStatus()


def new_condition(ctype: str, status: str, reason: str, message: str,
                  clock: Clock) -> JobCondition:
    now = clock.now()
    return JobCondition(type=ctype, status=status, reason=reason,
                        message=message, last_update_time=now,
                        last_transition_time=now)


def get_condition(status: JobStatus, ctype: str) -> Optional[JobCondition]:
    for cond in status.conditions:
        if cond.type == ctype:
            return cond
    return None


def has_condition(status: JobStatus, ctype: str) -> bool:
    return any(c.type == ctype and c.status == CONDITION_TRUE
               for c in status.conditions)


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, constants.JOB_SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, constants.JOB_FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def update_job_conditions(job: MPIJob, ctype: str, status: str, reason: str,
                          message: str, clock: Clock) -> bool:
    """updateMPIJobConditions (:51-54). Returns True if anything changed."""
    return set_condition(job.status, new_condition(ctype, status, reason,
                                                   message, clock))


def set_condition(status: JobStatus, condition: JobCondition) -> bool:
    """setCondition (:99-119)."""
    current = get_condition(status, condition.type)
    # Do nothing if the condition doesn't change.
    if (current is not None and current.status == condition.status
            and current.reason == condition.reason):
        return False
    # Preserve lastTransitionTime when only reason/message change.
    if current is not None and current.status == condition.status:
        condition.last_transition_time = current.last_transition_time
    status.conditions = filter_out_condition(status.conditions, condition.type)
    status.conditions.append(condition)
    return True


def filter_out_condition(conditions: list, ctype: str) -> list:
    """filterOutCondition (:122-144): drop same-type conditions; Running and
    Restarting are mutually exclusive; terminal conditions force Running
    (and stale Failed) to False."""
    out = []
    for c in conditions:
        if ctype == constants.JOB_RESTARTING and c.type == constants.JOB_RUNNING:
            continue
        if ctype == constants.JOB_RUNNING and c.type == constants.JOB_RESTARTING:
            continue
        if c.type == ctype:
            continue
        if (ctype in (constants.JOB_FAILED, constants.JOB_SUCCEEDED)
                and c.type in (constants.JOB_RUNNING, constants.JOB_FAILED)):
            c.status = CONDITION_FALSE
        out.append(c)
    return out
