"""Operator metric surface — a shim over the shared telemetry registry.

Parity with the reference's metric surface
(mpi_job_controller.go:125-141, cmd/mpi-operator/main.go:29-40,
README.md:227-234): jobs created/successful/failed counters,
mpi_operator_job_info gauge vector, mpi_operator_is_leader gauge, served
in Prometheus text exposition format.  The metric classes themselves now
live in :mod:`mpi_operator_tpu.telemetry.metrics` (with Histogram and
labeled vector variants added for the rest of the stack); the names and
the ``new_operator_metrics()`` dict shape are unchanged.  All value
reads go through the locked accessors — the original shim read
``_value`` unlocked in ``expose()``.
"""

from __future__ import annotations

from ..telemetry.metrics import (Counter, Gauge, GaugeVec,  # noqa: F401
                                 Histogram, HistogramVec, Registry)

# Workqueue depth histogram buckets: the queue is small-integer valued.
_DEPTH_BUCKETS = (0, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000)


def new_operator_metrics(registry: Registry | None = None):
    """The reference's metric set (mpi_job_controller.go:125-141 +
    main.go:29-40), extended with the reconcile-latency and
    workqueue-depth histograms the telemetry subsystem wires through
    the controller hot path."""
    registry = registry or Registry()
    metrics = {
        "registry": registry,
        "jobs_created": Counter("mpi_operator_jobs_created_total",
                                "Counts number of MPI jobs created", registry),
        "jobs_successful": Counter("mpi_operator_jobs_successful_total",
                                   "Counts number of MPI jobs successful",
                                   registry),
        "jobs_failed": Counter("mpi_operator_jobs_failed_total",
                               "Counts number of MPI jobs failed", registry),
        "job_info": GaugeVec("mpi_operator_job_info",
                             "Information about MPIJob",
                             ["launcher", "namespace"], registry),
        "is_leader": Gauge("mpi_operator_is_leader",
                           "Is this client the leader of this mpi-operator"
                           " client set?", registry),
    }
    backfill_telemetry_metrics(metrics)
    return metrics


def backfill_telemetry_metrics(metrics: dict) -> None:
    """Ensure a metrics dict carries the telemetry entries the
    controller hot path observes.  Hand-rolled dicts (tests, embedders)
    may predate them; get-or-create on the dict's registry keeps the
    definitions here as the single source of truth."""
    registry = metrics.get("registry")
    if registry is None or not hasattr(registry, "histogram"):
        return
    metrics.setdefault("reconcile_seconds", registry.histogram(
        "mpi_operator_reconcile_seconds",
        "MPIJob reconcile (sync_handler) latency"))
    metrics.setdefault("workqueue_depth", registry.histogram_vec(
        "mpi_operator_workqueue_depth",
        "Workqueue depth observed at each dequeue, per shard",
        ["shard"], buckets=_DEPTH_BUCKETS))
    metrics.setdefault("shard_syncs", registry.counter_vec(
        "mpi_operator_shard_sync_total",
        "Reconciles executed per workqueue shard",
        ["shard"]))
    metrics.setdefault("shard_violations", registry.counter(
        "mpi_operator_shard_cross_sync_violations_total",
        "Shard-routing invariant violations: a key observed in flight"
        " on two shards, or dequeued on a shard that does not own it"
        " (must stay 0)"))
    metrics.setdefault("gang_restarts", registry.counter(
        "mpi_operator_gang_restarts_total",
        "Worker gang restarts triggered by restartPolicy ExitCode"))
    metrics.setdefault("status_writes_suppressed", registry.counter(
        "mpi_operator_status_writes_suppressed_total",
        "MPIJob status UPDATEs skipped because the desired status"
        " matched the informer-cached snapshot"))
    metrics.setdefault("trace_ttfs", registry.histogram(
        "mpi_operator_trace_ttfs_seconds",
        "Time to first step: MPIJob create to the first full-gang"
        " Running flip (the causal trace's bootstrap-path total;"
        " docs/OBSERVABILITY.md \"Causal tracing & critical path\")"))
    metrics.setdefault("restart_adoptions", registry.counter(
        "mpi_operator_restart_adoptions_total",
        "Owned objects adopted on AlreadyExists instead of created"
        " (controller-restart recovery: informer caches lagging the"
        " previous incarnation's writes)"))
