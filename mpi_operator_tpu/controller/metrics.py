"""Prometheus-style metrics registry (no external deps).

Parity with the reference's metric surface
(mpi_job_controller.go:125-141, cmd/mpi-operator/main.go:29-40,
README.md:227-234): jobs created/successful/failed counters,
mpi_operator_job_info gauge vector, mpi_operator_is_leader gauge, served
in Prometheus text exposition format.
"""

from __future__ import annotations

import threading


class Counter:
    def __init__(self, name: str, help_text: str, registry: "Registry"):
        self.name = name
        self.help = help_text
        self._value = 0.0
        self._lock = threading.Lock()
        registry._register(self)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} counter\n"
                f"{self.name} {self._value}\n")


class Gauge(Counter):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def expose(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} gauge\n"
                f"{self.name} {self._value}\n")


class GaugeVec:
    def __init__(self, name: str, help_text: str, label_names: list,
                 registry: "Registry"):
        self.name = name
        self.help = help_text
        self.label_names = label_names
        self._values: dict = {}
        self._lock = threading.Lock()
        registry._register(self)

    def with_label_values(self, *values) -> "GaugeVec._Child":
        return GaugeVec._Child(self, tuple(values))

    class _Child:
        def __init__(self, parent, key):
            self._parent = parent
            self._key = key

        def set(self, value: float) -> None:
            with self._parent._lock:
                self._parent._values[self._key] = value

    def get(self, *values) -> float:
        with self._lock:
            return self._values.get(tuple(values), 0.0)

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            for key, val in sorted(self._values.items()):
                labels = ",".join(f'{n}="{v}"'
                                  for n, v in zip(self.label_names, key))
                lines.append(f"{self.name}{{{labels}}} {val}")
        return "\n".join(lines) + "\n"


class Registry:
    def __init__(self):
        self._metrics: list = []

    def _register(self, metric) -> None:
        self._metrics.append(metric)

    def expose(self) -> str:
        return "".join(m.expose() for m in self._metrics)


def new_operator_metrics(registry: Registry | None = None):
    """The reference's metric set (mpi_job_controller.go:125-141 +
    main.go:29-40)."""
    registry = registry or Registry()
    metrics = {
        "registry": registry,
        "jobs_created": Counter("mpi_operator_jobs_created_total",
                                "Counts number of MPI jobs created", registry),
        "jobs_successful": Counter("mpi_operator_jobs_successful_total",
                                   "Counts number of MPI jobs successful",
                                   registry),
        "jobs_failed": Counter("mpi_operator_jobs_failed_total",
                               "Counts number of MPI jobs failed", registry),
        "job_info": GaugeVec("mpi_operator_job_info",
                             "Information about MPIJob",
                             ["launcher", "namespace"], registry),
        "is_leader": Gauge("mpi_operator_is_leader",
                           "Is this client the leader of this mpi-operator"
                           " client set?", registry),
    }
    return metrics
