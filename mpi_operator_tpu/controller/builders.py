"""Object constructors for the reconcile loop.

Parity targets in /root/reference/pkg/controller/mpi_job_controller.go:
newConfigMap (:1335-1380), updateDiscoverHostsInConfigMap (:1383-1407),
newJobService (:1409-1438), newSSHAuthSecret (:1442-1477), newWorker
(:1499-1552), newLauncherJob (:1554-1580), newLauncherPodTemplate
(:1585-1674), setupSSHOnPod (:1793-1816), env matrices (:117-219).

TPU-native addition: the ``JAX`` implementation replaces the
hostfile/SSH column with coordination-service env injection —
JAX_COORDINATOR_ADDRESS points at process 0's stable DNS name (the
launcher when runLauncherAsWorker, else worker-0), JAX_PROCESS_ID comes
from the replica index, JAX_NUM_PROCESSES from the replica count, and
slotsPerWorker maps to JAX_LOCAL_DEVICE_COUNT (chips per host).  XLA then
forms collectives over ICI/DCN with no SSH, no hostfile, no mpirun.
"""

from __future__ import annotations

from ..api import constants
from ..api.types import MPIJob, ReplicaSpec, run_launcher_as_worker, worker_replicas
from ..k8s import batch, core
from ..k8s.core import (ConfigMap, ConfigMapVolumeSource, Container, EnvVar,
                        KeyToPath, Pod, PodDNSConfig, PodTemplateSpec, Secret,
                        SecretVolumeSource, Service, ServiceSpec, Volume,
                        VolumeMount)
from ..k8s.meta import deep_copy, new_controller_ref, ObjectMeta
from ..telemetry.trace import (TRACE_CONTEXT_ANNOTATION,
                               TRACE_CONTEXT_ENV)

# Naming / mount constants (mpi_job_controller.go:74-96)
CONFIG_SUFFIX = "-config"
CONFIG_VOLUME_NAME = "mpi-job-config"
CONFIG_MOUNT_PATH = "/etc/mpi"
HOSTFILE_NAME = "hostfile"
DISCOVER_HOSTS_SCRIPT_NAME = "discover_hosts.sh"
SSH_AUTH_SECRET_SUFFIX = "-ssh"
SSH_AUTH_VOLUME = "ssh-auth"
ROOT_SSH_PATH = "/root/.ssh"
LAUNCHER = "launcher"
WORKER = "worker"
LAUNCHER_SUFFIX = "-launcher"
WORKER_SUFFIX = "-worker"
SSH_PUBLIC_KEY = "ssh-publickey"
SSH_PRIVATE_KEY_FILE = "id_rsa"
SSH_PUBLIC_KEY_FILE = "id_rsa.pub"
SSH_AUTHORIZED_KEYS_FILE = "authorized_keys"

OPENMPI_SLOTS_ENV = "OMPI_MCA_orte_set_default_slots"
INTEL_MPI_SLOTS_ENV = "I_MPI_PERHOST"

# Env matrices (mpi_job_controller.go:169-219)
LAUNCHER_ENV = [EnvVar("K_MPI_JOB_ROLE", LAUNCHER)]
WORKER_ENV = [EnvVar("K_MPI_JOB_ROLE", WORKER)]
OMPI_ENV = [
    EnvVar("OMPI_MCA_orte_keep_fqdn_hostnames", "true"),
    EnvVar("OMPI_MCA_orte_default_hostfile",
           f"{CONFIG_MOUNT_PATH}/{HOSTFILE_NAME}"),
    EnvVar("OMPI_MCA_plm_rsh_args", "-o ConnectionAttempts=10"),
]
INTEL_ENV = [
    EnvVar("I_MPI_HYDRA_HOST_FILE", f"{CONFIG_MOUNT_PATH}/{HOSTFILE_NAME}"),
    EnvVar("I_MPI_HYDRA_BOOTSTRAP_EXEC_EXTRA_ARGS",
           "-o ConnectionAttempts=10"),
]
MPICH_ENV = [
    EnvVar("HYDRA_HOST_FILE", f"{CONFIG_MOUNT_PATH}/{HOSTFILE_NAME}"),
    EnvVar("HYDRA_LAUNCH_EXTRA_ARGS", "-o ConnectionAttempts=10"),
]
# Accelerator hygiene on a non-worker launcher (:216-219): GPU env blanked;
# TPU analogue forces the launcher's JAX onto CPU so it cannot grab chips.
NVIDIA_DISABLE_ENV = [EnvVar("NVIDIA_VISIBLE_DEVICES", ""),
                      EnvVar("NVIDIA_DRIVER_CAPABILITIES", "")]
JAX_LAUNCHER_CPU_ENV = [EnvVar("JAX_PLATFORMS", "cpu")]

SSH_VOLUME_ITEMS = [
    KeyToPath(core.SSH_AUTH_PRIVATE_KEY, SSH_PRIVATE_KEY_FILE),
    KeyToPath(SSH_PUBLIC_KEY, SSH_PUBLIC_KEY_FILE),
    KeyToPath(SSH_PUBLIC_KEY, SSH_AUTHORIZED_KEYS_FILE),
]
CONFIG_VOLUME_ITEMS = [
    KeyToPath(HOSTFILE_NAME, HOSTFILE_NAME, mode=0o444),
    KeyToPath(DISCOVER_HOSTS_SCRIPT_NAME, DISCOVER_HOSTS_SCRIPT_NAME,
              mode=0o555),
]


def worker_name(job: MPIJob, index: int) -> str:
    return f"{job.metadata.name}{WORKER_SUFFIX}-{index}"


def launcher_name(job: MPIJob) -> str:
    return f"{job.metadata.name}{LAUNCHER_SUFFIX}"


def default_labels(job_name: str, role: str) -> dict:
    """defaultLabels (:1772-1778)."""
    return {
        constants.OPERATOR_NAME_LABEL: constants.OPERATOR_NAME,
        constants.JOB_NAME_LABEL: job_name,
        constants.JOB_ROLE_LABEL: role,
    }


def worker_selector(job_name: str) -> dict:
    """workerSelector (:1780-1783)."""
    return default_labels(job_name, WORKER)


def _owner_ref(job: MPIJob):
    return new_controller_ref(job, constants.GROUP_VERSION, constants.KIND)


def _domain_format(cluster_domain: str) -> str:
    fmt = "{host}.{svc}.{ns}.svc"
    if cluster_domain:
        fmt += f".{cluster_domain}"
    return fmt


def _host_fqdn(host: str, job: MPIJob, cluster_domain: str) -> str:
    return _domain_format(cluster_domain).format(
        host=host, svc=job.metadata.name, ns=job.metadata.namespace)


def job_trace_context(job: MPIJob) -> str:
    """The encoded causal-trace context carried on the job (stamped at
    create by the apiserver), or "" when absent (foreign transports)."""
    return (job.metadata.annotations or {}).get(
        TRACE_CONTEXT_ANNOTATION, "")


def propagate_trace_context(job: MPIJob, annotations: dict,
                            container) -> None:
    """Carry the job's trace context one hop down: onto the pod's
    annotations (the kubelet parents its ``pod_start`` span from it)
    and into the container env (the in-pod train loop parents its
    distributed-init/compile/first-step spans from it) — the explicit
    carrier chain of docs/OBSERVABILITY.md "Causal tracing"."""
    raw = job_trace_context(job)
    if not raw:
        return
    annotations.setdefault(TRACE_CONTEXT_ANNOTATION, raw)
    if all(e.name != TRACE_CONTEXT_ENV for e in container.env):
        container.env.append(EnvVar(TRACE_CONTEXT_ENV, raw))


def propagate_placement(job: MPIJob, annotations: dict, container,
                        worker_index: int) -> None:
    """Surface the gang scheduler's torus placement to the worker pod:
    the placement annotations ride onto the pod, and the container env
    gets the full placement plus THIS worker's slice + chip coordinate
    (worker i owns chips [i*slots, (i+1)*slots) of the placement in
    canonical order).  The in-pod workload uses these to build a
    slice-aware mesh — intra-slice axes over ICI, cross-slice over DCN
    (parallel/mesh.py, docs/SCHEDULING.md "Topology-aware placement").
    No-op for jobs the scheduler did not place."""
    raw = (job.metadata.annotations or {}).get(
        constants.SCHED_PLACEMENT_ANNOTATION)
    if not raw:
        return
    from ..sched.topology import chip_of_index, decode_placement
    placement = decode_placement(raw)
    if not placement:
        return
    annotations.setdefault(constants.SCHED_PLACEMENT_ANNOTATION, raw)
    existing = {e.name for e in container.env}
    slots = job.spec.slots_per_worker or 1
    located = chip_of_index(placement, worker_index * slots)
    pairs = [(constants.PLACEMENT_ENV, raw),
             (constants.NUM_SLICES_ENV, str(len(placement)))]
    if located is not None:
        slice_name, coord = located
        pairs += [(constants.SLICE_NAME_ENV, slice_name),
                  (constants.CHIP_COORDS_ENV,
                   ".".join(str(c) for c in coord))]
    for name, value in pairs:
        if name not in existing:
            container.env.append(EnvVar(name, value))


def is_jax(job: MPIJob) -> bool:
    return job.spec.mpi_implementation == constants.IMPL_JAX


def uses_ssh(job: MPIJob) -> bool:
    """The JAX path needs no SSH transport; MPI paths do."""
    return not is_jax(job)


# ---------------------------------------------------------------------------
# Coordinator math (the TPU-native bootstrap contract)
# ---------------------------------------------------------------------------

def num_processes(job: MPIJob) -> int:
    """World size: workers, plus the launcher when it runs as a worker."""
    return worker_replicas(job) + (1 if run_launcher_as_worker(job) else 0)


def coordinator_host(job: MPIJob, cluster_domain: str) -> str:
    """Process 0's stable DNS name: launcher when runLauncherAsWorker,
    else worker-0 (headless-Service-backed, like the reference's hostfile
    entries at :1349-1361)."""
    if run_launcher_as_worker(job):
        return _host_fqdn(launcher_name(job), job, cluster_domain)
    return _host_fqdn(worker_name(job, 0), job, cluster_domain)


def jax_env(job: MPIJob, process_id: int, cluster_domain: str,
            container_env_names=()) -> list:
    port = constants.DEFAULT_JAX_COORDINATOR_PORT
    env = [
        EnvVar(constants.JAX_COORDINATOR_ADDRESS_ENV,
               f"{coordinator_host(job, cluster_domain)}:{port}"),
        EnvVar(constants.JAX_COORDINATOR_PORT_ENV, str(port)),
        EnvVar(constants.JAX_PROCESS_ID_ENV, str(process_id)),
        EnvVar(constants.JAX_NUM_PROCESSES_ENV, str(num_processes(job))),
        EnvVar(constants.JAX_LOCAL_DEVICE_COUNT_ENV,
               str(job.spec.slots_per_worker or 1)),
    ]
    # Submit timestamp -> workloads report launch-to-first-allreduce
    # latency (BASELINE.md's second target metric).
    if job.metadata.creation_timestamp is not None:
        env.append(EnvVar(
            constants.MPIJOB_SUBMIT_TIME_ENV,
            f"{job.metadata.creation_timestamp.timestamp():.3f}"))
    # Persistent compilation cache: the second life of any process (job
    # restart, gang repair, elastic re-form) skips XLA recompilation,
    # directly cutting launch-to-first-allreduce.  Annotation overrides
    # the path; empty annotation disables.
    # Injected env is merged AFTER the user's container env and the pod
    # runtime resolves duplicates last-wins, so an explicit user value
    # must suppress the default entirely.
    cache_dir = job.metadata.annotations.get(
        constants.JAX_COMPILATION_CACHE_ANNOTATION,
        constants.DEFAULT_JAX_COMPILATION_CACHE)
    if cache_dir and \
            constants.JAX_COMPILATION_CACHE_ENV not in container_env_names:
        env.append(EnvVar(constants.JAX_COMPILATION_CACHE_ENV, cache_dir))
    # Multislice (DCN): partition workers into same-sized slices and point
    # every process at one megascale coordinator (slice 0's worker-0);
    # XLA bridges slices over DCN, ICI stays intra-slice (SURVEY.md §5).
    slices = job.spec.slices or 1
    if slices > 1:
        per_slice = max(1, num_processes(job) // slices)
        env.extend([
            EnvVar(constants.MEGASCALE_COORDINATOR_ADDRESS_ENV,
                   f"{_host_fqdn(worker_name(job, 0), job, cluster_domain)}"
                   f":{constants.DEFAULT_MEGASCALE_PORT}"),
            EnvVar(constants.MEGASCALE_NUM_SLICES_ENV, str(slices)),
            EnvVar(constants.MEGASCALE_SLICE_ID_ENV,
                   str(process_id // per_slice)),
        ])
    return env


# ---------------------------------------------------------------------------
# ConfigMap (hostfile + discover_hosts.sh)
# ---------------------------------------------------------------------------

def new_config_map(job: MPIJob, workers: int, cluster_domain: str) -> ConfigMap:
    """newConfigMap (:1335-1380).  For JAX the hostfile is informational
    (one FQDN per line) — bootstrap rides the coordinator env instead."""
    slots = job.spec.slots_per_worker or 1
    lines = []

    def host_line(host: str) -> str:
        fqdn = _host_fqdn(host, job, cluster_domain)
        impl = job.spec.mpi_implementation
        if impl == constants.IMPL_OPENMPI:
            return f"{fqdn} slots={slots}"
        if impl in (constants.IMPL_INTEL, constants.IMPL_MPICH):
            return f"{fqdn}:{slots}"
        return fqdn  # JAX: plain host list for debugging/tooling

    if run_launcher_as_worker(job):
        lines.append(host_line(launcher_name(job)))
    for i in range(workers):
        lines.append(host_line(worker_name(job, i)))

    return ConfigMap(
        metadata=ObjectMeta(
            name=job.metadata.name + CONFIG_SUFFIX,
            namespace=job.metadata.namespace,
            labels={"app": job.metadata.name},
            owner_references=[_owner_ref(job)]),
        data={HOSTFILE_NAME: "".join(line + "\n" for line in lines)})


def update_discover_hosts_in_config_map(config_map: ConfigMap, job: MPIJob,
                                        running_pods: list,
                                        cluster_domain: str) -> None:
    """updateDiscoverHostsInConfigMap (:1383-1407): regenerate the elastic
    host-discovery script from *running* worker pods, sorted by name."""
    pods = sorted(running_pods, key=lambda p: p.metadata.name)
    lines = ["#!/bin/sh"]
    if run_launcher_as_worker(job):
        lines.append("echo " + _host_fqdn(launcher_name(job), job,
                                          cluster_domain))
    for pod in pods:
        lines.append("echo " + _domain_format(cluster_domain).format(
            host=pod.metadata.name, svc=job.metadata.name,
            ns=pod.metadata.namespace))
    config_map.data[DISCOVER_HOSTS_SCRIPT_NAME] = "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Service
# ---------------------------------------------------------------------------

def new_job_service(job: MPIJob) -> Service:
    """newJobService (:1409-1438): one headless Service fronting launcher
    and workers for stable per-pod DNS."""
    selector = {
        constants.OPERATOR_NAME_LABEL: constants.OPERATOR_NAME,
        constants.JOB_NAME_LABEL: job.metadata.name,
    }
    return Service(
        metadata=ObjectMeta(
            name=job.metadata.name,
            namespace=job.metadata.namespace,
            labels={"app": job.metadata.name},
            owner_references=[_owner_ref(job)]),
        spec=ServiceSpec(
            cluster_ip=core.CLUSTER_IP_NONE,
            selector=selector,
            # True only with runLauncherAsWorker to avoid the launcher-ready
            # deadlock (:1433-1435).  The JAX path needs it whenever workers
            # must resolve the coordinator before it is Ready.
            publish_not_ready_addresses=(run_launcher_as_worker(job)
                                         or is_jax(job))))


# ---------------------------------------------------------------------------
# SSH Secret (MPI implementations only)
# ---------------------------------------------------------------------------

def _generate_ssh_keypair() -> tuple:
    """Fresh ECDSA P-521 keypair as (private PEM, OpenSSH public key).

    Prefers the cryptography package; falls back to the system
    ``ssh-keygen`` binary when the package is absent (some images ship
    OpenSSH tooling but no Python cryptography wheel)."""
    try:
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric import ec
    except ImportError:
        import os
        import subprocess
        import tempfile

        with tempfile.TemporaryDirectory() as tmpdir:
            keyfile = os.path.join(tmpdir, "id_ecdsa")
            subprocess.run(
                ["ssh-keygen", "-q", "-t", "ecdsa", "-b", "521", "-N", "",
                 "-m", "PEM", "-C", "mpi-operator", "-f", keyfile],
                check=True, capture_output=True)
            with open(keyfile, "rb") as f:
                private_pem = f.read()
            with open(keyfile + ".pub", "rb") as f:
                public_ssh = f.read().strip()
        return private_pem, public_ssh

    private_key = ec.generate_private_key(ec.SECP521R1())
    private_pem = private_key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())
    public_ssh = private_key.public_key().public_bytes(
        serialization.Encoding.OpenSSH, serialization.PublicFormat.OpenSSH)
    return private_pem, public_ssh


def new_ssh_auth_secret(job: MPIJob) -> Secret:
    """newSSHAuthSecret (:1442-1477): fresh ECDSA P-521 keypair, private
    PEM + OpenSSH public key."""
    private_pem, public_ssh = _generate_ssh_keypair()

    return Secret(
        metadata=ObjectMeta(
            name=job.metadata.name + SSH_AUTH_SECRET_SUFFIX,
            namespace=job.metadata.namespace,
            labels={"app": job.metadata.name},
            owner_references=[_owner_ref(job)]),
        type=core.SECRET_TYPE_SSH_AUTH,
        data={core.SSH_AUTH_PRIVATE_KEY: private_pem,
              SSH_PUBLIC_KEY: public_ssh + b"\n"})


def setup_ssh_on_pod(pod_spec, job: MPIJob) -> None:
    """setupSSHOnPod (:1793-1816)."""
    mode = 0o600 if job.spec.ssh_auth_mount_path == ROOT_SSH_PATH else None
    pod_spec.volumes.append(Volume(
        name=SSH_AUTH_VOLUME,
        secret=SecretVolumeSource(
            secret_name=job.metadata.name + SSH_AUTH_SECRET_SUFFIX,
            items=deep_copy(SSH_VOLUME_ITEMS),
            default_mode=mode)))
    pod_spec.containers[0].volume_mounts.append(VolumeMount(
        name=SSH_AUTH_VOLUME, mount_path=job.spec.ssh_auth_mount_path))


# ---------------------------------------------------------------------------
# Worker Pod
# ---------------------------------------------------------------------------

def set_restart_policy(template: PodTemplateSpec, spec: ReplicaSpec) -> None:
    """setRestartPolicy (:1722-1728): ExitCode maps to Never."""
    if spec.restart_policy == constants.RESTART_POLICY_EXIT_CODE:
        template.spec.restart_policy = core.RESTART_POLICY_NEVER
    else:
        template.spec.restart_policy = spec.restart_policy


def worker_replica_index_label(job: MPIJob, index: int) -> str:
    """workerReplicaIndexLabel (:1487-1494): pad by one when the launcher
    runs as a worker so all PodGroup members carry unique indices."""
    if run_launcher_as_worker(job):
        return str(index + 1)
    return str(index)


def new_worker(job: MPIJob, index: int, pod_group_ctrl=None,
               cluster_domain: str = "") -> Pod:
    """newWorker (:1499-1552)."""
    name = worker_name(job, index)
    template = deep_copy(job.worker_spec.template)

    labels = dict(template.metadata.labels)
    labels.update(default_labels(job.metadata.name, WORKER))
    labels[constants.REPLICA_INDEX_LABEL] = worker_replica_index_label(job, index)
    template.metadata.labels = labels

    template.spec.hostname = name
    template.spec.subdomain = job.metadata.name  # matches the Service name
    if template.spec.host_network:
        template.spec.dns_policy = core.DNS_CLUSTER_FIRST_WITH_HOST_NET
    # Intel/MPICH workers reach the launcher by bare hostname (:1519-1525).
    search = f"{job.metadata.name}.{job.metadata.namespace}.svc.cluster.local"
    if template.spec.dns_config is None:
        template.spec.dns_config = PodDNSConfig(searches=[search])
    else:
        template.spec.dns_config.searches.append(search)
    set_restart_policy(template, job.worker_spec)

    container = template.spec.containers[0]
    if not container.command and not container.args:
        if uses_ssh(job):
            container.command = ["/usr/sbin/sshd", "-De"]
        # JAX workers run the user's image entrypoint: the workload calls
        # jax.distributed.initialize() from the injected env.
    container.env = list(container.env) + deep_copy(WORKER_ENV)
    if is_jax(job):
        process_id = index + (1 if run_launcher_as_worker(job) else 0)
        container.env += jax_env(
            job, process_id, cluster_domain,
            container_env_names={e.name for e in container.env})
    if uses_ssh(job):
        setup_ssh_on_pod(template.spec, job)

    if pod_group_ctrl is not None:
        pod_group_ctrl.decorate_pod_template(template, job.metadata.name)

    annotations = dict(template.metadata.annotations)
    propagate_trace_context(job, annotations, container)
    propagate_placement(job, annotations, container, index)

    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=job.metadata.namespace,
            labels=template.metadata.labels,
            annotations=annotations,
            owner_references=[_owner_ref(job)]),
        spec=template.spec)


# ---------------------------------------------------------------------------
# Launcher Job
# ---------------------------------------------------------------------------

def new_launcher_job(job: MPIJob, pod_group_ctrl=None, recorder=None,
                     cluster_domain: str = "") -> batch.Job:
    """newLauncherJob (:1554-1580)."""
    launcher = batch.Job(
        metadata=ObjectMeta(
            name=launcher_name(job),
            namespace=job.metadata.namespace,
            labels={"app": job.metadata.name},
            owner_references=[_owner_ref(job)]),
        spec=batch.JobSpec(
            ttl_seconds_after_finished=job.spec.run_policy.ttl_seconds_after_finished,
            active_deadline_seconds=job.spec.run_policy.active_deadline_seconds,
            backoff_limit=job.spec.run_policy.backoff_limit,
            template=new_launcher_pod_template(job, pod_group_ctrl, recorder,
                                               cluster_domain),
            # Guard against recreating terminating pods (:1571-1574).
            pod_replacement_policy=batch.POD_REPLACEMENT_POLICY_FAILED))
    if job.spec.run_policy.suspend:
        launcher.spec.suspend = True
    return launcher


def new_launcher_pod_template(job: MPIJob, pod_group_ctrl=None,
                              recorder=None,
                              cluster_domain: str = "") -> PodTemplateSpec:
    """newLauncherPodTemplate (:1585-1674)."""
    name = launcher_name(job)
    template = deep_copy(job.launcher_spec.template)

    labels = dict(template.metadata.labels)
    labels.update(default_labels(job.metadata.name, LAUNCHER))
    template.metadata.labels = labels
    if pod_group_ctrl is not None:
        pod_group_ctrl.decorate_pod_template(template, job.metadata.name)
    if run_launcher_as_worker(job):
        template.metadata.labels[constants.REPLICA_INDEX_LABEL] = "0"

    template.spec.hostname = name
    template.spec.subdomain = job.metadata.name
    if template.spec.host_network:
        template.spec.dns_policy = core.DNS_CLUSTER_FIRST_WITH_HOST_NET

    container = template.spec.containers[0]
    container.env = list(container.env) + deep_copy(LAUNCHER_ENV)
    slots = str(job.spec.slots_per_worker or 1)
    impl = job.spec.mpi_implementation
    if impl == constants.IMPL_OPENMPI:
        container.env += deep_copy(OMPI_ENV)
        container.env.append(EnvVar(OPENMPI_SLOTS_ENV, slots))
    elif impl == constants.IMPL_INTEL:
        container.env += deep_copy(INTEL_ENV)
        container.env.append(EnvVar(INTEL_MPI_SLOTS_ENV, slots))
    elif impl == constants.IMPL_MPICH:
        container.env += deep_copy(MPICH_ENV)
    elif impl == constants.IMPL_JAX:
        # Launcher is process 0 when it runs as a worker; otherwise it is a
        # pure driver that still receives the coordinator address for
        # monitoring (but no process id).
        if run_launcher_as_worker(job):
            container.env += jax_env(
                job, 0, cluster_domain,
                container_env_names={e.name for e in container.env})
        else:
            port = constants.DEFAULT_JAX_COORDINATOR_PORT
            container.env.append(EnvVar(
                constants.JAX_COORDINATOR_ADDRESS_ENV,
                f"{coordinator_host(job, cluster_domain)}:{port}"))
            container.env.append(EnvVar(constants.JAX_NUM_PROCESSES_ENV,
                                        str(num_processes(job))))

    if not run_launcher_as_worker(job):
        # Accelerator hygiene (:1629-1635): no GPUs, and for JAX pin the
        # launcher to CPU so it cannot claim the TPU chips.
        container.env += deep_copy(NVIDIA_DISABLE_ENV)
        if is_jax(job):
            container.env += deep_copy(JAX_LAUNCHER_CPU_ENV)

    if uses_ssh(job):
        setup_ssh_on_pod(template.spec, job)

    if template.spec.restart_policy and recorder is not None:
        recorder.event(job, core.EVENT_TYPE_WARNING,
                       "SetPodTemplateRestartPolicy",
                       "Restart policy in pod template overridden by restart"
                       " policy in replica spec")
    set_restart_policy(template, job.launcher_spec)

    # hostfile + discover_hosts.sh volume (:1647-1662) — all impls get it;
    # for JAX it is debugging/elastic-tooling surface.
    template.spec.volumes = list(template.spec.volumes) + [Volume(
        name=CONFIG_VOLUME_NAME,
        config_map=ConfigMapVolumeSource(
            name=job.metadata.name + CONFIG_SUFFIX,
            items=deep_copy(CONFIG_VOLUME_ITEMS)))]
    container.volume_mounts.append(VolumeMount(
        name=CONFIG_VOLUME_NAME, mount_path=CONFIG_MOUNT_PATH))

    launcher_annotations = dict(template.metadata.annotations)
    propagate_trace_context(job, launcher_annotations, container)

    return PodTemplateSpec(
        metadata=ObjectMeta(labels=template.metadata.labels,
                            annotations=launcher_annotations,
                            owner_references=[_owner_ref(job)]),
        spec=template.spec)


def sync_launcher_scheduling_directives(launcher: batch.Job,
                                        desired: PodTemplateSpec) -> None:
    """syncLauncherSchedulingDirectives (:1685-1692): Kueue (KEP-2926)
    mutable scheduling directives."""
    launcher.spec.template.metadata.labels = {
        **launcher.spec.template.metadata.labels, **desired.metadata.labels}
    launcher.spec.template.metadata.annotations = {
        **launcher.spec.template.metadata.annotations,
        **desired.metadata.annotations}
    launcher.spec.template.spec.node_selector = desired.spec.node_selector
    launcher.spec.template.spec.tolerations = desired.spec.tolerations
    launcher.spec.template.spec.scheduling_gates = desired.spec.scheduling_gates
