"""The MPIJob reconcile controller (TPU-native re-architecture of
/root/reference/pkg/controller)."""

from .controller import MPIJobController  # noqa: F401
from .servejob import ServeJobController  # noqa: F401
