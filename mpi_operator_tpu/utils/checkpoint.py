"""Orbax-backed checkpoint/resume for sharded train states.

Control-plane suspend/resume (controller + Kueue) deletes pods and
recreates them later; this is the data-plane half: workloads save the
sharded TrainState periodically and restore on restart, so a
suspended/preempted/rescheduled MPIJob resumes from the last step.
Orbax handles multi-host coordination and sharded array layouts
natively (each host writes its shards).

Two durability/latency properties on top of plain orbax:

- **Atomic commit**: every save writes into ``step_NNNNNNNN.tmp-*``,
  drops a ``_COMMITTED`` marker, then renames to ``step_NNNNNNNN`` —
  :func:`latest_steps` / :func:`restore_checkpoint` only ever see
  fully-written checkpoints, so a crash mid-write (sync or async) can
  never be restored as a torn checkpoint.  Retention GC also sweeps
  stale tmp dirs left by crashed writers.
- **Async saves** (:class:`CheckpointManager`, the default): ``save()``
  snapshots the sharded state to host memory (``jax.device_get``
  per-shard) and hands the write to a single background writer thread.
  The train loop only blocks if a new save is requested while the
  previous write is still in flight (``checkpoint_save_blocked_seconds``
  counts exactly that time); goodput's checkpoint bucket records only
  the snapshot, proving the write latency left the step path.  Writer
  failures are fatal-loud: the thread dumps a flight-recorder bundle,
  and the stored exception re-raises on the train loop at the next save
  point (or ``drain()``) instead of leaving a silently dead writer.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Any, Optional

from ..telemetry.metrics import default_registry
from ..telemetry.trace import span

# A checkpoint directory is only restorable once this marker exists
# inside it.  The marker is written into the tmp dir BEFORE the atomic
# rename, so every final-named dir carries it by construction.
COMMIT_MARKER = "_COMMITTED"

# Stale-tmp sweep age: tmp dirs older than this are crash leftovers
# (a live writer renames within one save); younger ones may belong to a
# concurrent writer on a shared filesystem and are left alone.
TMP_SWEEP_AGE_ENV = "MPI_OPERATOR_CKPT_TMP_SWEEP_AGE_S"
DEFAULT_TMP_SWEEP_AGE_S = 3600.0


def _checkpoint_metrics(registry=None):
    registry = registry or default_registry()
    return {
        "save": registry.histogram(
            "checkpoint_save_seconds", "Checkpoint save wall time"),
        "restore": registry.histogram(
            "checkpoint_restore_seconds", "Checkpoint restore wall time"),
    }


def _async_metrics(registry=None):
    registry = registry or default_registry()
    return {
        "async_saves": registry.counter(
            "checkpoint_async_saves_total",
            "Checkpoint saves handed to the background writer thread"),
        "blocked_seconds": registry.counter(
            "checkpoint_save_blocked_seconds",
            "Train-loop seconds spent blocked waiting for an in-flight"
            " async checkpoint write"),
        "snapshot": registry.histogram(
            "checkpoint_snapshot_seconds",
            "Device-to-host state snapshot wall time (async save)"),
    }


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:08d}")


def _tmp_dir(directory: str, step: int) -> str:
    # Deterministic suffix: multi-host orbax needs every process to
    # agree on the write path, and a crashed same-step attempt is
    # force-overwritten anyway.
    return _step_dir(directory, step) + ".tmp-w"


def _dir_restorable(path: str) -> bool:
    """A final-named checkpoint dir is restorable when it has any
    content at all.  The atomicity guarantee lives in the tmp+rename
    protocol: this writer only ever produces final-named dirs whole
    (with the ``_COMMITTED`` marker already inside), so the torn shapes
    it can leave behind are ``.tmp-*`` dirs (never listed) and empty
    final dirs — both rejected here.  Marker-less non-empty dirs are
    pre-marker legacy saves and must stay restorable (requiring the
    marker would silently restart upgraded jobs from step 0), which is
    why the marker itself is forensic, not load-bearing."""
    try:
        entries = os.listdir(path)
    except OSError:
        return False
    return bool(entries)


def is_committed(directory: str, step: int) -> bool:
    return _dir_restorable(_step_dir(directory, step))


def _sweep_stale_tmp(directory: str) -> None:
    age = float(os.environ.get(TMP_SWEEP_AGE_ENV, DEFAULT_TMP_SWEEP_AGE_S))
    now = time.time()
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        if not (name.startswith("step_") and ".tmp-" in name):
            continue
        path = os.path.join(directory, name)
        try:
            stale = now - os.path.getmtime(path) >= age
        except OSError:
            continue
        if stale:
            shutil.rmtree(path, ignore_errors=True)


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3) -> str:
    """Save `state` (any pytree, incl. sharded arrays) at `step`.

    Atomic: the write lands in ``step_NNNNNNNN.tmp-*`` and is renamed
    into place only after the data and the ``_COMMITTED`` marker are
    down — readers never observe a partial checkpoint.
    """
    import jax

    path = _step_dir(directory, step)
    tmp = _tmp_dir(directory, step)
    with span("checkpoint_save", step=step), \
            _checkpoint_metrics()["save"].time():
        if os.path.isdir(tmp):
            # Crash leftover from a previous attempt at this exact step.
            shutil.rmtree(tmp, ignore_errors=True)
        _checkpointer().save(tmp, state, force=True)
        if jax.process_index() == 0:
            with open(os.path.join(tmp, COMMIT_MARKER), "w") as f:
                f.write(f"step={step}\n")
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            os.rename(tmp, path)
    # Retention: drop oldest beyond `keep` (process 0 only on multi-host).
    # keep <= 0 disables GC entirely, and the step just written is never
    # a deletion candidate even if the directory listing races with
    # concurrent writers and miscounts.
    if jax.process_index() == 0 and keep > 0:
        steps = latest_steps(directory)
        for old in steps[:-keep]:
            if old == step:
                continue
            shutil.rmtree(_step_dir(directory, old), ignore_errors=True)
        _sweep_stale_tmp(directory)
    return path


def latest_steps(directory: str) -> list:
    """Sorted committed checkpoint steps.  Tmp dirs (in-flight or crash
    leftovers) and empty final-named dirs are never listed — a torn
    write can not be restored.  Marker-less but non-empty dirs are
    legacy (pre-marker) checkpoints and stay restorable."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if not name.startswith("step_") or ".tmp-" in name:
            continue
        try:
            step = int(name.split("_")[1])
        except (IndexError, ValueError):
            continue
        if _dir_restorable(os.path.join(directory, name)):
            steps.append(step)
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    steps = latest_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, target: Any,
                       step: Optional[int] = None) -> Any:
    """Restore into the structure/shardings of `target`; returns the
    restored pytree, or `target` unchanged if no committed checkpoint
    exists.  An explicitly requested uncommitted step raises rather
    than restoring a torn write."""
    if step is None:
        step = latest_step(directory)
    if step is None:
        return target
    if not is_committed(directory, step):
        raise ValueError(
            f"checkpoint step {step} in {directory} is uncommitted "
            f"(absent, empty, or still under a .tmp dir); refusing to "
            f"restore a torn write")
    import orbax.checkpoint as ocp
    with span("checkpoint_restore", step=step), \
            _checkpoint_metrics()["restore"].time():
        return _checkpointer().restore(
            _step_dir(directory, step), item=target,
            restore_args=ocp.checkpoint_utils.construct_restore_args(target))


class CheckpointManager:
    """Convenience wrapper for train loops, async by default.

    >>> mgr = CheckpointManager(dir, every=100)
    >>> state = mgr.restore(state)           # resume if possible
    >>> for ...: state = ...; mgr.maybe_save(state, step)
    >>> mgr.drain()                          # flush the in-flight write

    ``async_save=True`` (default): ``save()`` blocks only for the
    device-to-host snapshot (plus any wait for a previous still-running
    write); the orbax write itself runs on a background writer thread.
    ``async_save=False`` restores the fully synchronous legacy path.
    Read APIs (``restore``/``resume_step``) drain the writer first so
    they always observe the newest save.
    """

    def __init__(self, directory: str, every: int = 100, keep: int = 3,
                 goodput=None, async_save: bool = True, registry=None):
        self.directory = directory
        self.every = every
        self.keep = keep
        # Optional telemetry.goodput.GoodputTracker: snapshot (async) or
        # save (sync) time is then attributed to the checkpoint bucket
        # of the train loop's goodput summary.
        self.goodput = goodput
        self.async_save = async_save
        self._metrics = _async_metrics(registry)
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._writer_error: Optional[BaseException] = None
        self._completed_since_poll = False
        self.last_written_step: Optional[int] = None

    # -- async writer machinery -------------------------------------------
    def _join_inflight(self, count_blocked: bool) -> None:
        thread = self._thread
        if thread is None or not thread.is_alive():
            if thread is not None:
                thread.join()
            return
        start = time.perf_counter()
        thread.join()
        if count_blocked:
            self._metrics["blocked_seconds"].inc(
                time.perf_counter() - start)

    def _raise_writer_error(self) -> None:
        with self._lock:
            err, self._writer_error = self._writer_error, None
        if err is not None:
            raise err

    def _write(self, host_state: Any, step: int) -> None:
        try:
            save_checkpoint(self.directory, host_state, step, self.keep)
            with self._lock:
                self._completed_since_poll = True
                self.last_written_step = step
        except BaseException as exc:  # fatal-loud, re-raised on the loop
            try:
                from ..telemetry import flight
                nbytes = sum(
                    int(getattr(x, "nbytes", 0))
                    for x in _tree_leaves(host_state))
                flight.record("train", "checkpoint_writer_error",
                              step=step, in_flight_bytes=nbytes,
                              error=repr(exc))
                flight.dump_bundle("checkpoint-writer-error")
            # Failure path: best-effort telemetry must never mask the
            # stored writer error (re-raised at the next save point).
            except Exception:  # lint: allow[silent-except]
                pass
            with self._lock:
                self._completed_since_poll = True
                self._writer_error = exc

    def drain(self) -> None:
        """Block until the in-flight async write (if any) has finished;
        re-raises a writer failure on the caller.  Not counted into
        ``checkpoint_save_blocked_seconds`` — that counter measures the
        STEP PATH only (a save waiting on the previous write); drain
        runs off it (end of training, preemption grace window)."""
        self._join_inflight(count_blocked=False)
        self._raise_writer_error()

    def completed_since_last_poll(self) -> bool:
        """True exactly once after each async write finishes — the train
        loop re-polls the preemption notice on that edge."""
        with self._lock:
            done, self._completed_since_poll = \
                self._completed_since_poll, False
        return done

    @property
    def in_flight(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    # -- save/restore ------------------------------------------------------
    def restore(self, target: Any) -> Any:
        self.drain()
        return restore_checkpoint(self.directory, target)

    def resume_step(self) -> int:
        self.drain()
        return latest_step(self.directory) or 0

    def maybe_save(self, state: Any, step: int) -> bool:
        if self.every and step % self.every == 0 and step > 0:
            self.save(state, step)
            return True
        return False

    def _async_snapshot_possible(self, state: Any) -> bool:
        """Async saves snapshot the FULL state to this host's memory,
        which is only possible (and only correct) when every array is
        fully addressable from this process.  Multi-process jobs fall
        back to the sync path, where orbax has each host write its own
        shards — jax.device_get on a cross-host sharded array raises."""
        try:
            import jax
            if jax.process_count() > 1:
                return False
            return all(getattr(x, "is_fully_addressable", True)
                       for x in _tree_leaves(state))
        except ImportError:
            return True

    def save(self, state: Any, step: int) -> str:
        """Unconditional save — also the preemption path (a notice
        arrived; checkpoint NOW, off the periodic schedule, then exit).
        Async mode returns as soon as the host snapshot is taken and the
        write is handed to the writer thread."""
        # The next save point is where a dead writer must get loud: a
        # failure that only ever surfaced in drain() could hide for the
        # whole run under every-N scheduling.
        self._raise_writer_error()
        if not self.async_save or not self._async_snapshot_possible(state):
            # Never overlap a sync write with a still-running async one
            # (possible when addressability forces a mid-run fallback).
            self._join_inflight(count_blocked=True)
            self._raise_writer_error()
            if self.goodput is not None:
                with self.goodput.checkpoint_save():
                    return save_checkpoint(self.directory, state, step,
                                           self.keep)
            return save_checkpoint(self.directory, state, step, self.keep)

        # Only block if the previous write is still in flight.
        self._join_inflight(count_blocked=True)
        self._raise_writer_error()

        def _snapshot():
            import jax
            with self._metrics["snapshot"].time():
                return jax.device_get(state)

        if self.goodput is not None:
            with self.goodput.checkpoint_save():
                host_state = _snapshot()
        else:
            host_state = _snapshot()
        self._metrics["async_saves"].inc()
        self._thread = threading.Thread(
            target=self._write, args=(host_state, step),
            name=f"ckpt-writer-{step}", daemon=True)
        self._thread.start()
        return _step_dir(self.directory, step)


def _tree_leaves(tree):
    try:
        import jax
        return jax.tree_util.tree_leaves(tree)
    except ImportError:
        return []
