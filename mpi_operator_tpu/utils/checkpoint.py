"""Orbax-backed checkpoint/resume for sharded train states.

Control-plane suspend/resume (controller + Kueue) deletes pods and
recreates them later; this is the data-plane half: workloads save the
sharded TrainState periodically and restore on restart, so a
suspended/preempted/rescheduled MPIJob resumes from the last step.
Orbax handles multi-host coordination and sharded array layouts
natively (each host writes its shards).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional

from ..telemetry.metrics import default_registry
from ..telemetry.trace import span


def _checkpoint_metrics(registry=None):
    registry = registry or default_registry()
    return {
        "save": registry.histogram(
            "checkpoint_save_seconds", "Checkpoint save wall time"),
        "restore": registry.histogram(
            "checkpoint_restore_seconds", "Checkpoint restore wall time"),
    }


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:08d}")


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3) -> str:
    """Save `state` (any pytree, incl. sharded arrays) at `step`."""
    import jax

    path = _step_dir(directory, step)
    with span("checkpoint_save", step=step), \
            _checkpoint_metrics()["save"].time():
        _checkpointer().save(path, state, force=True)
    # Retention: drop oldest beyond `keep` (process 0 only on multi-host).
    # keep <= 0 disables GC entirely, and the step just written is never
    # a deletion candidate even if the directory listing races with
    # concurrent writers and miscounts.
    if jax.process_index() == 0 and keep > 0:
        steps = latest_steps(directory)
        for old in steps[:-keep]:
            if old == step:
                continue
            shutil.rmtree(_step_dir(directory, old), ignore_errors=True)
    return path


def latest_steps(directory: str) -> list:
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            try:
                steps.append(int(name.split("_")[1]))
            except (IndexError, ValueError):
                continue
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    steps = latest_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, target: Any,
                       step: Optional[int] = None) -> Any:
    """Restore into the structure/shardings of `target`; returns the
    restored pytree, or `target` unchanged if no checkpoint exists."""
    if step is None:
        step = latest_step(directory)
    if step is None:
        return target
    import orbax.checkpoint as ocp
    with span("checkpoint_restore", step=step), \
            _checkpoint_metrics()["restore"].time():
        return _checkpointer().restore(
            _step_dir(directory, step), item=target,
            restore_args=ocp.checkpoint_utils.construct_restore_args(target))


class CheckpointManager:
    """Tiny convenience wrapper for train loops.

    >>> mgr = CheckpointManager(dir, every=100)
    >>> state = mgr.restore(state)           # resume if possible
    >>> for ...: state = ...; mgr.maybe_save(state, step)
    """

    def __init__(self, directory: str, every: int = 100, keep: int = 3,
                 goodput=None):
        self.directory = directory
        self.every = every
        self.keep = keep
        # Optional telemetry.goodput.GoodputTracker: save time is then
        # attributed to the checkpoint bucket of the train loop's
        # goodput summary.
        self.goodput = goodput

    def restore(self, target: Any) -> Any:
        return restore_checkpoint(self.directory, target)

    def resume_step(self) -> int:
        return latest_step(self.directory) or 0

    def maybe_save(self, state: Any, step: int) -> bool:
        if self.every and step % self.every == 0 and step > 0:
            self.save(state, step)
            return True
        return False

    def save(self, state: Any, step: int) -> str:
        """Unconditional save — the preemption path (a notice arrived;
        checkpoint NOW, off the periodic schedule, then exit)."""
        if self.goodput is not None:
            with self.goodput.checkpoint_save():
                return save_checkpoint(self.directory, state, step,
                                       self.keep)
        return save_checkpoint(self.directory, state, step, self.keep)
