"""Profiling hook — env-driven jax profiler traces.

The reference only *mentions* Horovod Timeline as a roadmap idea
(ROADMAP.md:14) and keeps the operator thin; matching that philosophy,
profiling here is a workload-side opt-in: set ``JAX_PROFILE_DIR`` in the
MPIJob pod template env and wrap the hot loop in ``maybe_profile()`` —
traces land per-process for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import os

from ..telemetry.trace import span


@contextlib.contextmanager
def maybe_profile(name: str = "train", env_var: str = "JAX_PROFILE_DIR"):
    """Profile the enclosed block iff the env var points at a directory.

    Either way the block is bracketed by a telemetry span, so the
    profiled (or skipped) region shows up on the process timeline with
    the trace output directory attached when profiling is active."""
    directory = os.environ.get(env_var)
    if not directory:
        with span("profile", profile=name, active=False):
            yield False
        return
    import jax

    out = os.path.join(directory,
                       f"{name}-p{jax.process_index()}")
    os.makedirs(out, exist_ok=True)
    with span("profile", profile=name, active=True, out=out):
        with jax.profiler.trace(out):
            yield True
