"""Profiling hook — env-driven jax profiler traces.

The reference only *mentions* Horovod Timeline as a roadmap idea
(ROADMAP.md:14) and keeps the operator thin; matching that philosophy,
profiling here is a workload-side opt-in: set ``JAX_PROFILE_DIR`` in the
MPIJob pod template env and wrap the hot loop in ``maybe_profile()`` —
traces land per-process for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def maybe_profile(name: str = "train", env_var: str = "JAX_PROFILE_DIR"):
    """Profile the enclosed block iff the env var points at a directory."""
    directory = os.environ.get(env_var)
    if not directory:
        yield False
        return
    import jax

    out = os.path.join(directory,
                       f"{name}-p{jax.process_index()}")
    os.makedirs(out, exist_ok=True)
    with jax.profiler.trace(out):
        yield True
