"""Workload utilities: checkpointing, profiling, logging.

The reference keeps the operator thin and delegates data-plane concerns
to the workload (SURVEY.md §5: no checkpointing, profiling only as a
roadmap idea).  The TPU-native stack ships them as workload-side
utilities: orbax checkpoint/resume (pairs with the control plane's
suspend/resume so a preempted job restarts from step N), and a
jax-profiler hook driven by env.
"""

from .checkpoint import (CheckpointManager, is_committed,  # noqa: F401
                         latest_step, latest_steps, restore_checkpoint,
                         save_checkpoint)
from .data import DevicePrefetcher  # noqa: F401
from .profiler import maybe_profile  # noqa: F401
