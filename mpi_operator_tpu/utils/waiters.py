"""Condition-driven waits for tests and smokes.

The PR 10 deflake class: a test that hand-rolls ``while ...:
time.sleep(0.2)`` either flakes (deadline too tight for a loaded
1-core host) or wastes wall clock (interval too coarse — the condition
turned true 190ms ago).  The ``sleep-poll`` lint rule
(docs/ANALYSIS.md) bans the hand-rolled form in tests/ and
tools/*_smoke.py; this module is the sanctioned replacement: one
deadline-bounded primitive with a tight default interval, a uniform
TimeoutError that names the condition, and the final predicate value
returned so call sites assert on data instead of re-reading state.

Prefer a real watch (``cluster.wait_for``, informer handlers,
``threading.Event``) when the subsystem offers one; ``wait_until`` is
for conditions only observable by probing (HTTP endpoints, metric
counters, file existence, subprocess state).
"""

from __future__ import annotations

import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


def wait_until(predicate: Callable[[], T], timeout: float = 30.0,
               interval: float = 0.02, desc: str = "condition",
               on_timeout: Optional[Callable[[], str]] = None) -> T:
    """Poll ``predicate`` until it returns a truthy value (returned), or
    raise TimeoutError after ``timeout`` seconds.

    ``desc`` names the condition in the timeout error; ``on_timeout``
    (optional) contributes late diagnostics (e.g. the state actually
    observed) to the message.  The predicate is always evaluated one
    final time at the deadline, so a condition that turns true in the
    last interval still passes.
    """
    deadline = time.monotonic() + timeout
    while True:
        value = predicate()
        if value:
            return value
        if time.monotonic() >= deadline:
            detail = ""
            if on_timeout is not None:
                try:
                    detail = f" ({on_timeout()})"
                except Exception as exc:  # diagnostics must not mask
                    detail = f" (diagnostic failed: {exc!r})"
            raise TimeoutError(
                f"timed out after {timeout}s waiting for {desc}{detail}")
        time.sleep(interval)
