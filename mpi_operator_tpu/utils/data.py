"""Sharded input pipeline helpers.

Multi-host JAX needs every process to feed its local shard of the global
batch; this module turns per-process numpy batches into global sharded
arrays.  The reference delegates data loading entirely to the workload
(tf_cnn_benchmarks' synthetic data, Horovod MNIST downloads) — here the
framework ships the plumbing.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional, Sequence

import numpy as np


def global_batch_iterator(local_batch_fn: Callable[[int], Sequence],
                          mesh, shardings,
                          steps: Optional[int] = None) -> Iterator:
    """Yield global sharded batches from per-process local batches.

    - local_batch_fn(step) -> tuple of np arrays for THIS process's share
      (shape [local_batch, ...]).
    - shardings: matching tuple of NamedShardings for the global arrays.

    Single-process: a plain device_put.  Multi-process: each host
    contributes its slice via jax.make_array_from_process_local_data, so
    no host ever materializes the global batch.
    """
    import jax

    step = 0
    while steps is None or step < steps:
        local = local_batch_fn(step)
        if jax.process_count() == 1:
            yield tuple(jax.device_put(arr, s)
                        for arr, s in zip(local, shardings))
        else:
            yield tuple(
                jax.make_array_from_process_local_data(s, np.asarray(arr))
                for arr, s in zip(local, shardings))
        step += 1


class _PrefetchDone:
    pass


class _PrefetchError:
    def __init__(self, exc: BaseException):
        self.exc = exc


class DevicePrefetcher:
    """Double-buffered background batch prefetch.

    Pulls up to ``depth`` batches ahead of the consumer on a daemon
    thread, so host-side batch assembly (and the ``device_put`` the
    source iterator or the optional ``shardings`` perform) overlaps the
    in-flight device step instead of serializing behind it.  Source
    exceptions propagate to the consumer at the position they occurred.

    >>> for batch in DevicePrefetcher(batches, depth=2): ...

    ``close()`` stops the background thread without draining the source
    (the train loop calls it on every exit path; the thread parks on a
    bounded queue otherwise).
    """

    def __init__(self, source, depth: int = 2, shardings=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = iter(source)
        self._shardings = shardings
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._thread = threading.Thread(
            target=self._run, name="batch-prefetch", daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        try:
            for item in self._source:
                if self._shardings is not None:
                    import jax
                    item = tuple(jax.device_put(arr, s) for arr, s
                                 in zip(item, self._shardings))
                if not self._put(item):
                    return
            self._put(_PrefetchDone())
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            self._put(_PrefetchError(exc))

    def __iter__(self):
        return self

    def __next__(self):
        if self._done or self._stop.is_set():
            raise StopIteration
        item = self._queue.get()
        if isinstance(item, _PrefetchDone):
            self._done = True
            raise StopIteration
        if isinstance(item, _PrefetchError):
            self._done = True
            raise item.exc
        return item

    def close(self) -> None:
        self._stop.set()
        # Unblock a producer parked on a full queue.
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5)


def synthetic_image_batches(batch_per_process: int, image_size: int = 224,
                            num_classes: int = 1000,
                            dtype=np.float32) -> Callable[[int], tuple]:
    """Deterministic synthetic ImageNet-style batches (benchmark parity
    with tf_cnn_benchmarks --data_name=synthetic)."""
    rng = np.random.RandomState(0)
    images = rng.randn(batch_per_process, image_size, image_size, 3) \
        .astype(dtype)
    labels = rng.randint(0, num_classes, size=(batch_per_process,))

    def fn(step: int):
        return images, labels

    return fn


def synthetic_token_batches(batch_per_process: int, seq_len: int,
                            vocab_size: int) -> Callable[[int], tuple]:
    """Deterministic synthetic LM token batches."""
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, vocab_size, size=(batch_per_process, seq_len))

    def fn(step: int):
        return (tokens,)

    return fn
