"""Sharded input pipeline helpers.

Multi-host JAX needs every process to feed its local shard of the global
batch; this module turns per-process numpy batches into global sharded
arrays.  The reference delegates data loading entirely to the workload
(tf_cnn_benchmarks' synthetic data, Horovod MNIST downloads) — here the
framework ships the plumbing.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, Sequence

import numpy as np


def global_batch_iterator(local_batch_fn: Callable[[int], Sequence],
                          mesh, shardings,
                          steps: Optional[int] = None) -> Iterator:
    """Yield global sharded batches from per-process local batches.

    - local_batch_fn(step) -> tuple of np arrays for THIS process's share
      (shape [local_batch, ...]).
    - shardings: matching tuple of NamedShardings for the global arrays.

    Single-process: a plain device_put.  Multi-process: each host
    contributes its slice via jax.make_array_from_process_local_data, so
    no host ever materializes the global batch.
    """
    import jax

    step = 0
    while steps is None or step < steps:
        local = local_batch_fn(step)
        if jax.process_count() == 1:
            yield tuple(jax.device_put(arr, s)
                        for arr, s in zip(local, shardings))
        else:
            yield tuple(
                jax.make_array_from_process_local_data(s, np.asarray(arr))
                for arr, s in zip(local, shardings))
        step += 1


def synthetic_image_batches(batch_per_process: int, image_size: int = 224,
                            num_classes: int = 1000,
                            dtype=np.float32) -> Callable[[int], tuple]:
    """Deterministic synthetic ImageNet-style batches (benchmark parity
    with tf_cnn_benchmarks --data_name=synthetic)."""
    rng = np.random.RandomState(0)
    images = rng.randn(batch_per_process, image_size, image_size, 3) \
        .astype(dtype)
    labels = rng.randint(0, num_classes, size=(batch_per_process,))

    def fn(step: int):
        return images, labels

    return fn


def synthetic_token_batches(batch_per_process: int, seq_len: int,
                            vocab_size: int) -> Callable[[int], tuple]:
    """Deterministic synthetic LM token batches."""
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, vocab_size, size=(batch_per_process, seq_len))

    def fn(step: int):
        return (tokens,)

    return fn
