"""Fault-plan spec: what to break, when, and for how long.

A plan is a list of `Fault`s ordered by their offset from scenario
start.  Plans are plain data — JSON round-trip is exact, so a recorded
fault log (`ChaosReport.export_jsonl`) can be turned back into a plan
and replayed (`FaultPlan.from_events`), which is how a failing
randomized soak becomes a deterministic regression test.

`randomized_plan(seed, ...)` derives a plan from a seed alone
(`random.Random(seed)`, no wall-clock anywhere), so the same seed always
yields the same plan on any machine.
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass, field
from typing import List, Optional


@dataclass
class Fault:
    """One scheduled fault.

    - ``at``: seconds after scenario start.
    - ``kind``: injector name (see `injectors.INJECTORS`).
    - ``target``: injector-specific selector — a "namespace/name" pod
      for pod faults, an "apiVersion Kind" for watch faults, empty for
      cluster-wide faults (the injector may then pick a target with the
      scenario RNG and record the choice in the event log).
    - ``duration``: seconds the fault stays active; the engine heals
      durable faults at ``at + duration``.  0 means instantaneous —
      for durable kinds (api_*) a 0-duration fault is healed at
      timeline end, before convergence is judged.
    - ``params``: injector-specific knobs (error code, probability,
      latency, signal, grace period...).
    """

    at: float
    kind: str
    target: str = ""
    duration: float = 0.0
    params: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "Fault":
        return cls(at=float(data["at"]), kind=data["kind"],
                   target=data.get("target", ""),
                   duration=float(data.get("duration", 0.0)),
                   params=dict(data.get("params", {})))


@dataclass
class FaultPlan:
    name: str
    faults: List[Fault] = field(default_factory=list)
    seed: Optional[int] = None

    def sorted_faults(self) -> List[Fault]:
        """Stable order the engine executes in: by offset, then by the
        plan's own ordering (stable sort) so ties are deterministic."""
        return sorted(self.faults, key=lambda f: f.at)

    # -- JSON round-trip ---------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "name": self.name, "seed": self.seed,
            "faults": [f.to_dict() for f in self.faults]}, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        data = json.loads(text)
        return cls(name=data["name"], seed=data.get("seed"),
                   faults=[Fault.from_dict(f) for f in data["faults"]])

    @classmethod
    def from_events(cls, events: List[dict], name: str = "replay",
                    seed: Optional[int] = None) -> "FaultPlan":
        """Rebuild a plan from a recorded fault/event log (the JSONL a
        `ChaosReport` exports): every ``inject`` event becomes a fault
        at its recorded plan offset, with the *resolved* target (so a
        random pick replays against the exact pod it hit)."""
        faults = []
        for ev in events:
            if ev.get("event") != "inject":
                continue
            faults.append(Fault(
                at=float(ev.get("at", 0.0)), kind=ev["kind"],
                target=ev.get("resolved_target") or ev.get("target", ""),
                duration=float(ev.get("duration", 0.0)),
                params=dict(ev.get("params", {}))))
        return cls(name=name, seed=seed, faults=faults)


# Kinds eligible for randomized soaks (instantaneous or self-healing;
# params chosen inside safe ranges by `randomized_plan`).
RANDOMIZABLE_KINDS = ("pod_kill", "pod_delete", "preempt", "watch_relist",
                      "api_error_burst", "api_latency", "api_partition",
                      "event_storm")

# Serving-fleet soaks add replica_kill (the injector no-ops with a
# logged "no-fleet" against systems without a fleet).  Kept out of the
# default tuple so existing seeds keep deriving the same plans.
FLEET_RANDOMIZABLE_KINDS = RANDOMIZABLE_KINDS + ("replica_kill",)

# Gang-scheduler soaks add spot_reclaim (yank a whole spot TPU slice;
# the injector no-ops with a logged "no-scheduler" against systems
# without a GangScheduler).  Same opt-in shape as the fleet tuple: the
# default tuple is untouched, so existing seeds replay identically.
SCHED_RANDOMIZABLE_KINDS = RANDOMIZABLE_KINDS + ("spot_reclaim",)

# The macro-soak's everything-on tuple (docs/RESILIENCE.md "Macro-soak
# & crash recovery"): every opt-in kind plus the control-plane restart
# injectors — including the apiserver itself (``apiserver_restart``,
# the durable-control-plane fault: WAL replay + watch-from-revision
# resume, docs/RESILIENCE.md "Durable apiserver") — plus
# ``gang_resize`` (negotiate an admitted elastic gang up or down
# through the live resize protocol; docs/SCHEDULING.md "Elastic
# gangs").  Only full-stack systems (soak harness: training gangs
# through queues + serving fleet + restartable control plane over a
# WAL-backed apiserver) exercise every member; the rest no-op with a
# logged reason.  The DEFAULT tuple stays untouched — recorded seeds
# keep deriving byte-identical plans (regression-tested in
# tests/test_soak.py).
FULL_RANDOMIZABLE_KINDS = RANDOMIZABLE_KINDS + (
    "replica_kill", "spot_reclaim", "controller_restart",
    "scheduler_restart", "apiserver_restart", "gang_resize",
    "blob_fault")

# Named presets for `randomized_plan(profile=...)`.
PLAN_PROFILES = {
    "default": RANDOMIZABLE_KINDS,
    "fleet": FLEET_RANDOMIZABLE_KINDS,
    "sched": SCHED_RANDOMIZABLE_KINDS,
    "full": FULL_RANDOMIZABLE_KINDS,
}


def randomized_plan(seed: int, n_faults: int = 8, horizon: float = 6.0,
                    kinds=RANDOMIZABLE_KINDS,
                    name: Optional[str] = None,
                    profile: Optional[str] = None) -> FaultPlan:
    """Derive a fault plan from a seed — same seed, same plan, always.

    Targets are left empty: the injectors resolve them against live
    cluster state with the scenario RNG and record the resolution in
    the event log, so a failing run replays via `FaultPlan.from_events`.

    ``profile`` names a kind preset (PLAN_PROFILES: "default", "fleet",
    "sched", "full") and overrides ``kinds`` when given — "full" is the
    macro-soak's documented everything-on tuple.
    """
    if profile is not None:
        kinds = PLAN_PROFILES[profile]
    rng = random.Random(seed)
    faults = []
    for _ in range(n_faults):
        kind = rng.choice(list(kinds))
        at = round(rng.uniform(0.2, horizon), 3)
        fault = Fault(at=at, kind=kind)
        if kind == "pod_kill":
            fault.params = {"signal": rng.choice([9, 15])}
        elif kind == "preempt":
            fault.params = {"grace": round(rng.uniform(0.2, 1.0), 3)}
        elif kind == "api_error_burst":
            fault.duration = round(rng.uniform(0.3, 1.5), 3)
            fault.params = {"code": rng.choice(["Unavailable", "Timeout"]),
                            "probability": round(rng.uniform(0.3, 1.0), 3)}
        elif kind == "api_latency":
            fault.duration = round(rng.uniform(0.3, 1.0), 3)
            fault.params = {"latency": round(rng.uniform(0.01, 0.1), 3)}
        elif kind == "api_partition":
            fault.duration = round(rng.uniform(0.2, 0.8), 3)
        elif kind == "watch_relist":
            fault.target = rng.choice(["v1 Pod", "batch/v1 Job",
                                       "kubeflow.org/v2beta1 MPIJob"])
        elif kind == "event_storm":
            # Shard-skew: a MODIFIED burst aimed at one job (target
            # resolved at inject time -> one workqueue shard).
            fault.params = {"rounds": rng.randint(1, 3)}
        elif kind == "replica_kill":
            # Target resolved at inject time against the live fleet's
            # Running serve replicas (empty target = RNG pick).
            fault.params = {}
        elif kind == "spot_reclaim":
            # Target resolved at inject time against the scheduler's
            # spot slices (empty target = RNG pick); duration > 0 heals
            # the slice back online, modelling spot capacity returning.
            fault.duration = round(rng.uniform(0.5, 2.0), 3)
            fault.params = {"grace": round(rng.uniform(0.2, 0.8), 3)}
        elif kind in ("controller_restart", "scheduler_restart"):
            # duration = the control-plane outage before the respawn;
            # the restarted loop rebuilds its state from the apiserver.
            fault.duration = round(rng.uniform(0.4, 1.5), 3)
        elif kind == "apiserver_restart":
            # duration = the apiserver outage before the WAL replay
            # respawns the store; every component rides it out on
            # retried verbs + resumed watches.
            fault.duration = round(rng.uniform(0.4, 1.2), 3)
        elif kind == "blob_fault":
            # Checkpoint blob-store weather (ckpt/blobstore.py): slowed
            # or failed uploads, or a torn manifest at the next commit.
            # The ckpt_manifest_consistent invariant counter-asserts
            # that whatever survives stays bit-stable restorable.
            mode = rng.choice(["slow", "fail", "torn"])
            fault.params = {"mode": mode,
                            "count": rng.randint(1, 3),
                            "delay": round(rng.uniform(0.01, 0.1), 3)}
        elif kind == "gang_resize":
            # Target gang + direction resolved at inject time against
            # the live admitted elastic gangs (the injector prefers
            # the drawn direction and flips at a bound); deadline =
            # the negotiation window before rollback/fallback-evict.
            fault.params = {
                "direction": rng.choice(["grow", "shrink"]),
                "deadline": round(rng.uniform(1.0, 3.0), 3)}
        faults.append(fault)
    return FaultPlan(name=name or f"randomized-{seed}", seed=seed,
                     faults=faults)
