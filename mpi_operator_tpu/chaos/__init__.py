"""Deterministic fault-injection for the hermetic simulation stack.

The reference operator's entire value proposition is surviving cluster
entropy — pod failure, watch-stream 410s, partial gangs, apiserver
brown-outs — yet incidental unit tests only ever exercise the faults
someone thought to hand-write.  This package drives the in-process
simulation stack (runtime/kubelet, k8s/apiserver + kube_transport,
controller, bootstrap) through *scripted and seeded-random* fault plans
while invariant checkers assert the system converges.

Three parts:

- ``plan``: the fault-plan spec (`Fault`, `FaultPlan`) with JSON
  round-trip (a recorded fault log replays as a plan) and deterministic
  seeded randomized-plan generation.
- ``injectors``: the injector registry — pod kill, preemption notice,
  watch-stream 410/relist, apiserver error/latency bursts, full
  control-plane partition — implemented against chaos hooks on the sim
  layers (`ApiServer.fault_injector`, `LocalKubelet.kill_pod` /
  `inject_preemption`, `ApiServer.relist_watches`).
- ``engine``: `ChaosEngine` / `run()` — executes a plan against a
  `LocalCluster`-shaped system with a seeded RNG, emits a JSONL
  fault/event log (wired into telemetry spans), waits for convergence
  and evaluates invariants (`invariants` module).

See docs/RESILIENCE.md for the fault taxonomy, the invariants, and the
seed-replay workflow.
"""

from .engine import ChaosEngine, ChaosReport, run  # noqa: F401
from .injectors import INJECTORS, register_injector  # noqa: F401
from .invariants import (DEFAULT_INVARIANTS, checkpoint_intact,  # noqa: F401
                         gang_restarts_bounded, jobs_converged,
                         no_leaked_pod_ips, no_orphaned_pods,
                         no_orphaned_runners, no_surplus_worker_pods,
                         sched_capacity_conserved, serve_requests_intact,
                         workqueue_idle)
from .plan import (Fault, FaultPlan, FLEET_RANDOMIZABLE_KINDS,  # noqa: F401
                   FULL_RANDOMIZABLE_KINDS, PLAN_PROFILES,
                   randomized_plan)
