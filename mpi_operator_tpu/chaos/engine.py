"""Scenario runner: execute a FaultPlan against a live system, log every
fault deterministically, wait for convergence, assert invariants.

Determinism contract: the *canonical* fault/event log (seq, kind,
resolved target, params, result — no wall timestamps) of a scripted
plan is identical across runs, and a randomized plan derives entirely
from its seed — so any failing soak replays as
``ChaosEngine(system, FaultPlan.from_events(report.events))``.

Telemetry: the run and each injection are traced as spans on the
default tracer (`chaos_run` / `chaos_fault`), so chaos activity lands
in the same JSONL/Chrome exports as reconcile and train-step spans
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from ..telemetry import flight
from ..telemetry.trace import span
from .injectors import INJECTORS, ApiFaultBank
from .invariants import DEFAULT_INVARIANTS
from .plan import FaultPlan

# Event-log fields that must reproduce across runs of the same plan;
# wall-clock fields (ts) are excluded by construction.
CANONICAL_FIELDS = ("seq", "event", "at", "kind", "target",
                    "resolved_target", "duration", "params", "result")


@dataclass
class ChaosReport:
    plan_name: str
    seed: Optional[int]
    events: List[dict] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)
    converged: bool = True
    elapsed: float = 0.0
    # Debug-bundle path attached by ChaosEngine.run on invariant
    # violation (or when bundle="always"); None when no bundle was cut.
    bundle_dir: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.converged and not self.violations

    def canonical_log(self) -> List[dict]:
        """The reproducible view of the event log (no timestamps)."""
        return [{k: ev[k] for k in CANONICAL_FIELDS if k in ev}
                for ev in self.events]

    def export_jsonl(self, path_or_file) -> int:
        """One JSON object per line: a header, then every event, then
        the verdict — the artifact a failing seed is replayed from."""
        if isinstance(path_or_file, (str, os.PathLike)):
            with open(path_or_file, "w") as f:
                return self.export_jsonl(f)
        header = {"event": "plan", "name": self.plan_name,
                  "seed": self.seed}
        path_or_file.write(json.dumps(header) + "\n")
        for ev in self.events:
            path_or_file.write(json.dumps(ev) + "\n")
        path_or_file.write(json.dumps(
            {"event": "verdict", "converged": self.converged,
             "violations": self.violations,
             "elapsed": round(self.elapsed, 3)}) + "\n")
        return len(self.events) + 2


class ChaosEngine:
    """Drives one plan against one system (LocalCluster-shaped).

    The engine installs an `ApiFaultBank` as the apiserver's fault
    injector for the scenario's lifetime; its own thread (and any
    thread registered via `exempt_thread`) bypasses injected faults so
    target resolution and invariant checks observe the true state.
    """

    def __init__(self, system, plan: FaultPlan,
                 seed: Optional[int] = None):
        self.system = system
        self.plan = plan
        self.seed = plan.seed if seed is None else seed
        self.rng = random.Random(self.seed)
        # The bank rolls probabilities from arbitrary client threads;
        # giving it its own stream keeps the engine's target picks
        # deterministic regardless of API-call interleaving.
        self.bank = ApiFaultBank(random.Random(
            0 if self.seed is None else self.seed ^ 0x5EED))
        self.events: List[dict] = []
        # Convergence predicates raced a transient state and raised;
        # counted (NOT logged — the canonical log must stay byte-stable
        # across identical seeded runs) so a flapping predicate is
        # visible to the harness.
        self.predicate_errors = 0
        self._seq = 0
        self._lock = threading.Lock()
        self._pending_result: Optional[dict] = None
        self._heals: dict = {}

    # -- event log ---------------------------------------------------------
    def _log(self, event: dict) -> dict:
        with self._lock:
            event["seq"] = self._seq
            self._seq += 1
            event["ts"] = round(time.time(), 6)
            self.events.append(event)
        # Mirror onto the flight ring (canonical fields only) so chaos
        # activity appears in every layer's black-box bundle, in the
        # same deterministic per-run order as the engine's own log.
        flight.record("chaos", event.get("event", "event"),
                      **{k: event[k] for k in CANONICAL_FIELDS
                         if k in event})
        return event

    def log_result(self, fault, resolved_target: str = "",
                   result: str = "") -> None:
        """Called by injectors to attach the resolved target and outcome
        to the inject event being logged."""
        if self._pending_result is not None:
            self._pending_result["resolved_target"] = resolved_target
            self._pending_result["result"] = result

    # -- execution ---------------------------------------------------------
    @property
    def server(self):
        return self.system.client.server

    def exempt_thread(self) -> None:
        self.bank.exempt_current_thread()

    def run(self, converge: Optional[Callable[[], bool]] = None,
            timeout: float = 30.0,
            invariants: Sequence[Callable] = DEFAULT_INVARIANTS,
            settle: float = 10.0,
            bundle: Optional[str] = "violation") -> ChaosReport:
        """``bundle`` controls black-box attachment: "violation"
        (default) dumps a debug bundle when any invariant fails or
        convergence times out, "always" dumps unconditionally (smoke
        runs want the artifact even when green), None/False never
        dumps.  The bundle's canonical event section is this report's
        ``canonical_log()`` — byte-identical across identical seeded
        runs."""
        report = ChaosReport(plan_name=self.plan.name, seed=self.seed)
        self.bank.exempt_current_thread()
        prior_injector = getattr(self.server, "fault_injector", None)
        supports_bank = hasattr(self.server, "fault_injector")
        if supports_bank:
            self.server.fault_injector = self.bank
        start = time.monotonic()
        try:
            with span("chaos_run", plan=self.plan.name, seed=self.seed):
                self._execute_timeline(start)
                report.converged = self._wait_converged(
                    converge, start, timeout)
                report.violations = self._check_invariants(
                    invariants, settle)
        finally:
            self.bank.clear()
            if supports_bank:
                self.server.fault_injector = prior_injector
            report.events = self.events
            report.elapsed = time.monotonic() - start
            if bundle == "always" or (bundle == "violation"
                                      and not report.ok):
                controller = getattr(self.system, "controller", None)
                metrics = getattr(controller, "metrics", None) or {}
                report.bundle_dir = flight.dump_bundle(
                    f"chaos-{self.plan.name}",
                    registry=metrics.get("registry"),
                    clientset=getattr(self.system, "client", None),
                    canonical_events=report.canonical_log())
        return report

    def _execute_timeline(self, start: float) -> None:
        # (offset, order, action): inject steps carry order 0, heals 1,
        # so a zero-duration burst still injects before it heals.
        timeline = []
        for fault in self.plan.sorted_faults():
            timeline.append((fault.at, 0, "inject", fault))
            if fault.duration > 0:
                timeline.append((fault.at + fault.duration, 1, "heal",
                                 fault))
        timeline.sort(key=lambda t: (t[0], t[1]))
        for offset, _, action, fault in timeline:
            delay = start + offset - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if action == "inject":
                self._apply(fault)
                continue
            heal = self._heals.pop(id(fault), None)
            if heal is not None:
                heal()
            self._log({"event": "heal", "at": fault.at + fault.duration,
                       "kind": fault.kind,
                       "target": fault.target})
        # Durable faults whose plan never scheduled a heal (duration
        # left at 0) heal at timeline end: convergence and invariants
        # judge the healed system, and the heal is in the log — a rule
        # silently leaking into teardown would time out convergence for
        # what the plan spec calls an instantaneous fault.
        for fault in self.plan.sorted_faults():
            heal = self._heals.pop(id(fault), None)
            if heal is None:
                continue
            heal()
            self._log({"event": "heal", "at": fault.at,
                       "kind": fault.kind, "target": fault.target})

    def _apply(self, fault) -> None:
        injector = INJECTORS.get(fault.kind)
        event = {"event": "inject", "at": fault.at, "kind": fault.kind,
                 "target": fault.target, "duration": fault.duration,
                 "params": dict(fault.params)}
        if injector is None:
            event["result"] = "unknown-kind"
            self._log(event)
            return
        self._pending_result = event
        try:
            with span("chaos_fault", kind=fault.kind,
                      target=fault.target):
                heal = injector(self, fault)
        except Exception as exc:
            event["result"] = f"injector-error: {exc}"
            heal = None
        finally:
            self._pending_result = None
        self._log(event)
        if heal is not None:
            self._heals[id(fault)] = heal

    def _wait_converged(self, converge, start: float,
                        timeout: float) -> bool:
        if converge is None:
            return True
        deadline = start + timeout
        while time.monotonic() < deadline:
            try:
                if converge():
                    self._log({"event": "converged", "at": None,
                               "kind": "", "target": "",
                               "result": "ok"})
                    return True
            except Exception:
                # Predicate raced a transient state; retry.  Counted,
                # never canonical-logged (byte-stable replay).
                self.predicate_errors += 1
            time.sleep(0.1)
        self._log({"event": "converged", "at": None, "kind": "",
                   "target": "", "result": "timeout"})
        return False

    def _check_invariants(self, invariants, settle: float) -> List[str]:
        """Poll failing invariants for the settle window (most are
        eventual); whatever still fails is a violation."""
        deadline = time.monotonic() + settle
        per: dict = {}
        while True:
            per = {}
            for check in invariants:
                try:
                    per[check.__name__] = check(self.system)
                except Exception as exc:
                    per[check.__name__] = [
                        f"invariant {check.__name__} errored: {exc}"]
            if not any(per.values()) or time.monotonic() >= deadline:
                break
            time.sleep(0.2)
        for check in invariants:
            self._log({"event": "invariant", "at": None,
                       "kind": check.__name__, "target": "",
                       "result": "violated" if per.get(check.__name__)
                       else "ok"})
        return [f for v in per.values() for f in v]


def run(plan: FaultPlan, system, converge=None, timeout: float = 30.0,
        invariants: Sequence[Callable] = DEFAULT_INVARIANTS,
        settle: float = 10.0, seed: Optional[int] = None,
        bundle: Optional[str] = "violation") -> ChaosReport:
    """One-call form: ``chaos.run(plan, system)``."""
    return ChaosEngine(system, plan, seed=seed).run(
        converge=converge, timeout=timeout, invariants=invariants,
        settle=settle, bundle=bundle)
