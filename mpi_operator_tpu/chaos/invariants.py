"""Invariant checkers — what must stay true no matter which faults fire.

Each checker is ``fn(system) -> list[str]`` (empty = holds).  ``system``
is LocalCluster-shaped: ``.client`` (Clientset), ``.kubelet``
(LocalKubelet or None), ``.controller`` (MPIJobController).  The engine
polls failing checkers for a settle window before declaring a violation
— most invariants are *eventual* (a deleted pod's runner takes a beat
to stop).
"""

from __future__ import annotations

import os
from typing import List

from ..api import constants
from ..k8s import core


def no_orphaned_runners(system) -> List[str]:
    """Every kubelet runner (subprocess) belongs to a live pod object;
    a runner without a pod is a leaked process."""
    if system.kubelet is None:
        return []
    live = {(p.metadata.namespace, p.metadata.name)
            for p in system.client.server.list("v1", "Pod")}
    with system.kubelet._lock:
        runners = list(system.kubelet._runners)
    return [f"kubelet runner {ns}/{name} has no live pod object"
            for (ns, name) in runners if (ns, name) not in live]


def no_leaked_pod_ips(system) -> List[str]:
    """netsim address claims are released when pods go away."""
    if system.kubelet is None:
        return []
    live = {(p.metadata.namespace, p.metadata.name)
            for p in system.client.server.list("v1", "Pod")}
    with system.kubelet._lock:
        claims = dict(system.kubelet._pod_ips)
    return [f"netsim address {ip} still claimed by dead pod {owner}"
            for ip, owner in claims.items() if owner not in live]


def no_orphaned_pods(system) -> List[str]:
    """Every controller-owned pod's owner still exists (GC keeps up);
    an orphan survives its owner only transiently."""
    out = []
    jobs = {j.metadata.uid for j in
            system.client.server.list("batch/v1", "Job")}
    mpi_jobs = {j.metadata.uid
                for j in system.client.server.list(
                    "kubeflow.org/v2beta1", "MPIJob")}
    known = jobs | mpi_jobs
    for pod in system.client.server.list("v1", "Pod"):
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind in ("Job", "MPIJob") \
                    and ref.uid not in known:
                out.append(
                    f"pod {pod.metadata.namespace}/{pod.metadata.name} "
                    f"orphaned: owner {ref.kind} uid {ref.uid} gone")
    return out


def gang_restarts_bounded(system) -> List[str]:
    """Gang restarts never exceed runPolicy.backoffLimit (the annotation
    counter the controller maintains for restartPolicy=ExitCode)."""
    out = []
    for job in system.client.server.list("kubeflow.org/v2beta1", "MPIJob"):
        limit = job.spec.run_policy.backoff_limit
        if limit is None:
            continue
        restarts = int((job.metadata.annotations or {}).get(
            constants.GANG_RESTART_COUNT_ANNOTATION, "0"))
        if restarts > limit:
            out.append(f"MPIJob {job.metadata.name}: {restarts} gang "
                       f"restarts > backoffLimit {limit}")
    return out


def jobs_converged(system) -> List[str]:
    """Every MPIJob reaches a terminal state (Succeeded/Failed) or is
    (back) Running — never wedged in between."""
    out = []
    # Queued (admission pending behind quota/capacity) is a legitimate
    # steady state for queue-managed jobs, not a wedge.
    settled = (constants.JOB_SUCCEEDED, constants.JOB_FAILED,
               constants.JOB_RUNNING, constants.JOB_SUSPENDED,
               constants.JOB_QUEUED)
    for job in system.client.server.list("kubeflow.org/v2beta1", "MPIJob"):
        conds = {c.type: c.status for c in job.status.conditions}
        if not any(conds.get(t) == core.CONDITION_TRUE for t in settled):
            out.append(f"MPIJob {job.metadata.name} neither terminal nor "
                       f"running (conditions: {conds})")
    return out


def workqueue_idle(system) -> List[str]:
    """The controller workqueue drains once the cluster is quiet."""
    depth = len(system.controller.queue)
    return [f"controller workqueue still holds {depth} keys"] \
        if depth else []


def serve_requests_intact(system) -> List[str]:
    """Serving-fleet delivery invariant (replica_kill scenarios): no
    request is ever lost — an in-flight request on a killed replica
    completes via exactly one retry on a healthy one, so the router's
    lost counter must stay 0 (retries are expected and separately
    counted)."""
    router = getattr(system, "router", None)
    if router is None:
        return []
    lost = router.telemetry["requests_lost_total"].value
    return [f"fleet router lost {int(lost)} request(s) "
            f"(retry contract broken)"] if lost else []


def sched_no_partial_gangs(system) -> List[str]:
    """Gang-scheduler admission invariant: a queue-managed MPIJob that
    is NOT admitted must hold no running worker pods — gangs place
    all-or-nothing, and an evicted/queued gang's members must be gone,
    never half-running.  No-ops for jobs without the queue label (and
    therefore for every system without a scheduler)."""
    from ..controller.builders import worker_selector
    from ..controller.status import get_condition
    from ..k8s.selectors import match_labels
    from ..sched.api import job_queue_name

    out = []
    gated = []
    for job in system.client.server.list("kubeflow.org/v2beta1", "MPIJob"):
        if not job_queue_name(job):
            continue
        cond = get_condition(job.status, constants.JOB_ADMITTED)
        if cond is None or cond.status != core.CONDITION_TRUE:
            gated.append(job)
    if not gated:
        return out
    pods = system.client.server.list("v1", "Pod")
    for job in gated:
        selector = worker_selector(job.metadata.name)
        running = [p for p in pods
                   if p.metadata.namespace == job.metadata.namespace
                   and match_labels(selector, p.metadata.labels)
                   and p.status.phase == core.POD_RUNNING]
        if running:
            out.append(
                f"MPIJob {job.metadata.namespace}/{job.metadata.name} is"
                f" not admitted but {len(running)} worker pod(s) run —"
                f" partial gang")
    return out


def sched_capacity_conserved(system) -> List[str]:
    """Gang-scheduler restart-recovery invariant: the scheduler's
    placement bookkeeping and the apiserver's Admitted conditions agree
    — a placement without an admitted record is leaked chips, an
    admitted record without a placement is a ghost gang, and an
    Admitted=True job the scheduler does not know was never adopted
    (double-admission risk: a second admission pass would place it
    again).  No-ops for systems without a scheduler."""
    scheduler = getattr(system, "scheduler", None)
    if scheduler is None:
        return []
    from ..controller.status import get_condition, is_finished
    from ..sched.api import job_queue_name

    out = []
    placed = set(scheduler.pool.placed_keys())
    admitted = set(scheduler.admitted_keys())
    for key in sorted(placed - admitted):
        out.append(f"chips leaked: slice placement for {key} has no"
                   f" admitted record")
    for key in sorted(admitted - placed):
        out.append(f"ghost gang: {key} admitted with no slice placement")
    # Elastic resize must conserve capacity THROUGH every transition:
    # the chips the scheduler accounts (quota/demand) and the chips the
    # pool actually holds for a gang move in lockstep — a grow that
    # placed chips without charging them (or a shrink that released
    # without crediting) is a quiet capacity leak.  One ATOMIC snapshot
    # (scheduler-lock-held): separate reads would race a committing
    # resize into spurious drift.
    snapshot = scheduler.capacity_snapshot()
    for key, entry in sorted(snapshot["gangs"].items()):
        if entry["held"] != entry["charged"]:
            out.append(
                f"resize accounting drift: {key} holds"
                f" {entry['held']} chips on the pool but the scheduler"
                f" charges {entry['charged']}")
    for job in system.client.server.list("kubeflow.org/v2beta1", "MPIJob"):
        if not job_queue_name(job) or is_finished(job.status) \
                or job.spec.run_policy.suspend:
            continue
        cond = get_condition(job.status, constants.JOB_ADMITTED)
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        if cond is not None and cond.status == core.CONDITION_TRUE \
                and key not in admitted:
            out.append(f"MPIJob {key} is Admitted=True but unknown to"
                       f" the scheduler (not adopted — double-admission"
                       f" risk)")
    return out


def resize_never_loses_a_step(system) -> List[str]:
    """Elastic-resize continuity invariant: a COMPLETED resize must
    never move a gang's step counter backwards — shrink drains the
    departing workers' shards and grow re-partitions from on-device
    state, so training continues from the same step (no checkpoint
    rewind).  Checked against the resizer's terminal log; step
    watermarks come from an embedder-registered ``step_probe``
    (smoke/bench wire one to the workers' step files) — entries
    without both watermarks no-op, as does every system without a
    scheduler."""
    scheduler = getattr(system, "scheduler", None)
    resizer = getattr(scheduler, "resizer", None)
    if resizer is None:
        return []
    out = []
    for rec in resizer.log:
        before, after = rec.get("step_before"), rec.get("step_after")
        if rec.get("outcome") != "completed" \
                or before is None or after is None:
            continue
        if after < before:
            out.append(
                f"resize lost steps: {rec['job']}"
                f" {rec['direction']} {rec['from_workers']}->"
                f"{rec['target']} stepped {before} -> {after}")
    return out


def no_surplus_worker_pods(system) -> List[str]:
    """Duplicate-create invariant (controller restart recovery): a job
    never accumulates more worker pods than its replica count, and
    never more than one launcher Job — the respawned controller must
    adopt its predecessor's objects, not re-create them."""
    from ..api.types import worker_replicas
    from ..controller.builders import launcher_name, worker_selector
    from ..k8s.selectors import match_labels

    out = []
    jobs = list(system.client.server.list("kubeflow.org/v2beta1", "MPIJob"))
    if not jobs:
        return out
    # One pass over the cluster-wide pod/launcher lists, bucketed by
    # (namespace, job-name label) — the per-job loop then only matches
    # selectors inside its own bucket (this runs in DEFAULT_INVARIANTS
    # on every settle poll; O(jobs x pods) would bite at bench scale).
    pods_by_job: dict = {}
    for p in system.client.server.list("v1", "Pod"):
        job_name = p.metadata.labels.get(constants.JOB_NAME_LABEL)
        if job_name:
            pods_by_job.setdefault(
                (p.metadata.namespace, job_name), []).append(p)
    launcher_count: dict = {}
    for j in system.client.server.list("batch/v1", "Job"):
        key = (j.metadata.namespace, j.metadata.name)
        launcher_count[key] = launcher_count.get(key, 0) + 1
    from ..sched.elastic import max_workers_seen
    for job in jobs:
        try:
            replicas = worker_replicas(job) or 0
        except (AttributeError, KeyError, TypeError, ValueError):
            continue  # malformed spec: demand math undefined, skip
        # Elastic gangs legitimately run more workers than the spec
        # count mid-grow: the bound is the largest effective size the
        # resize protocol ever granted, not the frozen spec.
        replicas = max(replicas, max_workers_seen(job))
        selector = worker_selector(job.metadata.name)
        bucket = pods_by_job.get(
            (job.metadata.namespace, job.metadata.name), ())
        owned = [p for p in bucket
                 if match_labels(selector, p.metadata.labels)]
        if len(owned) > replicas:
            out.append(
                f"MPIJob {job.metadata.namespace}/{job.metadata.name}:"
                f" {len(owned)} worker pods exceed {replicas} replicas"
                f" (duplicate creates)")
        launchers = launcher_count.get(
            (job.metadata.namespace, launcher_name(job)), 0)
        if launchers > 1:
            out.append(
                f"MPIJob {job.metadata.namespace}/{job.metadata.name}:"
                f" {launchers} launcher Jobs")
    return out


def ckpt_manifest_consistent(system) -> List[str]:
    """Checkpoint data plane (docs/RESILIENCE.md): for every job in the
    system's blob store, the latest readable manifest chain must be
    fully restorable — every chunk blob present and content-verified,
    and the reassembled stream exactly ``total_bytes`` long.  Torn or
    partially-uploaded checkpoints are expected casualties (readers
    never see them); a READABLE manifest that cannot restore bit-stable
    is the corruption this invariant exists to catch.  Vacuous against
    systems without a blob store."""
    store = getattr(system, "blobstore", None)
    if store is None:
        return []
    from ..ckpt.blobstore import BlobError
    from ..ckpt.manifest import effective_chunks, latest_restorable

    out = []
    for job in store.jobs():
        if not store.manifest_steps(job):
            continue  # only torn/uncommitted artifacts: nothing visible
        latest = latest_restorable(store, job)
        if latest is None:
            out.append(f"ckpt {job}: committed manifests exist but no"
                       f" chain is restorable")
            continue
        step, chain = latest
        head = chain[-1]
        view = effective_chunks(chain)
        total = 0
        for shard in range(head["num_shards"]):
            for idx, ref in sorted(view.get(shard, {}).items()):
                try:
                    data = store.get(ref["blob"])  # verifies content
                except BlobError as exc:
                    out.append(f"ckpt {job} step {step} shard {shard}"
                               f" chunk {idx}: {exc}")
                    continue
                if len(data) != ref["nbytes"]:
                    out.append(
                        f"ckpt {job} step {step} shard {shard} chunk"
                        f" {idx}: {len(data)} bytes != manifest"
                        f" {ref['nbytes']}")
                total += len(data)
        if total != head["total_bytes"]:
            out.append(f"ckpt {job} step {step}: reassembled {total}"
                       f" bytes != manifest total {head['total_bytes']}")
    return out


DEFAULT_INVARIANTS = (no_orphaned_runners, no_leaked_pod_ips,
                      no_orphaned_pods, gang_restarts_bounded,
                      jobs_converged, workqueue_idle,
                      serve_requests_intact, sched_no_partial_gangs,
                      sched_capacity_conserved,
                      resize_never_loses_a_step,
                      no_surplus_worker_pods, ckpt_manifest_consistent)


def checkpoint_intact(directory: str) -> List[str]:
    """Standalone checker for scenarios with checkpointing workloads:
    every retained step directory is non-empty (a torn save must never
    be left looking restorable — orbax writes are atomic-by-rename, so
    an empty or file-less step dir means corruption)."""
    from ..utils import checkpoint as ckpt

    out = []
    steps = ckpt.latest_steps(directory)
    if not steps:
        return [f"no checkpoint steps under {directory}"]
    for step in steps:
        step_dir = os.path.join(directory, f"step_{step:08d}")
        has_files = any(files for _, _, files in os.walk(step_dir))
        if not has_files:
            out.append(f"checkpoint step {step} is empty ({step_dir})")
    return out
