"""Fault injectors — the registry mapping `Fault.kind` to an action
against the simulation stack's chaos hooks.

An injector is ``fn(ctx, fault) -> Optional[heal]``: it applies the
fault through `ctx` (engine context: the system under test, the seeded
RNG, the apiserver fault bank, the event log) and returns a heal
callable when the fault is durable (the engine calls it at
``fault.at + fault.duration``).  Injectors RESOLVE loose targets (an
empty ``target`` means "pick one with the scenario RNG, from sorted
candidates") and record the resolution in the event log, so a recorded
run replays exactly (`FaultPlan.from_events`).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..api.constants import JOB_NAME_LABEL, JOB_ROLE_LABEL
from ..k8s.apiserver import TRANSPORT_ERRORS, ApiError

INJECTORS: Dict[str, Callable] = {}


def register_injector(name: str):
    def deco(fn):
        INJECTORS[name] = fn
        return fn
    return deco


# Verbs an error burst hits by default.  ``watch`` is deliberately
# excluded: in-process consumers open their streams once at startup and
# never re-dial, so failing the verb would wedge rather than exercise
# anything — stream loss is modelled by `relist_watches` instead.
DEFAULT_FAULT_VERBS = ("create", "get", "list", "update", "delete")


class ApiFaultBank:
    """The single `ApiServer.fault_injector` slot, multiplexed.

    Rules (error probability, latency) are added/removed by injectors;
    every apiserver verb consults the active set.  Calls from exempt
    threads (the chaos engine itself, invariant checkers) bypass the
    bank so the scenario's own observations are never faulted.
    """

    def __init__(self, rng):
        self._rules: dict = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._rng = rng
        self._exempt: set = set()

    def exempt_current_thread(self) -> None:
        self._exempt.add(threading.get_ident())

    def add_rule(self, verbs=DEFAULT_FAULT_VERBS, kinds=None,
                 code: Optional[str] = None, probability: float = 1.0,
                 latency: float = 0.0) -> int:
        with self._lock:
            rule_id = self._next_id
            self._next_id += 1
            self._rules[rule_id] = {
                "verbs": tuple(verbs), "kinds": tuple(kinds or ()),
                "code": code, "probability": float(probability),
                "latency": float(latency)}
            return rule_id

    def remove_rule(self, rule_id: int) -> None:
        with self._lock:
            self._rules.pop(rule_id, None)

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()

    def __call__(self, verb: str, api_version: str, kind: str,
                 namespace: str, name: str) -> None:
        if threading.get_ident() in self._exempt:
            return
        with self._lock:
            rules = list(self._rules.values())
        for rule in rules:
            if verb not in rule["verbs"]:
                continue
            if rule["kinds"] and kind not in rule["kinds"]:
                continue
            if rule["probability"] < 1.0:
                with self._lock:
                    roll = self._rng.random()
                if roll >= rule["probability"]:
                    continue
            if rule["latency"] > 0:
                time.sleep(rule["latency"])
            if rule["code"]:
                raise ApiError(rule["code"],
                               f"chaos: injected {rule['code']} on "
                               f"{verb} {kind} {namespace}/{name}")


def _resolve_pod(ctx, fault, running_only: bool = True) -> Optional[tuple]:
    """(namespace, name) for a pod fault: an explicit "ns/name" target,
    or an RNG pick over the sorted live candidates (workers preferred —
    they are the gang-repair surface; launchers only when nothing else
    runs)."""
    if fault.target:
        ns, _, name = fault.target.partition("/")
        return (ns, name) if name else ("default", ns)
    from ..k8s import core
    pods = [p for p in ctx.server.list("v1", "Pod")
            if not running_only or p.status.phase == core.POD_RUNNING]
    workers = [p for p in pods
               if p.metadata.labels.get(JOB_ROLE_LABEL) == "worker"]
    candidates = sorted(workers or pods,
                        key=lambda p: (p.metadata.namespace,
                                       p.metadata.name))
    if not candidates:
        return None
    pick = ctx.rng.choice(candidates)
    return (pick.metadata.namespace, pick.metadata.name)


def _wait_live_process(ctx, target, timeout: float) -> bool:
    """Block (bounded) until the target pod has a live container
    process.  Scripted plans use ``params["wait"]`` so a fault aimed at
    a pod that is being recreated (mid gang-restart) lands
    deterministically instead of racing the kubelet — the race would
    make the fault log's result field differ across runs."""
    deadline = time.monotonic() + timeout
    kubelet = ctx.system.kubelet
    while time.monotonic() < deadline:
        with kubelet._lock:
            runner = kubelet._runners.get(tuple(target))
        proc = runner.proc if runner is not None else None
        if proc is not None and proc.poll() is None:
            return True
        time.sleep(0.05)
    return False


@register_injector("pod_kill")
def inject_pod_kill(ctx, fault):
    """Kill the container process (node crash / OOM): the kubelet
    reflects a signal death (128+signum) and restart/gang policy takes
    over."""
    target = _resolve_pod(ctx, fault)
    if target is None:
        ctx.log_result(fault, resolved_target="", result="no-candidate")
        return None
    wait = float(fault.params.get("wait", 0))
    if wait > 0:
        _wait_live_process(ctx, target, wait)
    sig = int(fault.params.get("signal", 9))
    ok = ctx.system.kubelet.kill_pod(*target, sig=sig)
    ctx.log_result(fault, resolved_target="/".join(target),
                   result="killed" if ok else "no-process")
    return None


@register_injector("replica_kill")
def inject_replica_kill(ctx, fault):
    """Kill a serving-fleet replica (abrupt process death): the
    replica's batcher is poisoned so in-flight requests fail loudly,
    /healthz flips 503, the pod goes Failed — the fleet router must
    complete every in-flight request via EXACTLY one retry on a healthy
    replica (zero lost, zero duplicated streams; the
    serve_requests_intact invariant counter-asserts it) while the
    ServeJob controller replaces the replica."""
    fleet = getattr(ctx.system, "runner", None)
    if fleet is None or not hasattr(ctx.system, "kill_replica"):
        ctx.log_result(fault, resolved_target="", result="no-fleet")
        return None
    if fault.target:
        ns, _, name = fault.target.partition("/")
        target = (ns, name) if name else ("default", ns)
    else:
        from ..api import constants
        serve = [p for p in ctx.server.list("v1", "Pod")
                 if p.metadata.labels.get(constants.REPLICA_TYPE_LABEL)
                 == constants.REPLICA_TYPE_SERVE.lower()
                 and p.status.phase == "Running"]
        candidates = sorted(serve, key=lambda p: (p.metadata.namespace,
                                                  p.metadata.name))
        if not candidates:
            ctx.log_result(fault, resolved_target="",
                           result="no-candidate")
            return None
        pick = ctx.rng.choice(candidates)
        target = (pick.metadata.namespace, pick.metadata.name)
    ok = ctx.system.kill_replica(*target)
    ctx.log_result(fault, resolved_target="/".join(target),
                   result="killed" if ok else "no-replica")
    return None


@register_injector("spot_reclaim")
def inject_spot_reclaim(ctx, fault):
    """Yank a whole spot TPU slice from the gang scheduler's capacity
    pool: every gang holding chips on it gets the preemption notice,
    the checkpoint grace window, then eviction + requeue
    (sched/scheduler.py reclaim_slice).  A duration > 0 heals the
    slice back online at ``at + duration`` — spot capacity returning.
    No-ops (logged) against systems without a GangScheduler."""
    scheduler = getattr(ctx.system, "scheduler", None)
    if scheduler is None:
        ctx.log_result(fault, resolved_target="", result="no-scheduler")
        return None
    if fault.target:
        name = fault.target
    else:
        online = set(scheduler.pool.spot_slices()) \
            - set(scheduler.pool.offline_slices())
        candidates = sorted(online)
        if not candidates:
            ctx.log_result(fault, resolved_target="",
                           result="no-spot-slice")
            return None
        name = ctx.rng.choice(candidates)
    grace = fault.params.get("grace")
    victims = scheduler.reclaim_slice(
        name, grace=float(grace) if grace is not None else None)
    ctx.log_result(fault, resolved_target=name,
                   result=f"reclaimed victims={len(victims)}")

    def heal():
        scheduler.restore_slice(name)
    return heal


@register_injector("controller_restart")
def inject_controller_restart(ctx, fault):
    """Control-plane crash: kill the reconcile loops (MPIJob controller
    + batch Job controller) mid-flight and respawn them at heal time —
    ``duration`` is the control-plane outage.  A duration of 0 respawns
    at timeline end (before convergence is judged), like every durable
    fault.  The respawned controller has EMPTY in-memory state and must
    re-adopt pods/launchers from the apiserver without duplicate
    creates (server/cluster.py crash_controller/respawn_controller;
    no-ops, logged, against systems without the surface)."""
    crash = getattr(ctx.system, "crash_controller", None)
    respawn = getattr(ctx.system, "respawn_controller", None)
    if crash is None or respawn is None:
        ctx.log_result(fault, resolved_target="",
                       result="no-restartable-controller")
        return None
    crashed = crash()
    # crash() returns False when the controller is already down
    # (overlapping restart faults): log honestly — the scorecard
    # counts result=="crashed" as restarts actually applied.
    ctx.log_result(fault, resolved_target="controller",
                   result="crashed" if crashed else "already-down")

    def heal():
        respawn()
    return heal


@register_injector("scheduler_restart")
def inject_scheduler_restart(ctx, fault):
    """Gang-scheduler crash: admitted-set, quota usage, slice
    placements and the backfill reservation fence die with the process;
    the heal respawns a scheduler that must rebuild all of it from API
    object conditions/annotations (no double admission, no leaked
    chips, no partial gangs — sched/scheduler.py adoption/sweep paths).
    No-ops, logged, against systems without a GangScheduler."""
    crash = getattr(ctx.system, "crash_scheduler", None)
    respawn = getattr(ctx.system, "respawn_scheduler", None)
    if crash is None or respawn is None \
            or getattr(ctx.system, "scheduler", None) is None:
        ctx.log_result(fault, resolved_target="", result="no-scheduler")
        return None
    crashed = crash()
    ctx.log_result(fault, resolved_target="scheduler",
                   result="crashed" if crashed else "already-down")

    def heal():
        respawn()
    return heal


@register_injector("apiserver_restart")
def inject_apiserver_restart(ctx, fault):
    """Kill the apiserver ITSELF — the last single point of total state
    loss (docs/RESILIENCE.md "Durable apiserver").  Every verb fails
    Unavailable for ``duration``, the un-fsynced WAL tail is lost, and
    every watch stream is CLOSED; the heal replays snapshot + WAL back
    to the exact acknowledged revision and swaps the fresh store into
    the shared clientset — controller, scheduler, kubelet and fleet
    must all survive on resumed watches with zero acknowledged writes
    lost.  No-ops (logged) against systems without the surface or with
    a memory-only apiserver (nothing would survive to respawn)."""
    crash = getattr(ctx.system, "crash_apiserver", None)
    respawn = getattr(ctx.system, "respawn_apiserver", None)
    durable = getattr(ctx.system, "apiserver_durable", None)
    if crash is None or respawn is None:
        ctx.log_result(fault, resolved_target="",
                       result="no-restartable-apiserver")
        return None
    if durable is not None and not durable():
        ctx.log_result(fault, resolved_target="", result="no-wal")
        return None
    crashed = crash()
    ctx.log_result(fault, resolved_target="apiserver",
                   result="crashed" if crashed else "already-down")

    def heal():
        respawn()
    return heal


@register_injector("gang_resize")
def inject_gang_resize(ctx, fault):
    """Negotiate an admitted elastic gang up or down through the live
    resize protocol (sched/elastic.py): grow grants idle aligned
    blocks, shrink opens a drain window for the departing workers —
    either way training continues from the same step on the survivors
    (the ``resize_never_loses_a_step`` invariant watches).  The drawn
    direction flips at a bound (a gang at max grows nowhere), and the
    injector logs honestly when no scheduler / no elastic gang exists
    or the scheduler rejects the offer (e.g. no appendable
    capacity)."""
    scheduler = getattr(ctx.system, "scheduler", None)
    if scheduler is None:
        ctx.log_result(fault, resolved_target="", result="no-scheduler")
        return None
    from ..sched.elastic import elastic_bounds, settled_workers
    jobs = {f"{j.metadata.namespace}/{j.metadata.name}": j
            for j in ctx.server.list("kubeflow.org/v2beta1", "MPIJob")}
    candidates = []
    for key in scheduler.admitted_keys():
        job = jobs.get(key)
        if job is None or scheduler.resizer.in_flight(key):
            continue
        bounds = elastic_bounds(job)
        if bounds is None:
            continue
        candidates.append((key, job, bounds))
    if fault.target:
        candidates = [c for c in candidates if c[0] == fault.target]
    if not candidates:
        ctx.log_result(fault, resolved_target="",
                       result="no-elastic-gang")
        return None
    key, job, bounds = ctx.rng.choice(sorted(candidates,
                                             key=lambda c: c[0]))
    current = settled_workers(job)
    direction = fault.params.get("direction") or \
        ctx.rng.choice(["grow", "shrink"])
    # Flip at a bound so a drawn direction that cannot move still
    # exercises the protocol when the other one can.
    if direction == "grow" and current >= bounds[1]:
        direction = "shrink"
    elif direction == "shrink" and current <= bounds[0]:
        direction = "grow"
    target = current + 1 if direction == "grow" else current - 1
    if not bounds[0] <= target <= bounds[1]:
        # min == max bounds: no move exists ("no-" prefix keeps the
        # no-op out of the applied-faults accounting).
        ctx.log_result(fault, resolved_target=key,
                       result="no-move-at-bounds")
        return None
    raw_deadline = fault.params.get("deadline")
    deadline = float(raw_deadline) if raw_deadline is not None else None

    def offer(direction, target):
        accepted, msg = scheduler.request_resize(
            *key.split("/", 1), target, deadline=deadline,
            reason="chaos gang_resize")
        return accepted, msg

    accepted, msg = offer(direction, target)
    if not accepted:
        # Try the opposite direction once (a grow with no appendable
        # capacity can still shrink, and vice versa) — the soak's
        # resize SLO needs negotiated transitions, not coin-flip
        # no-ops.
        other = "shrink" if direction == "grow" else "grow"
        alt = current - 1 if other == "shrink" else current + 1
        if bounds[0] <= alt <= bounds[1]:
            flipped, msg2 = offer(other, alt)
            if flipped:
                ctx.log_result(
                    fault, resolved_target=key,
                    result=f"{other} {current}->{alt} accepted"
                           f" ({direction} rejected)")
                return None
            msg = f"{msg}; {other}: {msg2}"
    # A rejected offer changed nothing: the "no-" prefix keeps it out
    # of the applied-faults accounting (_fault_applied), like every
    # other injector no-op.
    result = (f"{direction} {current}->{target} accepted" if accepted
              else f"no-accept {direction} {current}->{target}: {msg}")
    ctx.log_result(fault, resolved_target=key, result=result)
    return None


@register_injector("pod_delete")
def inject_pod_delete(ctx, fault):
    """Delete the pod object through the API (eviction/drain analogue):
    exercises the controller's recreate path and the kubelet's DELETED
    handling."""
    target = _resolve_pod(ctx, fault)
    if target is None:
        ctx.log_result(fault, resolved_target="", result="no-candidate")
        return None
    try:
        ctx.system.client.pods(target[0]).delete(target[1])
        result = "deleted"
    except Exception as exc:
        result = f"error: {exc}"
    ctx.log_result(fault, resolved_target="/".join(target), result=result)
    return None


@register_injector("preempt")
def inject_preempt(ctx, fault):
    """Spot/preemption notice with a grace window: touch the pod's
    K_PREEMPTION_NOTICE_FILE, SIGTERM after ``grace`` seconds.
    Preemption-aware workloads checkpoint-then-exit inside the window
    (parallel/train.run_train_loop)."""
    target = _resolve_pod(ctx, fault)
    if target is None:
        ctx.log_result(fault, resolved_target="", result="no-candidate")
        return None
    wait = float(fault.params.get("wait", 0))
    if wait > 0:
        _wait_live_process(ctx, target, wait)
    grace = float(fault.params.get("grace", 1.0))
    ok = ctx.system.kubelet.inject_preemption(*target, grace=grace)
    ctx.log_result(fault, resolved_target="/".join(target),
                   result="noticed" if ok else "no-runner")
    return None


@register_injector("watch_relist")
def inject_watch_relist(ctx, fault):
    """Watch-stream continuity loss (disconnect + 410 Expired resume):
    every live stream on the kind receives the RELIST sentinel and must
    reconcile against a fresh list."""
    api_version = kind = None
    if fault.target:
        api_version, _, kind = fault.target.partition(" ")
    n = ctx.server.relist_watches(api_version or None, kind or None)
    # resolved_target mirrors the selector verbatim (empty = every
    # stream): FaultPlan.from_events copies it back into target, so a
    # replayed log must hit the same streams, not a '*' placeholder
    # that would parse as a (nonexistent) group-version.
    ctx.log_result(fault, resolved_target=fault.target,
                   result=f"signalled {n} streams")
    return None


@register_injector("event_storm")
def inject_event_storm(ctx, fault):
    """Shard-skew event storm: aim a burst of no-information MODIFIED
    events (status.message bumps) at ONE job's pods.  Because the
    controller routes keys by stable namespace/name hash, the whole
    storm lands on the single workqueue shard that owns the job — the
    skew case the priority/fairness layer must absorb without starving
    that shard's other jobs or tripping any invariant."""
    target_ns = target_name = None
    if fault.target:
        target_ns, _, target_name = fault.target.partition("/")
    else:
        jobs = sorted(ctx.server.list("kubeflow.org/v2beta1", "MPIJob"),
                      key=lambda j: (j.metadata.namespace, j.metadata.name))
        if not jobs:
            ctx.log_result(fault, resolved_target="", result="no-candidate")
            return None
        pick = ctx.rng.choice(jobs)
        target_ns = pick.metadata.namespace
        target_name = pick.metadata.name
    rounds = int(fault.params.get("rounds", 2))
    pods = [p for p in ctx.server.list("v1", "Pod", target_ns)
            if p.metadata.labels.get(JOB_NAME_LABEL) == target_name]
    client = ctx.system.client.pods(target_ns)
    bump = getattr(client, "patch_status", None)
    for r in range(rounds):
        for p in sorted(pods, key=lambda p: p.metadata.name):
            try:
                if bump is not None:
                    bump(p.metadata.name,
                         message=f"chaos-storm-{fault.at}-{r}")
                else:  # transport without PATCH: read-modify-write
                    live = client.get(p.metadata.name)
                    live.status.message = f"chaos-storm-{fault.at}-{r}"
                    client.update_status(live)
            except TRANSPORT_ERRORS:
                continue  # pod churned away mid-storm: storm on
    # Result stays count-free: pod membership during the storm races
    # gang repair, and the canonical log must replay byte-identically.
    ctx.log_result(fault, resolved_target=f"{target_ns}/{target_name}",
                   result=f"storm rounds={rounds}")
    return None


@register_injector("api_error_burst")
def inject_api_error_burst(ctx, fault):
    """Apiserver brown-out: verbs fail with an ApiError (default
    Unavailable) at ``probability`` until healed.  Controllers must
    requeue with backoff and converge after the heal."""
    rule = ctx.bank.add_rule(
        verbs=tuple(fault.params.get("verbs", DEFAULT_FAULT_VERBS)),
        kinds=tuple(fault.params.get("kinds", ())),
        code=fault.params.get("code", "Unavailable"),
        probability=float(fault.params.get("probability", 1.0)))
    ctx.log_result(fault, resolved_target="apiserver", result="burst-on")

    def heal():
        ctx.bank.remove_rule(rule)
    return heal


@register_injector("api_latency")
def inject_api_latency(ctx, fault):
    """Apiserver latency: every matching verb sleeps ``latency``
    seconds before serving (outside the store lock — only the caller
    stalls)."""
    rule = ctx.bank.add_rule(
        verbs=tuple(fault.params.get("verbs", DEFAULT_FAULT_VERBS)),
        kinds=tuple(fault.params.get("kinds", ())),
        code=None,
        latency=float(fault.params.get("latency", 0.05)))
    ctx.log_result(fault, resolved_target="apiserver", result="latency-on")

    def heal():
        ctx.bank.remove_rule(rule)
    return heal


@register_injector("blob_fault")
def inject_blob_fault(ctx, fault):
    """Checkpoint blob-store weather (docs/RESILIENCE.md "Checkpoint
    data plane"): arm rules on the system's blob store fault bank —
    ``slow`` uploads, ``fail``-ed uploads, or a ``torn`` manifest at
    the next job-level commit (the writer dies mid-commit and leaves
    truncated bytes at the final name).  Writers must retry or die
    loudly, and the ``ckpt_manifest_consistent`` invariant holds: a
    readable manifest always restores bit-stable.  No-ops (logged)
    against systems without a blob store."""
    store = getattr(ctx.system, "blobstore", None)
    if store is None:
        ctx.log_result(fault, resolved_target="", result="no-blobstore")
        return None
    mode = fault.params.get("mode", "slow")
    count = int(fault.params.get("count", 1))
    op = fault.params.get("op", "commit" if mode == "torn" else "put")
    store.faults.arm(op, mode, count=count,
                     delay=float(fault.params.get("delay", 0.05)))
    ctx.log_result(fault, resolved_target=f"blobstore:{op}",
                   result=f"armed-{mode} count={count}")

    def heal():
        store.faults.clear()
    return heal


@register_injector("api_partition")
def inject_api_partition(ctx, fault):
    """Full control-plane partition: every verb from every component
    fails until healed.  The system must hold state (no flapping to
    empty membership, no abandoned status writes) and reconverge."""
    rule = ctx.bank.add_rule(
        verbs=DEFAULT_FAULT_VERBS, code="Unavailable", probability=1.0)
    ctx.log_result(fault, resolved_target="apiserver", result="partitioned")

    def heal():
        ctx.bank.remove_rule(rule)
    return heal


@register_injector("slow_node")
def inject_slow_node(ctx, fault):
    """Gray failure: one worker runs at a duty-cycled fraction of full
    speed (degraded NIC, thermal throttle, noisy neighbor) with NO
    scheduler-visible symptom — the pod stays Running, heartbeats flow,
    only its step cadence sags.  Implemented by SIGSTOP/SIGCONT
    duty-cycling the container process from a shim thread: ``duty`` is
    the stopped fraction of each ``period`` (duty 0.66 ~= a 3x slower
    worker).  The only thing that should catch this is the metrics
    plane's straggler score — the scheduler, by design, is given
    nothing to mitigate with.

    Scripted-plan only: not in any randomized-kind tuple (randomized
    plan SHAs are pinned) and excluded from the converge predicate's
    concerns because the pod never leaves Running.
    """
    import signal

    target = _resolve_pod(ctx, fault)
    if target is None:
        ctx.log_result(fault, resolved_target="", result="no-candidate")
        return None
    wait = float(fault.params.get("wait", 0))
    if wait > 0:
        _wait_live_process(ctx, target, wait)
    kubelet = ctx.system.kubelet
    with kubelet._lock:
        runner = kubelet._runners.get(tuple(target))
    proc = runner.proc if runner is not None else None
    if proc is None or proc.poll() is not None:
        ctx.log_result(fault, resolved_target="/".join(target),
                       result="no-process")
        return None
    # The period must dominate the worker's step interval for the duty
    # cycle to translate into step-rate slowdown: a sleep-dominated
    # step loop rides out sub-interval stop windows for free (sleep
    # deadlines keep elapsing while stopped).
    duty = min(0.95, max(0.05, float(fault.params.get("duty", 0.66))))
    period = max(0.02, float(fault.params.get("period", 0.5)))
    healed = threading.Event()

    def shim():
        while not healed.is_set():
            try:
                if proc.poll() is not None:
                    return  # died (restart/kill): nothing left to slow
                proc.send_signal(signal.SIGSTOP)
                healed.wait(duty * period)
                proc.send_signal(signal.SIGCONT)
            except (OSError, ProcessLookupError):
                return
            healed.wait((1.0 - duty) * period)

    thread = threading.Thread(target=shim, daemon=True,
                              name=f"slow-node-{target[1]}")
    thread.start()
    ctx.log_result(fault, resolved_target="/".join(target),
                   result=f"throttled duty={duty}")

    def heal():
        healed.set()
        thread.join(timeout=2)
        try:
            if proc.poll() is None:
                proc.send_signal(signal.SIGCONT)
        except (OSError, ProcessLookupError):
            pass
    return heal
