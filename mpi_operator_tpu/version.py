"""Version stamp.

Parity with /root/reference/pkg/version/version.go:21-45 (ldflags-injected
Version/GitSHA/Built + PrintVersionAndExit); here populated at import from
the environment or git metadata when available.
"""

from __future__ import annotations

import os
import platform
import subprocess
import sys

VERSION = os.environ.get("MPI_OPERATOR_TPU_VERSION", "v0.1.0")


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5,
        ).stdout.strip() or "unknown"
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def info() -> dict:
    """version.Info equivalent."""
    return {
        "version": VERSION,
        "gitSHA": _git_sha(),
        "goVersion": f"python {platform.python_version()}",
        "platform": f"{platform.system().lower()}/{platform.machine()}",
    }


def print_version_and_exit() -> None:
    """PrintVersionAndExit (version.go:38-45)."""
    i = info()
    print(f"mpi-operator-tpu {i['version']} (git {i['gitSHA']},"
          f" {i['goVersion']}, {i['platform']})")
    sys.exit(0)
