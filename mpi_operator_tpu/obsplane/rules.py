"""Alert rules + engine over the time-series store.

The rule grammar is deliberately small (docs/OBSERVABILITY.md "Metrics
plane & alerting"):

- :class:`ThresholdRule` — one range evaluator (``last`` / ``rate`` /
  ``increase`` / ``quantile``) over one selector, compared against a
  bound; fires per offending series, carrying that series' labels.
- :class:`BurnRateRule` — multiwindow SLO burn rate: the error ratio
  (from a latency histogram's over-objective fraction, or a gauge's
  distance from target) averaged over a FAST and a SLOW window, both
  divided by the error budget; fires only when both burn factors
  exceed their thresholds — the classic fast-burn page that a brief
  blip cannot trip and a slow leak cannot hide from.
- :class:`AbsentRule` — a feed that should exist does not.
- :class:`StragglerRule` — a ThresholdRule over
  ``mpi_operator_straggler_score`` in its flagship costume.

Every rule names its ``metric`` as a string literal — the
`metrics-catalog` lint rule (analysis/lint.py) cross-checks each
reference against the documented catalog and the registered families,
both directions, so a rule cannot silently watch a series that will
never exist.

The :class:`AlertEngine` runs rules on the scrape cadence with
pending->firing promotion after ``for_s`` of sustained violation and
resolution when the condition clears.  Alert history is recorded with
engine timestamps; :meth:`AlertEngine.canonical_history` is the
timestamp-free, (alert, labels)-sorted view that flight bundles embed
and run-twice smoke tests byte-compare.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from .store import TimeSeriesStore


@dataclass
class Alert:
    """One (rule, labels) incident."""
    name: str
    labels: Dict[str, str]
    severity: str = "warning"
    state: str = "pending"        # pending | firing | resolved
    since: float = 0.0            # first violating evaluation
    fired_at: Optional[float] = None
    resolved_at: Optional[float] = None
    value: Optional[float] = None  # the offending evaluation value

    def key(self) -> tuple:
        return (self.name, tuple(sorted(self.labels.items())))

    def to_dict(self) -> dict:
        return {
            "alert": self.name,
            "labels": dict(sorted(self.labels.items())),
            "severity": self.severity,
            "state": self.state,
            "since": round(self.since, 4),
            "fired_at": (round(self.fired_at, 4)
                         if self.fired_at is not None else None),
            "resolved_at": (round(self.resolved_at, 4)
                            if self.resolved_at is not None else None),
            "value": (round(self.value, 6)
                      if isinstance(self.value, float) else self.value),
        }


class Rule:
    """Base: ``evaluate(store, t) -> [(labels, value)]`` listing every
    series violating right now."""

    def __init__(self, name: str, metric: str, severity: str = "warning",
                 for_s: float = 0.0):
        self.name = name
        self.metric = metric
        self.severity = severity
        self.for_s = float(for_s)

    def evaluate(self, store: TimeSeriesStore, t: float) -> List[tuple]:
        raise NotImplementedError

    def describe(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "severity": self.severity, "for_s": self.for_s,
                "kind": type(self).__name__}


class ThresholdRule(Rule):
    """``mode`` over ``selector`` compared against ``above``/``below``
    (at least one required).  Modes: ``last`` (newest sample),
    ``rate`` / ``increase`` (counter windows), ``quantile`` (gauge or
    histogram windows, with ``q``)."""

    def __init__(self, name: str, metric: str, selector: Optional[str]
                 = None, mode: str = "last", window: float = 60.0,
                 q: float = 0.99, above: Optional[float] = None,
                 below: Optional[float] = None, **kwargs):
        super().__init__(name, metric, **kwargs)
        if above is None and below is None:
            raise ValueError(f"rule {name}: need above= or below=")
        if mode not in ("last", "rate", "increase", "quantile"):
            raise ValueError(f"rule {name}: unknown mode {mode!r}")
        self.selector = selector or metric
        self.mode = mode
        self.window = float(window)
        self.q = q
        self.above = above
        self.below = below

    def _offending(self, value: float) -> bool:
        if self.above is not None and value > self.above:
            return True
        if self.below is not None and value < self.below:
            return True
        return False

    def evaluate(self, store: TimeSeriesStore, t: float) -> List[tuple]:
        if self.mode == "last":
            # The window doubles as a staleness bound: a series whose
            # feed stopped (worker departed, store still retains it)
            # must stop alerting, not freeze at its last bad value.
            rows = [(labels, v) for labels, ts, v
                    in store.latest(self.selector)
                    if isinstance(v, (int, float))
                    and ts > t - self.window]
        elif self.mode == "rate":
            rows = store.rate(self.selector, self.window, t)
        elif self.mode == "increase":
            rows = store.increase(self.selector, self.window, t)
        else:
            rows = store.quantile_over_time(self.selector, self.q,
                                            self.window, t)
        return [(labels, v) for labels, v in rows
                if self._offending(v)]


class BurnRateRule(Rule):
    """Multiwindow SLO burn rate.

    For a histogram series: error ratio = fraction of windowed
    observations above ``objective_le`` (a real bucket bound).  For a
    gauge series (``gauge_target`` given): error ratio = how far below
    target the windowed mean sits, as a fraction of target.  Budget =
    1 - objective (e.g. objective 0.99 -> 1% budget).  Fires when
    fast-window burn >= ``fast_burn`` AND slow-window burn >=
    ``slow_burn``.
    """

    def __init__(self, name: str, metric: str, objective: float,
                 selector: Optional[str] = None,
                 objective_le: Optional[float] = None,
                 gauge_target: Optional[float] = None,
                 fast_window: float = 60.0, slow_window: float = 300.0,
                 fast_burn: float = 14.0, slow_burn: float = 6.0,
                 **kwargs):
        super().__init__(name, metric, **kwargs)
        if (objective_le is None) == (gauge_target is None):
            raise ValueError(f"rule {name}: exactly one of objective_le"
                             f" (histogram) or gauge_target (gauge)")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"rule {name}: objective in (0, 1)")
        self.selector = selector or metric
        self.objective = objective
        self.objective_le = objective_le
        self.gauge_target = gauge_target
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)

    def _error_ratios(self, store: TimeSeriesStore, window: float,
                      t: float) -> Dict[tuple, float]:
        if self.objective_le is not None:
            rows = store.histogram_error_ratio(
                self.selector, self.objective_le, window, t)
        else:
            rows = [(labels,
                     max(0.0, (self.gauge_target - mean)
                         / self.gauge_target))
                    for labels, mean in store.avg_over_time(
                        self.selector, window, t)
                    if self.gauge_target > 0]
        return {tuple(sorted(labels.items())): (labels, ratio)
                for labels, ratio in rows}

    def evaluate(self, store: TimeSeriesStore, t: float) -> List[tuple]:
        budget = 1.0 - self.objective
        fast = self._error_ratios(store, self.fast_window, t)
        slow = self._error_ratios(store, self.slow_window, t)
        out = []
        for key, (labels, fast_ratio) in fast.items():
            if key not in slow:
                continue
            fast_factor = fast_ratio / budget
            slow_factor = slow[key][1] / budget
            if fast_factor >= self.fast_burn \
                    and slow_factor >= self.slow_burn:
                out.append((labels, fast_factor))
        return out


class AbsentRule(Rule):
    """Fires when no matching series holds any retained sample."""

    def __init__(self, name: str, metric: str,
                 selector: Optional[str] = None, **kwargs):
        super().__init__(name, metric, **kwargs)
        self.selector = selector or metric

    def evaluate(self, store: TimeSeriesStore, t: float) -> List[tuple]:
        if store.absent(self.selector):
            return [({"selector": self.selector}, 1.0)]
        return []


class StallRule(Rule):
    """Activity without completion: the ``activity_metric`` counter
    advanced by at least ``min_activity`` over the window while the
    watched ``metric`` counter did not move at all.  The WAL fsync
    stall is the canonical instance — appends keep arriving, fsyncs
    stop, and durability silently evaporates."""

    def __init__(self, name: str, metric: str, activity_metric: str,
                 window: float = 60.0, min_activity: float = 1.0,
                 **kwargs):
        super().__init__(name, metric, **kwargs)
        self.activity_metric = activity_metric
        self.window = float(window)
        self.min_activity = float(min_activity)

    def evaluate(self, store: TimeSeriesStore, t: float) -> List[tuple]:
        active = [(labels, inc) for labels, inc
                  in store.increase(self.activity_metric, self.window, t)
                  if inc >= self.min_activity]
        if not active:
            return []
        stalled = {tuple(sorted(labels.items())): inc for labels, inc
                   in store.increase(self.metric, self.window, t)}
        out = []
        for labels, activity in active:
            key = tuple(sorted(labels.items()))
            if stalled.get(key, 0.0) <= 0.0:
                out.append((labels, activity))
        return out


class StragglerRule(ThresholdRule):
    """The flagship consumer's rule: a worker whose straggler score
    (its rolling mean step time over the gang's rolling median,
    obsplane/straggler.py) sustains above ``threshold`` is paced by
    something — NIC, thermal, noisy neighbor — that per-job metrics
    cannot see."""

    def __init__(self, name: str = "StragglerAlert",
                 metric: str = "mpi_operator_straggler_score",
                 threshold: float = 1.8, **kwargs):
        kwargs.setdefault("severity", "critical")
        super().__init__(name, metric, mode="last", above=threshold,
                         **kwargs)


class AlertEngine:
    """Evaluates rules on the scrape cadence; owns alert lifecycle and
    history.  Thread-safe: the scrape thread evaluates while the CLI /
    harness reads."""

    def __init__(self, store: TimeSeriesStore, rules: List[Rule],
                 registry=None):
        self.store = store
        self.rules = list(rules)
        self._alerts: Dict[tuple, Alert] = {}
        self._history: List[dict] = []
        self._lock = threading.Lock()
        self._fired_counter = None
        if registry is not None:
            self._fired_counter = registry.counter_vec(
                "mpi_operator_obsplane_alerts_total",
                "Alert firing transitions (pending->firing), by alert"
                " rule name", ["alert"])

    def evaluate(self, t: float) -> List[Alert]:
        """Run every rule at logical time ``t``; returns alerts that
        TRANSITIONED to firing this evaluation."""
        fired: List[Alert] = []
        with self._lock:
            for rule in self.rules:
                violating = rule.evaluate(self.store, t)
                seen = set()
                for labels, value in violating:
                    alert = Alert(rule.name, dict(labels),
                                  severity=rule.severity, since=t,
                                  value=value)
                    key = alert.key()
                    seen.add(key)
                    live = self._alerts.get(key)
                    if live is None or live.state == "resolved":
                        self._alerts[key] = live = alert
                    live.value = value
                    if live.state == "pending" \
                            and t - live.since >= rule.for_s:
                        live.state = "firing"
                        live.fired_at = t
                        fired.append(live)
                        self._history.append(
                            {"event": "firing", **live.to_dict(),
                             "t": round(t, 4)})
                        if self._fired_counter is not None:
                            self._fired_counter.labels(rule.name).inc()
                for key, live in list(self._alerts.items()):
                    if live.name != rule.name or key in seen \
                            or live.state == "resolved":
                        continue
                    if live.state == "firing":
                        live.state = "resolved"
                        live.resolved_at = t
                        self._history.append(
                            {"event": "resolved", **live.to_dict(),
                             "t": round(t, 4)})
                    else:
                        del self._alerts[key]  # pending blip cleared
        return fired

    # -- views ---------------------------------------------------------------
    def active(self) -> List[Alert]:
        with self._lock:
            return sorted((a for a in self._alerts.values()
                           if a.state == "firing"),
                          key=lambda a: a.key())

    def all_alerts(self) -> List[Alert]:
        with self._lock:
            return sorted(self._alerts.values(), key=lambda a: a.key())

    def history(self) -> List[dict]:
        with self._lock:
            return list(self._history)

    def firings(self) -> List[dict]:
        """Every firing transition, chronological, with timestamps —
        the alert-fidelity scorer's feed."""
        return [h for h in self.history() if h["event"] == "firing"]

    def canonical_history(self) -> List[dict]:
        """Timestamp-free, deduplicated, (alert, labels)-sorted: the
        set of incidents that ever fired.  Two identical seeded runs
        produce byte-identical JSON of this view even when their wall
        timings differ."""
        seen = {}
        for h in self.history():
            if h["event"] != "firing":
                continue
            key = (h["alert"], tuple(sorted(h["labels"].items())))
            seen[key] = {"alert": h["alert"],
                         "labels": dict(sorted(h["labels"].items())),
                         "severity": h["severity"]}
        return [seen[k] for k in sorted(seen)]

    def canonical_history_json(self) -> str:
        return json.dumps(self.canonical_history(), indent=2,
                          sort_keys=True) + "\n"
