"""The fleet's stock rule set and the alert-fidelity scorer.

`default_fleet_rules` is the one place the operator's alerting policy
lives: SLO burn-rate rules over the soak targets (goodput, serve TTFT)
plus the structural rules that catch control-plane pathologies the SLO
windows are too slow for (watch resume storms, WAL fsync stalls,
queue-wait growth, gang disruption).  Every metric reference here is a
string literal so the `metrics-catalog` lint rule can hold it against
the documented catalog.

`FIDELITY_MAP` + `score_alert_fidelity` close the loop: for each chaos
fault class we can solidly map to an alert, an injected fault MUST
raise one of its mapped alerts within the deadline — that is the
soak scorecard's alert-fidelity section and BENCH_OBSPLANE's gate.
Fault kinds with no solid mapping (e.g. `blob_fault`, absorbed by
checkpoint retries by design) are reported as unmapped, not silently
counted as covered.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .rules import (AbsentRule, BurnRateRule, Rule, StallRule,
                    StragglerRule, ThresholdRule)

__all__ = ["default_fleet_rules", "FIDELITY_MAP",
           "score_alert_fidelity"]


def default_fleet_rules(window: float = 30.0,
                        slow_window: float = 120.0,
                        for_s: float = 0.0,
                        straggler_threshold: float = 1.8,
                        queue_wait_p99: float = 2.0,
                        ttft_objective_le: float = 2.5,
                        goodput_target: float = 0.7,
                        watchdog_selector: Optional[str] = None
                        ) -> List[Rule]:
    """The stock rule set the soak harness and smoke arm.

    ``window``/``slow_window`` are the fast/slow burn windows — soak
    runs are short, so defaults are tighter than a production 5m/1h
    pair; the grammar is identical.  ``watchdog_selector`` optionally
    adds an AbsentRule for a feed that must exist (e.g. the worker
    step counters once a job is running).
    """
    rules: List[Rule] = [
        # Flagship: per-worker straggler score (obsplane/straggler.py).
        StragglerRule(threshold=straggler_threshold, for_s=for_s),
        # Control-plane restarts, from the soak watchdog's recovery
        # counter — one rule per component so the fidelity map can
        # hold each fault class to its own alert.
        ThresholdRule(
            "ControllerRestart",
            metric="mpi_operator_soak_recoveries_total",
            selector='mpi_operator_soak_recoveries_total'
                     '{component="controller"}',
            mode="increase", window=window, above=0.0, for_s=for_s),
        ThresholdRule(
            "SchedulerRestart",
            metric="mpi_operator_soak_recoveries_total",
            selector='mpi_operator_soak_recoveries_total'
                     '{component="scheduler"}',
            mode="increase", window=window, above=0.0, for_s=for_s),
        ThresholdRule(
            "ApiserverRestart",
            metric="mpi_operator_soak_recoveries_total",
            selector='mpi_operator_soak_recoveries_total'
                     '{component="apiserver"}',
            mode="increase", window=window, above=0.0, for_s=for_s),
        # Structural: informers re-listing in a loop — apiserver churn
        # or a compaction horizon chasing the watchers.
        ThresholdRule(
            "WatchResumeStorm",
            metric="mpi_operator_informer_watch_resumes_total",
            mode="increase", window=window, above=2.0, for_s=for_s),
        # Structural: WAL appends advancing while fsyncs do not.
        StallRule(
            "WalFsyncStall",
            metric="mpi_operator_wal_fsyncs_total",
            activity_metric="mpi_operator_wal_appends_total",
            window=window, min_activity=5.0, for_s=for_s,
            severity="critical"),
        # Structural: admission queue wait growing — capacity crunch
        # or a scheduler stall, visible before jobs actually miss SLO.
        ThresholdRule(
            "QueueWaitGrowth",
            metric="mpi_operator_workqueue_wait_seconds",
            mode="quantile", q=0.99, window=slow_window,
            above=queue_wait_p99, for_s=for_s),
        # Gang disruption: worker death / preemption restarted a gang.
        ThresholdRule(
            "GangDisruption",
            metric="mpi_operator_gang_restarts_total",
            mode="increase", window=window, above=0.0, for_s=for_s),
        # Serving: router retries mean replicas are failing requests.
        ThresholdRule(
            "ServeRetryBurst",
            metric="mpi_operator_router_retries_total",
            mode="increase", window=window, above=0.0, for_s=for_s),
        # SLO burn: TTFT objective (fraction of requests over the
        # objective bucket bound, multiwindow).
        BurnRateRule(
            "ServeTtftBurnRate",
            metric="mpi_operator_router_ttft_seconds",
            objective=0.99, objective_le=ttft_objective_le,
            fast_window=window, slow_window=slow_window,
            severity="critical"),
        # SLO burn: training goodput sagging below target.  Gauge
        # error ratio saturates at 1.0, so burn thresholds are small
        # multiples, not the 14x/6x of the histogram path.
        BurnRateRule(
            "GoodputBurnRate",
            metric="train_goodput_fraction",
            objective=0.9, gauge_target=goodput_target,
            fast_window=window, slow_window=slow_window,
            fast_burn=2.0, slow_burn=1.0, severity="critical"),
    ]
    if watchdog_selector:
        rules.append(AbsentRule(
            "FeedAbsent", metric=watchdog_selector.split("{")[0],
            selector=watchdog_selector, for_s=for_s))
    return rules


# Chaos fault kind -> alert names that count as detecting it.  Only
# kinds with a SOLID mapping appear; anything else is reported as
# unmapped by score_alert_fidelity (an honest gap, not a silent pass).
FIDELITY_MAP: Dict[str, tuple] = {
    "controller_restart": ("ControllerRestart",),
    "scheduler_restart": ("SchedulerRestart",),
    "apiserver_restart": ("ApiserverRestart", "WatchResumeStorm"),
    "pod_kill": ("GangDisruption",),
    "pod_delete": ("GangDisruption",),
    "preempt": ("GangDisruption",),
    "replica_kill": ("ServeRetryBurst",),
    "slow_node": ("StragglerAlert",),
}

# Results that mean the injector did NOT actually apply the fault
# (mirrors the soak harness's applied-fault accounting).
_SKIP_RESULT_PREFIXES = ("no-", "already-", "error", "unknown-kind")


def _applied(event: dict) -> bool:
    if event.get("event") != "inject":
        return False
    result = str(event.get("result", ""))
    return not result.startswith(_SKIP_RESULT_PREFIXES)


def score_alert_fidelity(events: List[dict], firings: List[dict],
                         t0: float, deadline_s: float = 30.0) -> dict:
    """Hold a chaos run's applied faults against the alert firings.

    ``events`` is the chaos report's event log (plan offsets in
    ``at``); ``firings`` is AlertEngine.firings() (engine clock in
    ``t``); ``t0`` is the engine-clock time the chaos scenario
    started, aligning the two timelines.
    """
    first_inject: Dict[str, float] = {}
    unmapped: set = set()
    for ev in events:
        if not _applied(ev):
            continue
        kind = ev.get("kind", "")
        if kind not in FIDELITY_MAP:
            unmapped.add(kind)
            continue
        at = t0 + float(ev.get("at") or 0.0)
        if kind not in first_inject or at < first_inject[kind]:
            first_inject[kind] = at
    per_kind = {}
    for kind, injected_at in sorted(first_inject.items()):
        expected = FIDELITY_MAP[kind]
        detected = [f["t"] for f in firings
                    if f["alert"] in expected and f["t"] >= injected_at]
        detected_at = min(detected) if detected else None
        ttd = (detected_at - injected_at
               if detected_at is not None else None)
        per_kind[kind] = {
            "expected": list(expected),
            "injected_at": round(injected_at, 3),
            "detected_at": (round(detected_at, 3)
                            if detected_at is not None else None),
            "time_to_detect_s": (round(ttd, 3)
                                 if ttd is not None else None),
            "ok": ttd is not None and ttd <= deadline_s,
        }
    return {
        "deadline_s": deadline_s,
        "per_kind": per_kind,
        "unmapped_kinds": sorted(unmapped),
        "mapped_kinds_injected": len(per_kind),
        "ok": all(v["ok"] for v in per_kind.values()),
    }
