"""The metrics-plane scraper: registries + sidecars -> TimeSeriesStore.

One `Scraper` samples every configured source on a cadence and feeds
the time-series store (obsplane/store.py).  Sources:

- **in-process registries** (controller, scheduler, apiserver, kubelet,
  router, batcher, soak) via the structured ``Registry.collect()``
  snapshot — no exposition-text round trip for local state;
- **text sources** — a zero-arg callable returning a Prometheus text
  exposition (a remote ``/metrics`` fetch, a worker's exported
  ``metrics-*.prom`` sidecar next to its flight ring) parsed by
  :func:`parse_exposition`, histogram families reassembled from their
  ``_bucket``/``_sum``/``_count`` lines;
- **step-file probes** (:meth:`Scraper.add_step_dir`) — the soak
  workers' persisted ``step-<pod>`` counters, published as
  ``mpi_operator_worker_steps_total{job,worker}`` so the straggler
  scorer can derive per-step latency from progress deltas even for
  workers that emit no spans.

Timestamps come from the injectable ``clock``; ``scrape_once(t=...)``
lets a simulated feed drive the plane with logical time.  The scraper
meters itself (scrapes, duration, live series) into a registry it is
also scraping — the plane observes its own overhead.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .store import TimeSeriesStore

_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>[^\s]+)$")
_LABEL = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)='
                    r'"(?P<v>(?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str) -> List[tuple]:
    """Prometheus text -> ``[(name, kind, labels_dict, sample)]``.

    Scalar families yield one float sample per labeled series.
    Histogram families are reassembled into cumulative snapshot dicts
    (one per label set, ``le`` stripped) so the store's windowed
    quantile math works on scraped text exactly as on collected
    registries.  ``+Inf`` buckets are folded into ``count``.
    """
    kinds: Dict[str, str] = {}
    scalars: List[tuple] = []
    # (family, labels-sans-le as sorted tuple) -> snapshot parts
    hists: Dict[tuple, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        m = _SAMPLE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        name = m.group("name")
        labels = {lm.group("k"): _unescape(lm.group("v"))
                  for lm in _LABEL.finditer(m.group("labels") or "")}
        family = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) \
                    and kinds.get(name[: -len(suffix)]) == "histogram":
                family = name[: -len(suffix)]
                part = suffix[1:]
                break
        if family is None:
            scalars.append((name, kinds.get(name, "untyped"),
                            labels, value))
            continue
        le = labels.pop("le", None)
        key = (family, tuple(sorted(labels.items())))
        snap = hists.setdefault(key, {"buckets": {}, "sum": 0.0,
                                      "count": 0, "labels": labels})
        if part == "bucket":
            if le is not None and le not in ("+Inf", "inf"):
                snap["buckets"][float(le)] = int(value)
        elif part == "sum":
            snap["sum"] = value
        else:
            snap["count"] = int(value)
    out = list(scalars)
    for (family, _), snap in sorted(hists.items()):
        labels = snap.pop("labels")
        out.append((family, "histogram", labels, snap))
    return out


class Scraper:
    """Periodic sampler feeding one TimeSeriesStore.  Single-writer by
    design: one scrape thread (or one simulated driver) owns the
    store; readers (alert engine, CLI) run on the same cadence."""

    def __init__(self, store: Optional[TimeSeriesStore] = None,
                 clock: Optional[Callable[[], float]] = None,
                 registry=None):
        import time
        self.store = store if store is not None else TimeSeriesStore()
        self.clock = clock if clock is not None else time.monotonic
        self._registries: List[tuple] = []
        self._text_sources: List[tuple] = []
        self._step_dirs: List[tuple] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._self_metrics = None
        if registry is not None:
            self._self_metrics = {
                "scrapes": registry.counter(
                    "mpi_operator_obsplane_scrapes_total",
                    "Scrape cycles completed by the metrics-plane"
                    " scraper"),
                "seconds": registry.histogram(
                    "mpi_operator_obsplane_scrape_seconds",
                    "Wall time of one scrape cycle across all"
                    " configured sources"),
                "series": registry.gauge(
                    "mpi_operator_obsplane_series",
                    "Live labeled series held by the metrics-plane"
                    " time-series store"),
            }

    # -- sources -------------------------------------------------------------
    def add_registry(self, registry,
                     labels: Optional[Dict[str, str]] = None) -> None:
        """Scrape an in-process Registry via collect(); ``labels`` are
        stamped onto every sample (e.g. component="controller")."""
        self._registries.append((registry, dict(labels or {})))

    def add_text_source(self, fetch: Callable[[], Optional[str]],
                        labels: Optional[Dict[str, str]] = None) -> None:
        """Scrape a callable returning Prometheus exposition text (or
        None to skip this cycle) — remote /metrics, sidecar files."""
        self._text_sources.append((fetch, dict(labels or {})))

    def add_sidecar_dir(self, directory: str,
                        labels: Optional[Dict[str, str]] = None) -> None:
        """Scrape every ``metrics-*.prom`` exposition a worker exported
        next to its flight ring (telemetry/flight.py sidecar dir)."""
        def fetch() -> Optional[str]:
            try:
                names = sorted(n for n in os.listdir(directory)
                               if n.startswith("metrics-")
                               and n.endswith(".prom"))
            except OSError:
                return None
            parts = []
            for name in names:
                try:
                    with open(os.path.join(directory, name)) as f:
                        parts.append(f.read())
                except OSError:
                    continue
            return "\n".join(parts) if parts else None
        self._text_sources.append((fetch, dict(labels or {})))

    def add_step_dir(self, directory: str,
                     job_of: Optional[Callable[[str], Tuple[str, str]]]
                     = None) -> None:
        """Scrape ``step-<pod>`` progress files into
        ``mpi_operator_worker_steps_total{job,worker}``.  ``job_of``
        maps a pod name to (job, worker); the default splits the soak
        convention ``<job>-worker-<i>``."""
        def default_job_of(pod: str) -> Tuple[str, str]:
            job, sep, idx = pod.rpartition("-worker-")
            return (job, f"worker-{idx}") if sep else (pod, pod)
        self._step_dirs.append((directory, job_of or default_job_of))

    # -- scraping ------------------------------------------------------------
    def _scrape_steps(self, directory: str, job_of, t: float) -> None:
        try:
            names = sorted(n for n in os.listdir(directory)
                           if n.startswith("step-"))
        except OSError:
            return
        for name in names:
            if name.endswith(".tmp"):
                continue
            try:
                with open(os.path.join(directory, name)) as f:
                    steps = int(f.read().strip() or 0)
            except (OSError, ValueError):
                continue  # torn mid-replace: next cycle reads it
            job, worker = job_of(name[len("step-"):])
            self.store.add_sample(
                "mpi_operator_worker_steps_total",
                {"job": job, "worker": worker}, float(steps), t,
                kind="counter")

    def scrape_once(self, t: Optional[float] = None) -> int:
        """One cycle over every source; returns samples ingested.
        ``t`` overrides the clock (simulated feeds)."""
        start = self.clock()
        if t is None:
            t = start
        n = 0
        for registry, extra in self._registries:
            for name, kind, entries in registry.collect():
                for labels, sample in entries:
                    merged = {**labels, **extra} if extra else labels
                    self.store.add_sample(name, merged, sample, t,
                                          kind=kind)
                    n += 1
        for fetch, extra in self._text_sources:
            try:
                text = fetch()
            except Exception:
                text = None  # a dead source must not kill the cycle
            if not text:
                continue
            for name, kind, labels, sample in parse_exposition(text):
                merged = {**labels, **extra} if extra else labels
                self.store.add_sample(name, merged, sample, t,
                                      kind=kind)
                n += 1
        for directory, job_of in self._step_dirs:
            self._scrape_steps(directory, job_of, t)
        if self._self_metrics is not None:
            self._self_metrics["scrapes"].inc()
            self._self_metrics["seconds"].observe(self.clock() - start)
            self._self_metrics["series"].set(self.store.series_count())
        return n

    # -- cadence -------------------------------------------------------------
    def start(self, interval: float,
              on_cycle: Optional[Callable[[float], None]] = None
              ) -> "Scraper":
        """Background scrape thread every ``interval`` seconds;
        ``on_cycle(t)`` runs after each cycle (the alert engine's
        evaluate hook rides the scrape cadence)."""
        def loop() -> None:
            while not self._stop.wait(interval):
                t = self.clock()
                self.scrape_once(t=t)
                if on_cycle is not None:
                    on_cycle(t)
        self._stop.clear()
        self._thread = threading.Thread(
            target=loop, daemon=True, name="obsplane-scraper")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
