"""Ring-buffered time-series store — the metrics plane's memory.

Instant registries (telemetry/metrics.py) answer "what is the value
now"; nothing in the stack could answer "how has it changed" — which is
the only question that detects gray failures like a worker pacing every
collective at 0.3x speed (the bandwidth-asymmetry failure mode of
arXiv:1810.11112 / arXiv:1909.09756).  This module is the change-over-
time half: labeled series of (t, sample) rings with bounded retention,
plus the Prometheus-shaped range evaluators the alert rules
(obsplane/rules.py) are written against:

- ``rate()`` / ``increase()`` — counter deltas with reset correction
  (a restarted process's counter dropping to zero contributes the
  post-reset value, never a negative delta);
- ``quantile_over_time()`` — exact quantiles over gauge samples in the
  window, or windowed ``histogram_quantile`` via cumulative-snapshot
  subtraction for histogram series;
- ``avg_over_time()`` — the burn-rate rules' error-ratio mean;
- ``absent()`` — "this series never appeared", the watchdog primitive.

Everything is driven by caller-supplied timestamps from the injectable
clock — no wallclock reads, so a simulated feed evaluates bit-identically
on every run (the wallclock-sim lint rule enforces this file).
"""

from __future__ import annotations

import re
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..soak.slo import histogram_quantile, quantile

# name{label="value",...} — the selector grammar for queries and the
# CLI `series` verb.  Labels given must match exactly; omitted labels
# are wildcards.
_SELECTOR = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?$")
_LABEL = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)='
                    r'"(?P<v>[^"]*)"')


def parse_selector(selector: str) -> Tuple[str, Dict[str, str]]:
    """``name{label="value"}`` -> (name, {label: value}).  Raises
    ValueError on malformed input — a typo'd alert rule must fail
    loudly at construction, not match nothing forever."""
    m = _SELECTOR.match(selector.strip())
    if m is None:
        raise ValueError(f"malformed series selector: {selector!r}")
    labels: Dict[str, str] = {}
    body = m.group("labels")
    if body:
        consumed = 0
        for lm in _LABEL.finditer(body):
            labels[lm.group("k")] = lm.group("v")
            consumed += 1
        # Commas between matchers are the only other legal content.
        leftover = _LABEL.sub("", body).replace(",", "").strip()
        if leftover or (body.strip() and not consumed):
            raise ValueError(f"malformed label matchers: {body!r}")
    return m.group("name"), labels


class Series:
    """One labeled series: a bounded ring of (t, sample) where sample
    is a float (counter/gauge) or a cumulative histogram snapshot."""

    __slots__ = ("name", "labels", "kind", "samples")

    def __init__(self, name: str, labels: Dict[str, str], kind: str,
                 max_samples: int):
        self.name = name
        self.labels = dict(labels)
        self.kind = kind
        self.samples: deque = deque(maxlen=max_samples)

    def window(self, start: float, end: float) -> List[tuple]:
        return [(t, v) for t, v in self.samples if start < t <= end]

    def last_at_or_before(self, t: float) -> Optional[tuple]:
        out = None
        for ts, v in self.samples:
            if ts > t:
                break
            out = (ts, v)
        return out


def _increase(points: List[tuple]) -> Optional[float]:
    """Monotone-counter increase over chronologically ordered samples,
    with reset correction: a drop means the counter restarted, so the
    post-reset absolute value IS the increase since the reset."""
    if len(points) < 2:
        return None
    total = 0.0
    prev = points[0][1]
    for _, v in points[1:]:
        total += v if v < prev else v - prev
        prev = v
    return total


def _snapshot_delta(first: dict, last: dict) -> dict:
    """Windowed histogram: cumulative ``last`` minus cumulative
    ``first``.  A count regression (process restart reset the
    histogram) falls back to ``last`` alone — the post-reset window."""
    if last.get("count", 0) < first.get("count", 0):
        return last
    buckets = {
        bound: cum - first.get("buckets", {}).get(bound, 0)
        for bound, cum in last.get("buckets", {}).items()}
    return {"buckets": buckets,
            "sum": last.get("sum", 0.0) - first.get("sum", 0.0),
            "count": last.get("count", 0) - first.get("count", 0)}


class TimeSeriesStore:
    """Labeled series rings with retention-bounded history and
    deterministic range evaluators.  Not thread-locked per sample on
    the read path beyond one dict lookup: the scraper is the single
    writer; queries run on the scraper/engine cadence."""

    def __init__(self, retention_s: float = 600.0,
                 max_samples: int = 2048):
        self.retention_s = float(retention_s)
        self.max_samples = int(max_samples)
        self._series: Dict[tuple, Series] = {}
        # Name -> series index: every rule evaluation funnels through
        # select(), and the alert engine runs the full rule set each
        # scrape cycle — a flat scan over the whole store would make
        # rule cost O(rules x total series) on the hot path.
        self._by_name: Dict[str, List[Series]] = {}

    # -- ingest --------------------------------------------------------------
    @staticmethod
    def _key(name: str, labels: Dict[str, str]) -> tuple:
        return (name, tuple(sorted((str(k), str(v))
                                   for k, v in labels.items())))

    def add_sample(self, name: str, labels: Dict[str, str], value,
                   t: float, kind: str = "gauge") -> None:
        key = self._key(name, labels)
        series = self._series.get(key)
        if series is None:
            series = self._series[key] = Series(
                name, labels, kind, self.max_samples)
            self._by_name.setdefault(name, []).append(series)
        series.samples.append((float(t), value))
        # Retention: prune from the left against the newest timestamp
        # (logical time — the feed's clock, never the wall).
        horizon = t - self.retention_s
        while series.samples and series.samples[0][0] < horizon:
            series.samples.popleft()

    # -- selection -----------------------------------------------------------
    def select(self, selector: str) -> List[Series]:
        name, want = parse_selector(selector)
        out = []
        for series in self._by_name.get(name, ()):
            if any(series.labels.get(k) != v for k, v in want.items()):
                continue
            out.append(series)
        return sorted(out, key=lambda s: sorted(s.labels.items()))

    def series_count(self) -> int:
        return len(self._series)

    def names(self) -> List[str]:
        return sorted({s.name for s in self._series.values()})

    # -- instant evaluators --------------------------------------------------
    def latest(self, selector: str) -> List[tuple]:
        """[(labels, t, value)] — the newest sample per matching
        series."""
        out = []
        for s in self.select(selector):
            if s.samples:
                t, v = s.samples[-1]
                out.append((dict(s.labels), t, v))
        return out

    def absent(self, selector: str) -> bool:
        """True when NO matching series holds any retained sample —
        the `absent()` watchdog for feeds that should exist."""
        return not any(s.samples for s in self.select(selector))

    # -- range evaluators ----------------------------------------------------
    def increase(self, selector: str, window: float, at: float
                 ) -> List[tuple]:
        """[(labels, increase)] per series over (at-window, at] with
        counter-reset correction; series with < 2 samples in the window
        are skipped (no delta exists yet).  Histogram series are
        skipped too — their samples are cumulative snapshots, not
        scalars; window them via ``quantile_over_time`` /
        ``histogram_error_ratio`` instead."""
        out = []
        for s in self.select(selector):
            points = s.window(at - window, at)
            if points and isinstance(points[-1][1], dict):
                continue
            inc = _increase(points)
            if inc is not None:
                out.append((dict(s.labels), inc))
        return out

    def rate(self, selector: str, window: float, at: float
             ) -> List[tuple]:
        """[(labels, per-second rate)] — increase divided by the span
        the samples actually cover (never the nominal window, which
        would understate rates early in a run).  Histogram series are
        skipped, as in ``increase``."""
        out = []
        for s in self.select(selector):
            points = s.window(at - window, at)
            if points and isinstance(points[-1][1], dict):
                continue
            inc = _increase(points)
            if inc is None:
                continue
            span = points[-1][0] - points[0][0]
            if span <= 0:
                continue
            out.append((dict(s.labels), inc / span))
        return out

    def avg_over_time(self, selector: str, window: float, at: float
                      ) -> List[tuple]:
        """[(labels, mean)] of gauge samples in the window."""
        out = []
        for s in self.select(selector):
            vals = [v for _, v in s.window(at - window, at)
                    if isinstance(v, (int, float))]
            if vals:
                out.append((dict(s.labels), sum(vals) / len(vals)))
        return out

    def quantile_over_time(self, selector: str, q: float,
                           window: float, at: float) -> List[tuple]:
        """[(labels, quantile)] per series over (at-window, at].

        Gauge series: exact quantile of the raw samples (soak/slo.py
        `quantile` — empty window -> series skipped, single sample is
        every quantile of itself).  Histogram series: windowed
        snapshot subtraction, then `histogram_quantile`; a window
        whose delta observed nothing is skipped, and a mid-window
        counter reset scores the post-reset snapshot alone.
        """
        out = []
        for s in self.select(selector):
            points = s.window(at - window, at)
            if not points:
                continue
            if isinstance(points[-1][1], dict):
                base = s.last_at_or_before(at - window)
                first = base[1] if base is not None \
                    and isinstance(base[1], dict) else \
                    {"buckets": {}, "sum": 0.0, "count": 0}
                delta = _snapshot_delta(first, points[-1][1])
                value = histogram_quantile(delta, q)
            else:
                value = quantile([v for _, v in points], q)
            if value is not None:
                out.append((dict(s.labels), value))
        return out

    def histogram_error_ratio(self, selector: str, le: float,
                              window: float, at: float) -> List[tuple]:
        """[(labels, fraction of windowed observations ABOVE the
        ``le`` bucket bound)] — the burn-rate rules' error ratio for
        latency SLOs (e.g. "TTFT over 2.5s").  ``le`` must be an
        actual bucket bound of the series.  A window with zero new
        observations is skipped (no traffic burns no budget)."""
        out = []
        for s in self.select(selector):
            points = s.window(at - window, at)
            if not points or not isinstance(points[-1][1], dict):
                continue
            base = s.last_at_or_before(at - window)
            first = base[1] if base is not None \
                and isinstance(base[1], dict) else \
                {"buckets": {}, "sum": 0.0, "count": 0}
            delta = _snapshot_delta(first, points[-1][1])
            count = delta.get("count", 0)
            if count <= 0:
                continue
            good = delta.get("buckets", {}).get(le)
            if good is None:
                continue  # not a bucket bound of this histogram
            out.append((dict(s.labels),
                        max(0.0, 1.0 - good / count)))
        return out
