"""Fleet metrics plane: scraper -> time-series store -> alert rules.

The observability stack before this package was instant-only: registries
answer "what is the value now" (telemetry/metrics.py), flight rings
answer "what just happened" (telemetry/flight.py).  This package adds
the change-over-time layer — a scraping TSDB with Prometheus-shaped
range evaluators, SLO burn-rate alerting over the soak targets, and the
flagship consumer: per-worker step-time distributions scored into
``mpi_operator_straggler_score{job,worker}``.
"""

from .store import Series, TimeSeriesStore, parse_selector
from .scrape import Scraper, parse_exposition
from .rules import (Alert, AlertEngine, AbsentRule, BurnRateRule, Rule,
                    StallRule, StragglerRule, ThresholdRule)
from .straggler import StragglerScorer
from .fleet import FIDELITY_MAP, default_fleet_rules, score_alert_fidelity

__all__ = [
    "Series", "TimeSeriesStore", "parse_selector",
    "Scraper", "parse_exposition",
    "Alert", "AlertEngine", "AbsentRule", "BurnRateRule", "Rule",
    "StallRule", "StragglerRule", "ThresholdRule",
    "StragglerScorer",
    "FIDELITY_MAP", "default_fleet_rules", "score_alert_fidelity",
]
