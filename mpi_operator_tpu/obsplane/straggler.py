"""Per-worker step-time distributions and the straggler score.

The gray failure this plane exists to catch: one worker in a
data-parallel gang running at 0.3x — a degraded NIC, a thermally
throttled chip, a noisy neighbor — paces EVERY collective, so the
whole gang slows down while every per-job metric still looks healthy
(the bandwidth-asymmetry effect of arXiv:1810.11112; arXiv:1909.09756
measures the same at pod scale).  Detection needs per-WORKER step
latency, which this scorer assembles from two feeds:

- :meth:`observe_step` — explicit per-step durations from the span
  stream (PR 11 ``first_step``/train spans, flight sidecar records);
- :meth:`observe_progress` — cumulative step counters (the soak
  workers' persisted ``step-<pod>`` files, scraped into
  ``mpi_operator_worker_steps_total``): per-step latency is the time
  delta over the progress delta between scrapes.  A counter going
  BACKWARDS (pod restarted, checkpoint rewind) resets the baseline
  and contributes no sample — a restart is disruption, not slowness.

Score: the worker's rolling mean step time divided by the gang's
rolling MEDIAN of per-worker means.  The median is the robust center —
one straggler cannot drag it, so its own score stands out; a uniformly
slow gang scores ~1.0 everywhere (that is a capacity problem, not a
straggler).  Published as ``mpi_operator_straggler_score{job,worker}``
(plus per-worker ``mpi_operator_worker_step_seconds`` distributions),
with departed workers' series REMOVED on the next publish — the same
live-set idiom as the scheduler's gang gauges.

All timestamps are caller-supplied logical time; no wallclock.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..soak.slo import quantile

# A worker must show this many step samples before it is scored at
# all — one noisy first step must not page anyone.
MIN_SAMPLES = 3
# Rolling window of per-step samples kept per worker.
WINDOW_SAMPLES = 64
# Samples older than this (logical seconds) fall out of the mean even
# if the ring is not full — a worker that STOPPED reporting keeps its
# last known speed only this long.
SAMPLE_TTL_S = 120.0


class StragglerScorer:
    """Assembles per-worker step-time windows and publishes scores."""

    def __init__(self, registry=None, min_samples: int = MIN_SAMPLES,
                 window_samples: int = WINDOW_SAMPLES,
                 sample_ttl_s: float = SAMPLE_TTL_S):
        self.min_samples = int(min_samples)
        self.window_samples = int(window_samples)
        self.sample_ttl_s = float(sample_ttl_s)
        self._lock = threading.Lock()
        # (job, worker) -> deque[(t, step_seconds)]
        self._windows: Dict[tuple, deque] = {}
        # (job, worker) -> (t, cumulative_steps) progress baseline
        self._progress: Dict[tuple, tuple] = {}
        self._score_gauge = None
        self._step_hist = None
        self._published: set = set()
        if registry is not None:
            self._score_gauge = registry.gauge_vec(
                "mpi_operator_straggler_score",
                "Worker rolling-mean step time over the gang's rolling"
                " median (1.0 = keeping pace; sustained >1.8 pages via"
                " StragglerAlert)", ["job", "worker"])
            self._step_hist = registry.histogram_vec(
                "mpi_operator_worker_step_seconds",
                "Per-worker train step wall time as assembled by the"
                " straggler scorer (span stream + progress deltas)",
                ["job", "worker"])

    # -- feeds ---------------------------------------------------------------
    def observe_step(self, job: str, worker: str, seconds: float,
                     t: float) -> None:
        """One measured step duration (span stream)."""
        if seconds <= 0:
            return
        key = (str(job), str(worker))
        with self._lock:
            ring = self._windows.get(key)
            if ring is None:
                ring = self._windows[key] = deque(
                    maxlen=self.window_samples)
            ring.append((float(t), float(seconds)))
        if self._step_hist is not None:
            self._step_hist.labels(*key).observe(float(seconds))

    def observe_progress(self, job: str, worker: str, steps: float,
                         t: float) -> None:
        """A cumulative step-counter reading (flight step probe /
        scraped worker counter).  Derives per-step latency from the
        delta against the previous reading."""
        key = (str(job), str(worker))
        with self._lock:
            prev = self._progress.get(key)
            if prev is None or steps < prev[1]:
                # First reading, or backwards = restart/rewind:
                # (re)set the baseline, observe nothing — a restart
                # is disruption, not slowness.
                self._progress[key] = (float(t), float(steps))
                return
            prev_t, prev_steps = prev
            dsteps = steps - prev_steps
            dt = t - prev_t
            if dsteps == 0 or dt <= 0:
                # Idle interval: the current step is still in flight.
                # KEEP the baseline — advancing it here would charge a
                # slow step only for its final interval and make a
                # straggler look healthy.
                return
            self._progress[key] = (float(t), float(steps))
        self.observe_step(job, worker, dt / dsteps, t)

    # -- scoring -------------------------------------------------------------
    def _means(self, t: float) -> Dict[tuple, float]:
        horizon = t - self.sample_ttl_s
        out: Dict[tuple, float] = {}
        with self._lock:
            for key, ring in self._windows.items():
                while ring and ring[0][0] < horizon:
                    ring.popleft()
                if len(ring) < self.min_samples:
                    continue
                vals = [s for _, s in ring]
                out[key] = sum(vals) / len(vals)
        return out

    def scores(self, t: float) -> Dict[tuple, float]:
        """{(job, worker): score} for every scoreable worker.  Gangs
        with fewer than 2 reporting workers are skipped — a median of
        one is the worker itself and every score would be 1.0."""
        means = self._means(t)
        by_job: Dict[str, List[Tuple[str, float]]] = {}
        for (job, worker), mean in means.items():
            by_job.setdefault(job, []).append((worker, mean))
        out: Dict[tuple, float] = {}
        for job, rows in by_job.items():
            if len(rows) < 2:
                continue
            median = quantile([m for _, m in rows], 0.5)
            if not median:
                continue
            for worker, mean in rows:
                out[(job, worker)] = mean / median
        return out

    def publish(self, t: float) -> Dict[tuple, float]:
        """Compute scores at ``t``, set the gauge series, and REMOVE
        series for workers that departed the scoreable set (died,
        resized away, went stale) so the scrape never carries ghosts."""
        scores = self.scores(t)
        if self._score_gauge is not None:
            live = set(scores)
            for key, score in sorted(scores.items()):
                self._score_gauge.labels(*key).set(round(score, 6))
            for stale in self._published - live:
                self._score_gauge.remove(*stale)
            self._published = live
        return scores

    def worker_distribution(self, job: str, worker: str,
                            q: float, t: float) -> Optional[float]:
        """Quantile of the worker's retained step-time window."""
        horizon = t - self.sample_ttl_s
        with self._lock:
            ring = self._windows.get((str(job), str(worker)))
            vals = [s for ts, s in (ring or ()) if ts >= horizon]
        return quantile(vals, q)
