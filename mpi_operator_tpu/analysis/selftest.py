"""Analyzer self-test: seed one synthetic violation per rule + a
deliberate lock inversion, assert each is caught.

``python -m mpi_operator_tpu analyze --self-test`` (and the `make
analyze` gate) runs this so a refactor that silently disables a rule —
a scope regression, a broken regex, a detached detector — fails CI the
same way a real violation would.  The synthetic tree lives in a
tempdir shaped like the repo (package/tests/docs layout), so rule
scoping is exercised for real; the lockcheck checks run on a PRIVATE
detector so a globally armed one (tier-1) is never polluted with the
deliberate inversion.
"""

from __future__ import annotations

import os
import queue
import tempfile
import textwrap
import threading
from contextlib import contextmanager
from typing import List, Tuple

from . import lint, lockcheck

# (rule, path-suffix, count) expected from the synthetic tree.
EXPECTED_STATIC = (
    ("raw-annotation-key", "mpi_operator_tpu/seeded_annotation.py", 1),
    ("silent-except", "mpi_operator_tpu/seeded_except.py", 2),
    ("sleep-poll", "tests/test_seeded_poll.py", 1),
    ("wallclock-sim", "mpi_operator_tpu/chaos/plan.py", 2),
    ("metrics-catalog", "mpi_operator_tpu/seeded_metrics.py", 1),
    ("metrics-catalog", "docs/OBSERVABILITY.md", 1),
    # The alert-rule extension's violation pair: one rule watching a
    # metric that exists nowhere, one watching the documented-but-
    # unregistered ghost.
    ("metrics-catalog", "mpi_operator_tpu/seeded_rules.py", 2),
    # One relist in a while loop fires; the pragma'd resync and the
    # for-iterator list (evaluated once) in the same file must NOT —
    # precision is asserted by no-extra-findings.
    ("full-relist-in-loop", "mpi_operator_tpu/sched/seeded_relist.py", 1),
)

_SEEDED_FILES = {
    # (This module is in lint.CORPUS_FILES — the seed corpus retypes
    # keys and sleeps in loops by design.)
    "mpi_operator_tpu/seeded_annotation.py": """\
        WORKER_ROLE_LABEL = "training.kubeflow.org/job-role"
    """,
    "mpi_operator_tpu/seeded_except.py": """\
        def swallow_bare():
            try:
                risky()
            except:
                pass

        def swallow_broad(items):
            for item in items:
                try:
                    risky(item)
                except Exception:
                    continue
    """,
    "tests/test_seeded_poll.py": """\
        import time

        def test_poll():
            while not done():
                time.sleep(0.1)
    """,
    "mpi_operator_tpu/chaos/plan.py": """\
        import random
        import time

        def seeded_plan():
            started = time.time()
            return started + random.random()
    """,
    "mpi_operator_tpu/seeded_metrics.py": """\
        def new_metrics(registry):
            return registry.counter(
                "mpi_operator_selftest_undocumented_total",
                "registered but missing from the catalog")
    """,
    "mpi_operator_tpu/seeded_rules.py": """\
        from mpi_operator_tpu.obsplane.rules import ThresholdRule

        def rules():
            return [
                ThresholdRule(
                    "SeededPhantomWatch",
                    metric="mpi_operator_selftest_phantom_total",
                    above=0.0),
                ThresholdRule(
                    "SeededGhostWatch",
                    metric="mpi_operator_selftest_ghost_total",
                    above=0.0),
            ]
    """,
    "mpi_operator_tpu/sched/seeded_relist.py": """\
        def hot_path(server, pending):
            while pending:
                jobs = server.list("kubeflow.org/v2beta1", "MPIJob")
                pending = admit(jobs, pending)

        def deliberate_resync(server, pending):
            for _ in range(3):
                jobs = server.list(  # lint: allow[full-relist-in-loop] — seeded resync
                    "kubeflow.org/v2beta1", "MPIJob")
                if jobs:
                    return jobs

        def iter_once(server):
            for job in server.list("kubeflow.org/v2beta1", "MPIJob"):
                mark(job)
    """,
    "docs/OBSERVABILITY.md": """\
        | metric | type | layer | meaning |
        |---|---|---|---|
        | `mpi_operator_selftest_ghost_total` | counter | x | documented but registered nowhere |
    """,
}


def _build_tree(root: str) -> None:
    for relpath, body in _SEEDED_FILES.items():
        path = os.path.join(root, relpath)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            fh.write(textwrap.dedent(body))


def run_static_selftest() -> List[Tuple[str, bool, str]]:
    results = []
    with tempfile.TemporaryDirectory(prefix="analyze-selftest-") as root:
        _build_tree(root)
        res = lint.run_lint(root, baseline_path=os.path.join(
            root, "no_baseline.txt"))
        for rule_id, suffix, want in EXPECTED_STATIC:
            got = [f for f in res.findings
                   if f.rule == rule_id and f.path == suffix]
            ok = len(got) == want
            detail = (f"{len(got)}/{want} finding(s) in {suffix}"
                      + ("" if ok else
                         f" — got {[f.render() for f in res.findings]}"))
            results.append((f"lint:{rule_id}@{suffix}", ok, detail))
        # The seeded tree must produce NOTHING beyond the seeds (rule
        # precision): every finding maps to an expectation.
        expected_pairs = {(r, p) for r, p, _ in EXPECTED_STATIC}
        extras = [f.render() for f in res.findings
                  if (f.rule, f.path) not in expected_pairs]
        results.append(("lint:no-extra-findings", not extras,
                        f"unexpected: {extras}" if extras else "clean"))
    return results


@contextmanager
def _swapped_detector(det: lockcheck.LockCheck):
    """Route the module-level blocking patches at a private detector for
    the duration (restores the armed global one, if any, on exit)."""
    old_det = lockcheck._detector
    old_get = queue.Queue.get
    old_wait = threading.Condition.wait
    lockcheck._detector = det
    queue.Queue.get = lockcheck._queue_get
    threading.Condition.wait = lockcheck._condition_wait
    try:
        yield
    finally:
        lockcheck._detector = old_det
        queue.Queue.get = old_get
        threading.Condition.wait = old_wait


def run_lockcheck_selftest() -> List[Tuple[str, bool, str]]:
    results = []
    det = lockcheck.LockCheck()

    # Deliberate A->B / B->A inversion (sequential, so it records the
    # order without actually deadlocking).
    lock_a = det.wrap(lockcheck.raw_lock(), site="selftest.py:A")
    lock_b = det.wrap(lockcheck.raw_lock(), site="selftest.py:B")
    with lock_a:
        with lock_b:
            pass
    with lock_b:
        with lock_a:
            pass
    cycles = det.cycles()
    ok = any(c["kind"] == "lock-order cycle" for c in cycles)
    witness_ok = ok and all(
        len([w for w in c["witness"] if w]) >= 2 for c in cycles
        if c["kind"] == "lock-order cycle")
    results.append(("lockcheck:cycle", ok,
                    f"{len(cycles)} cycle(s) from the seeded inversion"))
    results.append(("lockcheck:witness-stacks", bool(witness_ok),
                    "both witness stacks captured" if witness_ok
                    else "missing witness stacks"))
    fatal_raised = False
    try:
        det.check_fatal()
    except lockcheck.LockOrderError:
        fatal_raised = True
    results.append(("lockcheck:fatal-on-cycle", fatal_raised,
                    "check_fatal raised LockOrderError"))

    # Blocking call (queue.get) under a named hot lock, through the
    # real monkeypatched path.
    det2 = lockcheck.LockCheck()
    hot = det2.wrap(lockcheck.raw_lock(), site="selftest.py:hot",
                    name="selftest.hot")
    with _swapped_detector(det2):
        with hot:
            try:
                queue.Queue().get(timeout=0.01)
            except queue.Empty:
                pass
    blocking = det2.blocking_findings()
    ok = any(b["kind"] == "queue.get" and b["hot_lock"] == "selftest.hot"
             for b in blocking)
    results.append(("lockcheck:blocking-under-hot-lock", ok,
                    f"{len(blocking)} blocking finding(s)"))
    return results


def run_self_test() -> Tuple[bool, List[str]]:
    """Returns (all_caught, report_lines)."""
    results = run_static_selftest() + run_lockcheck_selftest()
    lines = []
    seeded = 0
    for name, ok, detail in results:
        status = "CAUGHT" if ok else "MISSED"
        if name.startswith(("lint:no-extra", "lockcheck:witness",
                            "lockcheck:fatal")):
            status = "OK" if ok else "FAIL"
        else:
            seeded += 1
        lines.append(f"  {status:6s} {name}: {detail}")
    all_ok = all(ok for _, ok, _ in results)
    lines.append(f"self-test: {seeded} seeded violation classes, "
                 f"{'all caught' if all_ok else 'FAILURES ABOVE'}")
    return all_ok, lines
