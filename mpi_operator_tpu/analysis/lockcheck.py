"""Runtime concurrency detector: lock-order cycles + blocking-under-lock.

Opt-in instrumentation (``MPI_OPERATOR_LOCKCHECK=1``, the same arming
pattern as ``MPI_OPERATOR_CACHE_MUTATION_DETECT``): ``install()`` wraps
``threading.Lock``/``RLock`` *creation* so every lock created by repo
code (and only repo code — stdlib/third-party callers get real locks,
keeping overhead off foreign hot paths) is a tracked proxy that records
per-thread acquisition order.

From the recorded order the detector maintains the global lock-order
graph, keyed by lock *creation site* (file:line, or a registered name
for the hot locks), and reports:

- **lock-order cycles** — site A's lock taken under site B's AND vice
  versa (potential deadlock), with both witness stacks (captured once,
  at the first observation of each edge).  Same-site lock pairs (e.g.
  per-shard stores) only count as a cycle when the SAME two instances
  are seen in both orders — a globally-ordered walk over siblings stays
  clean.
- **blocking calls under a named hot lock** — acquiring a second lock,
  ``queue.Queue.get``/``threading.Condition.wait`` (blocking form), or
  any site routed through :func:`note_blocking`, while the thread holds
  a lock registered via :func:`name_lock` (apiserver ``_KindStore``,
  flight ring, batcher device lock, router state).  Counted in
  ``mpi_operator_lockcheck_blocking_under_lock_total`` and summarized
  in the report; unlike cycles these are advisory, not fatal.

Armed for all of tier-1 via ``tests/conftest.py`` and for every
``make *-smoke`` (the smoke mains call :func:`check_fatal` before
exiting); a cycle fails the run.  ``analyze --self-test`` seeds a
deliberate A->B/B->A inversion plus a queue.get-under-hot-lock and
asserts both are caught (docs/ANALYSIS.md).
"""

from __future__ import annotations

import itertools
import os
import queue
import sys
import threading
import traceback
from typing import Dict, List, Optional, Tuple

ENV_FLAG = "MPI_OPERATOR_LOCKCHECK"

# Real primitives, captured before any monkeypatching.
raw_lock = threading.Lock
raw_rlock = threading.RLock
_raw_queue_get = queue.Queue.get
_raw_condition_wait = threading.Condition.wait

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class LockOrderError(RuntimeError):
    """Raised by check_fatal() when the lock-order graph has a cycle."""


def _external_frame():
    """First stack frame outside this module (the real call site)."""
    frame = sys._getframe(1)
    while frame is not None and frame.f_code.co_filename == __file__:
        frame = frame.f_back
    return frame


class _TrackedLock:
    """Proxy around a real Lock/RLock recording acquisition order."""

    __slots__ = ("_lock", "_site", "_name", "_hot", "_det", "_reentrant",
                 "_owner", "_depth", "_serial")

    _serials = itertools.count(1)  # id() recycles after GC; this never

    def __init__(self, lock, site: str, det: "LockCheck",
                 reentrant: bool):
        self._serial = next(self._serials)
        self._lock = lock
        self._site = site
        self._name: Optional[str] = None
        self._hot = False
        self._det = det
        self._reentrant = reentrant
        self._owner: Optional[int] = None   # owning thread id (RLock)
        self._depth = 0

    @property
    def label(self) -> str:
        return self._name or self._site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            # Reentrant re-acquire: no ordering information.
            got = self._lock.acquire(blocking, timeout)
            if got:
                self._depth += 1
            return got
        self._det._record_attempt(self)
        got = self._lock.acquire(blocking, timeout)
        if got:
            if self._reentrant:
                self._owner = me
                self._depth = 1
            self._det._push_held(self)
        return got

    def release(self):
        if self._reentrant and self._owner == threading.get_ident():
            self._depth -= 1
            if self._depth > 0:
                self._lock.release()
                return
            self._owner = None
        self._det._pop_held(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TrackedLock {self.label} wrapping {self._lock!r}>"

    def __getattr__(self, item):
        # Pass through the rest of the primitive's surface (_is_owned,
        # _release_save, ... — Condition compatibility).
        return getattr(object.__getattribute__(self, "_lock"), item)


class LockCheck:
    """The detector core.  The global armed instance is created by
    install(); tests drive private instances via wrap()."""

    def __init__(self):
        # A real (untracked) lock guards the graph structures.
        self._mu = raw_lock()
        self._tl = threading.local()
        # (site_a, site_b) -> witness stack captured when the edge first
        # appeared (the stack shows BOTH acquires: b under a).
        self._edges: Dict[Tuple[str, str], str] = {}
        self._graph: Dict[str, set] = {}
        # Same-site instance pairs: (site, frozenset{serial,serial}) ->
        # observed (first,second) acquisition orders (proxy serials are
        # monotonic and never recycled, unlike id()).
        self._pairs: Dict[Tuple[str, frozenset], Dict[tuple, str]] = {}
        self._cycles: List[dict] = []
        # (hot label, kind, call site) -> count; stacks kept per key.
        self._blocking: Dict[Tuple[str, str, str], dict] = {}
        self._counter = None  # lazy telemetry counter

    # -- wrapping ----------------------------------------------------------

    def wrap(self, lock, site: Optional[str] = None,
             reentrant: bool = False, name: Optional[str] = None
             ) -> _TrackedLock:
        if site is None:
            caller = sys._getframe(1)
            site = (f"{os.path.basename(caller.f_code.co_filename)}:"
                    f"{caller.f_lineno}")
        proxy = _TrackedLock(lock, site, self, reentrant)
        if name:
            proxy._name = name
            proxy._hot = True
        return proxy

    # -- per-thread held list ----------------------------------------------

    def _held(self) -> list:
        held = getattr(self._tl, "held", None)
        if held is None:
            held = self._tl.held = []
        return held

    def _push_held(self, proxy: _TrackedLock):
        self._held().append(proxy)

    def _pop_held(self, proxy: _TrackedLock):
        held = self._held()
        # Non-LIFO release is legal (e.g. Condition._release_save);
        # remove by identity from the right.
        for i in range(len(held) - 1, -1, -1):
            if held[i] is proxy:
                del held[i]
                return

    # -- edge recording ----------------------------------------------------

    def _record_attempt(self, proxy: _TrackedLock):
        # Re-entrancy guard: recording itself may acquire tracked locks
        # (the telemetry counter's) — never recurse into recording.
        if getattr(self._tl, "busy", False):
            return
        held = self._held()
        if not held:
            return
        for h in held:
            if h is proxy:
                return  # reentrant (RLock) — no ordering info
        self._tl.busy = True
        try:
            self._record_attempt_inner(proxy, held)
        finally:
            self._tl.busy = False

    def _record_attempt_inner(self, proxy: _TrackedLock, held: list):
        hot = [h for h in held if h._hot]
        if hot:
            self._note_blocking_locked(
                hot[-1].label, "lock.acquire",
                f"acquire of {proxy.label}")
        stack = None
        with self._mu:
            for h in held:
                if h._site == proxy._site:
                    key = (h._site,
                           frozenset((h._serial, proxy._serial)))
                    orders = self._pairs.setdefault(key, {})
                    order = (h._serial, proxy._serial)
                    if order not in orders:
                        if stack is None:
                            stack = "".join(traceback.format_stack(
                                _external_frame()))
                        orders[order] = stack
                        rev = (proxy._serial, h._serial)
                        if rev in orders:
                            self._cycles.append({
                                "sites": [h._site, proxy._site],
                                "labels": [h.label, proxy.label],
                                "kind": "same-site instance inversion",
                                "witness": [orders[rev], stack],
                            })
                    continue
                edge = (h._site, proxy._site)
                if edge in self._edges:
                    continue
                if stack is None:
                    stack = "".join(traceback.format_stack(
                        _external_frame()))
                self._edges[edge] = stack
                self._graph.setdefault(h._site, set()).add(proxy._site)
                cycle_path = self._find_path(proxy._site, h._site)
                if cycle_path is not None:
                    sites = [h._site] + cycle_path
                    self._cycles.append({
                        "sites": sites,
                        "labels": [h.label, proxy.label],
                        "kind": "lock-order cycle",
                        "witness": [stack] + [
                            self._edges.get((a, b), "")
                            for a, b in zip(cycle_path, cycle_path[1:])],
                    })

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """Path src -> ... -> dst in the edge graph (caller holds _mu)."""
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- blocking-under-hot-lock -------------------------------------------

    def note_blocking(self, kind: str, detail: str = ""):
        """Record a potentially-blocking call if the calling thread holds
        a named hot lock.  Cheap no-op otherwise."""
        if getattr(self._tl, "busy", False):
            return
        held = getattr(self._tl, "held", None)
        if not held:
            return
        hot = [h for h in held if h._hot]
        if not hot:
            return
        self._tl.busy = True
        try:
            self._note_blocking_locked(hot[-1].label, kind, detail)
        finally:
            self._tl.busy = False

    def _note_blocking_locked(self, hot_label: str, kind: str,
                              detail: str):
        frame = _external_frame()
        site = "?"
        if frame is not None:
            site = (f"{os.path.basename(frame.f_code.co_filename)}:"
                    f"{frame.f_lineno}")
        key = (hot_label, kind, site)
        with self._mu:
            rec = self._blocking.get(key)
            if rec is None:
                rec = self._blocking[key] = {
                    "hot_lock": hot_label, "kind": kind, "site": site,
                    "detail": detail, "count": 0,
                    "stack": "".join(traceback.format_stack(frame)),
                }
            rec["count"] += 1
            counter = self._counter
        if counter is None:
            counter = self._ensure_counter()
        if counter is not None:
            counter.inc()

    def _ensure_counter(self):
        try:
            from ..telemetry import metrics as telemetry_metrics
            with self._mu:
                if self._counter is None:
                    self._counter = telemetry_metrics.default_registry(
                    ).counter(
                        "mpi_operator_lockcheck_blocking_under_lock_total",
                        "Blocking calls (second-lock acquire, queue.get, "
                        "Condition.wait) executed while holding a named "
                        "hot lock")
                return self._counter
        except ImportError:
            return None

    # -- reporting ---------------------------------------------------------

    def cycles(self) -> List[dict]:
        with self._mu:
            return list(self._cycles)

    def blocking_findings(self) -> List[dict]:
        with self._mu:
            return sorted(self._blocking.values(),
                          key=lambda r: -r["count"])

    def report(self) -> dict:
        with self._mu:
            return {
                "edges": len(self._edges),
                "cycles": list(self._cycles),
                "blocking_under_hot_lock": sorted(
                    ({k: v for k, v in rec.items() if k != "stack"}
                     for rec in self._blocking.values()),
                    key=lambda r: -r["count"]),
            }

    def render_report(self, max_blocking: int = 10) -> str:
        rep = self.report()
        lines = [f"lockcheck: {rep['edges']} lock-order edges, "
                 f"{len(rep['cycles'])} cycles, "
                 f"{len(rep['blocking_under_hot_lock'])} distinct "
                 f"blocking-under-hot-lock sites"]
        for cyc in rep["cycles"]:
            lines.append(f"  CYCLE ({cyc['kind']}): "
                         + " -> ".join(cyc["sites"]))
            for i, stack in enumerate(cyc.get("witness", ())):
                if stack:
                    lines.append(f"  witness stack {i + 1}:")
                    lines.extend("    " + ln
                                 for ln in stack.rstrip().splitlines())
        for rec in rep["blocking_under_hot_lock"][:max_blocking]:
            lines.append(
                f"  blocking under {rec['hot_lock']}: {rec['kind']} at "
                f"{rec['site']} x{rec['count']}"
                + (f" ({rec['detail']})" if rec["detail"] else ""))
        return "\n".join(lines)

    def check_fatal(self):
        """Raise LockOrderError if any lock-order cycle was observed."""
        cycles = self.cycles()
        if cycles:
            raise LockOrderError(
                f"{len(cycles)} lock-order cycle(s) detected:\n"
                + self.render_report())


# ---------------------------------------------------------------------------
# Global install

_detector: Optional[LockCheck] = None
_install_mu = raw_lock()


def detector() -> Optional[LockCheck]:
    return _detector


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "") not in ("", "0", "false")


def _from_repo(frame) -> bool:
    fn = frame.f_code.co_filename
    return fn.startswith(_REPO_ROOT) and f"{os.sep}analysis{os.sep}" \
        not in fn


def _lock_factory():
    det = _detector
    if det is None:
        return raw_lock()
    caller = sys._getframe(1)
    if not _from_repo(caller):
        return raw_lock()
    site = (f"{os.path.basename(caller.f_code.co_filename)}:"
            f"{caller.f_lineno}")
    return det.wrap(raw_lock(), site=site, reentrant=False)


def _rlock_factory():
    det = _detector
    if det is None:
        return raw_rlock()
    caller = sys._getframe(1)
    if not _from_repo(caller):
        return raw_rlock()
    site = (f"{os.path.basename(caller.f_code.co_filename)}:"
            f"{caller.f_lineno}")
    return det.wrap(raw_rlock(), site=site, reentrant=True)


def _queue_get(self, block=True, timeout=None):
    det = _detector
    if det is not None and block:
        det.note_blocking("queue.get",
                          f"timeout={timeout!r}")
    return _raw_queue_get(self, block, timeout)


def _condition_wait(self, timeout=None):
    det = _detector
    if det is not None:
        det.note_blocking("Condition.wait", f"timeout={timeout!r}")
    return _raw_condition_wait(self, timeout)


def install() -> LockCheck:
    """Arm the global detector (idempotent).  Wraps threading.Lock/RLock
    creation for repo code and patches queue.get/Condition.wait for
    blocking-under-hot-lock accounting."""
    global _detector
    with _install_mu:
        if _detector is not None:
            return _detector
        _detector = LockCheck()
        threading.Lock = _lock_factory
        threading.RLock = _rlock_factory
        queue.Queue.get = _queue_get
        threading.Condition.wait = _condition_wait
        return _detector


def uninstall():
    """Disarm and restore the real primitives (already-created proxies
    keep working — they hold real locks inside)."""
    global _detector
    with _install_mu:
        threading.Lock = raw_lock
        threading.RLock = raw_rlock
        queue.Queue.get = _raw_queue_get
        threading.Condition.wait = _raw_condition_wait
        _detector = None


def install_from_env() -> Optional[LockCheck]:
    if enabled_by_env():
        return install()
    return None


def name_lock(lock, name: str):
    """Register a hot lock by name (apiserver._KindStore, flight.ring,
    batcher.device_lock, router.state).  No-op when the detector is
    disarmed (the lock is then a plain primitive)."""
    if isinstance(lock, _TrackedLock):
        lock._name = name
        lock._hot = True
    return lock


def check_fatal():
    """Fatal gate for smokes/CI: raise if the armed detector saw a
    lock-order cycle; print the summary line either way."""
    det = _detector
    if det is None:
        return
    print(det.render_report(max_blocking=5))
    det.check_fatal()


def gate(rc: int) -> int:
    """Smoke-exit gate (docs/ANALYSIS.md): when the Makefile armed
    MPI_OPERATOR_LOCKCHECK, a lock-order cycle observed anywhere in the
    run fails the smoke even if the scenario itself passed.  Usage:
    ``sys.exit(lockcheck.gate(main()))``."""
    try:
        check_fatal()
    except LockOrderError as exc:
        print(f"FAIL: {exc}")
        return rc or 4
    return rc
