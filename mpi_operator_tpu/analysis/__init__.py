"""Correctness tooling: static lint engine + runtime concurrency detector.

Two engines (docs/ANALYSIS.md):

- ``analysis.lint`` — an AST-walking rule framework with project-specific
  rules (raw annotation-key literals, silent broad excepts, sleep-polling
  tests, wall-clock in the sim substrate, metrics-catalog drift), a
  checked-in baseline for grandfathered findings, and inline
  ``# lint: allow[rule-id]`` pragmas.  Surfaced as
  ``python -m mpi_operator_tpu analyze`` and ``make analyze``.

- ``analysis.lockcheck`` — an opt-in (``MPI_OPERATOR_LOCKCHECK=1``)
  instrumentation layer that wraps ``threading.Lock``/``RLock`` creation
  in repo code, builds the global lock-order graph, and reports
  lock-order cycles (with both witness stacks) and blocking calls
  executed while holding a named hot lock.  Armed for all of tier-1 via
  ``tests/conftest.py`` and for every ``make *-smoke``; fatal on cycle.

Both engines self-test: ``analyze --self-test`` seeds one synthetic
violation per rule plus a deliberate A->B/B->A lock inversion and
asserts each is caught.
"""
