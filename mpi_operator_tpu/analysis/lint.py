"""Static lint engine: project-specific AST rules over the repo tree.

The bug classes these rules encode were all found *by hand* in recent
PRs' review-hardening passes; the engine catches them mechanically
(docs/ANALYSIS.md has the full catalog with rationale and examples):

- ``raw-annotation-key``   retyped ``*.kubeflow.org/...`` annotation/label
  keys outside ``api/constants.py`` (the PR 9-12 tamper/restart bug class
  rode on retyped keys).
- ``silent-except``        bare/overbroad ``except`` whose body swallows
  silently (the PR 3/5 silent-death class).
- ``sleep-poll``           hand-rolled ``time.sleep`` polling loops in
  tests/smokes (the PR 10 deflake class — waits must be watch- or
  condition-driven, via ``utils.waiters.wait_until``).
- ``wallclock-sim``        wall-clock / unseeded randomness inside the
  deterministic sim/chaos/topology substrate (byte-stable-replay killers).
- ``metrics-catalog``      metric families registered in code but missing
  from the docs/OBSERVABILITY.md catalog, and vice versa (the obs-smoke
  drift check, promoted to static so it runs without standing up a
  cluster).

Suppression, in burn-down order of preference: fix the finding; else an
inline ``# lint: allow[rule-id] — reason`` pragma on the offending line
(or the line above); else a baseline entry (``tools/analysis_baseline.txt``)
so existing findings are grandfathered while NEW violations still fail.
Baseline entries that no longer match anything are STALE and fail the run
(the baseline only burns down).
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Findings, pragmas, fingerprints


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str

    def render(self) -> str:
        # path:line rule-id message — clickable in editors/CI logs.
        return f"{self.path}:{self.line} {self.rule} {self.message}"


_PRAGMA = re.compile(r"#\s*lint:\s*allow\[([a-z0-9-]+(?:,[a-z0-9-]+)*)\]")


def _pragma_rules(line: str) -> frozenset:
    m = _PRAGMA.search(line)
    if not m:
        return frozenset()
    return frozenset(r.strip() for r in m.group(1).split(","))


def fingerprint(finding: Finding, line_text: str, occurrence: int) -> str:
    """Stable id for a finding: survives unrelated line-number churn
    (keyed on the line's text, not its number); identical lines in one
    file disambiguate by occurrence index."""
    h = hashlib.blake2b(digest_size=6)
    h.update(finding.rule.encode())
    h.update(finding.path.encode())
    h.update(line_text.strip().encode())
    h.update(str(occurrence).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Rule framework


@dataclass
class Rule:
    """One lint rule: per-file AST check plus an optional project-level
    finalize pass (for cross-file rules like catalog drift)."""
    id: str
    severity: str
    doc: str
    scope: Callable[[str], bool]
    check: Optional[Callable[["FileContext"], List[Finding]]] = None
    finalize: Optional[Callable[["ProjectContext"], List[Finding]]] = None


@dataclass
class FileContext:
    root: str
    relpath: str
    tree: ast.AST
    lines: List[str]
    project: "ProjectContext"


@dataclass
class ProjectContext:
    root: str
    # metrics-catalog collect phase: name -> first (relpath, line) seen
    metric_sites: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    # alert-rule metric references: (metric, relpath, line) per string
    # literal passed as metric=/..._metric= to an obsplane rule class
    alert_rule_refs: List[Tuple[str, str, int]] = field(
        default_factory=list)


RULES: List[Rule] = []


def rule(id: str, severity: str, doc: str, scope):
    def deco(fn):
        RULES.append(Rule(id=id, severity=severity, doc=doc, scope=scope,
                          check=fn))
        return fn
    return deco


def _in_pkg(relpath: str) -> bool:
    return relpath.startswith("mpi_operator_tpu/")


def _docstring_linenos(tree: ast.AST) -> set:
    """Line numbers spanned by module/class/function docstrings (their
    prose legitimately names annotation keys)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                c = body[0].value
                out.update(range(c.lineno, (c.end_lineno or c.lineno) + 1))
    return out


# ---------------------------------------------------------------------------
# raw-annotation-key

_ANNOTATION_KEY = re.compile(
    r"(?:[a-z0-9-]+\.)*kubeflow\.org/[A-Za-z0-9][A-Za-z0-9._-]*")


def _is_api_version(key: str) -> bool:
    # "kubeflow.org/v2beta1" is an apiVersion (GVK idiom), not a
    # retypable annotation/label key.
    suffix = key.rsplit("/", 1)[1]
    return bool(re.match(r"^v\d", suffix))


@rule("raw-annotation-key", "error",
      "kubeflow.org-domain annotation/label key retyped as a string "
      "literal outside api/constants.py; route it through the constant "
      "(retyped keys are the PR 9-12 tamper/restart bug class)",
      scope=lambda p: p != "mpi_operator_tpu/api/constants.py")
def check_raw_annotation_key(ctx: FileContext) -> List[Finding]:
    findings = []
    doc_lines = _docstring_linenos(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Constant) and
                isinstance(node.value, str)):
            continue
        if node.lineno in doc_lines:
            continue
        for key in _ANNOTATION_KEY.findall(node.value):
            if _is_api_version(key):
                continue
            findings.append(Finding(
                "raw-annotation-key", ctx.relpath, node.lineno,
                f"raw annotation/label key {key!r} — use the "
                f"api/constants.py constant"))
    return findings


# ---------------------------------------------------------------------------
# silent-except

_BROAD = ("Exception", "BaseException")


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for n in ([t] if not isinstance(t, ast.Tuple) else t.elts):
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return any(n in _BROAD for n in names)


def _body_is_silent(handler: ast.ExceptHandler) -> bool:
    """Silent = no call, no raise, no state recorded: nothing but
    pass/continue/break/return-constant.  A counter increment, log line,
    re-raise, or flag assignment all count as 'not silent'."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Call, ast.Assign,
                                 ast.AugAssign, ast.AnnAssign)):
                return False
    return True


@rule("silent-except", "error",
      "bare/overbroad except that swallows silently in a control-plane "
      "package; narrow to typed exceptions and record the drop (counter, "
      "log, or re-raise) — the PR 3/5 silent-death class",
      scope=_in_pkg)
def check_silent_except(ctx: FileContext) -> List[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and \
                _handler_is_broad(node) and _body_is_silent(node):
            what = "bare except" if node.type is None else \
                f"except {ast.unparse(node.type)}"
            findings.append(Finding(
                "silent-except", ctx.relpath, node.lineno,
                f"{what} swallows silently — narrow the type and count/"
                f"log the drop, or re-raise"))
    return findings


# ---------------------------------------------------------------------------
# sleep-poll


def _sleep_poll_scope(relpath: str) -> bool:
    return relpath.startswith("tests/") or (
        relpath.startswith("tools/") and relpath.endswith("_smoke.py"))


@rule("sleep-poll", "error",
      "time.sleep inside a loop in a test/smoke — hand-rolled polling "
      "is the PR 10 deflake class; use a watch-driven wait or "
      "utils.waiters.wait_until (pacing sleeps take a pragma)",
      scope=_sleep_poll_scope)
def check_sleep_poll(ctx: FileContext) -> List[Finding]:
    findings = []

    def visit(node, in_loop):
        for child in ast.iter_child_nodes(node):
            child_in_loop = in_loop or isinstance(child,
                                                  (ast.While, ast.For))
            if isinstance(child, ast.Call) and in_loop:
                f = child.func
                if (isinstance(f, ast.Attribute) and f.attr == "sleep" and
                        isinstance(f.value, ast.Name) and
                        f.value.id == "time"):
                    findings.append(Finding(
                        "sleep-poll", ctx.relpath, child.lineno,
                        "time.sleep in a loop — use wait_until/a watch "
                        "instead of hand-rolled polling"))
            # A nested def resets loop context (the loop runs the def,
            # not the sleep).
            reset = isinstance(child, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.Lambda))
            visit(child, False if reset else child_in_loop)

    visit(ctx.tree, False)
    return findings


# ---------------------------------------------------------------------------
# wallclock-sim

# The deterministic substrate: seeded-replay byte-stability depends on
# these files never reading the wall clock or the process-global RNG.
SIM_SCOPE = frozenset((
    "mpi_operator_tpu/chaos/plan.py",
    "mpi_operator_tpu/sched/topology.py",
    "mpi_operator_tpu/sched/capacity.py",
    "mpi_operator_tpu/runtime/netsim.py",
    # Checkpoint data plane: manifests are canonically encoded and
    # carry no wallclock — run-twice byte-identity (ckpt_smoke) breaks
    # the moment either file reads the clock or the global RNG.
    # (time.sleep for armed slow-faults is injected delay, not a read.)
    "mpi_operator_tpu/ckpt/blobstore.py",
    "mpi_operator_tpu/ckpt/manifest.py",
    # Metrics plane: the store, rules, and straggler scorer run on
    # caller-supplied logical time only — simulated feeds (bench,
    # run-twice smoke) must evaluate bit-identically.  The scraper
    # (obsplane/scrape.py) is deliberately NOT here: its default clock
    # is time.monotonic for live cadence.
    "mpi_operator_tpu/obsplane/store.py",
    "mpi_operator_tpu/obsplane/rules.py",
    "mpi_operator_tpu/obsplane/straggler.py",
    "mpi_operator_tpu/obsplane/fleet.py",
))

_WALLCLOCK_FNS = {("time", "time"), ("time", "time_ns"),
                  ("time", "monotonic"), ("time", "monotonic_ns"),
                  ("datetime", "now"), ("datetime", "utcnow")}


@rule("wallclock-sim", "error",
      "wall-clock read or unseeded randomness inside the deterministic "
      "sim/chaos/topology substrate — byte-stable seeded replay breaks",
      scope=lambda p: p in SIM_SCOPE)
def check_wallclock_sim(ctx: FileContext) -> List[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            pair = (f.value.id, f.attr)
            if pair in _WALLCLOCK_FNS:
                findings.append(Finding(
                    "wallclock-sim", ctx.relpath, node.lineno,
                    f"{pair[0]}.{pair[1]}() in the sim substrate — "
                    f"thread logical time through instead"))
            elif f.value.id == "random":
                if f.attr == "Random":
                    if not node.args and not node.keywords:
                        findings.append(Finding(
                            "wallclock-sim", ctx.relpath, node.lineno,
                            "random.Random() without a seed — pass the "
                            "plan seed"))
                else:
                    findings.append(Finding(
                        "wallclock-sim", ctx.relpath, node.lineno,
                        f"process-global random.{f.attr}() — use a "
                        f"seeded random.Random instance"))
    return findings


# ---------------------------------------------------------------------------
# full-relist-in-loop

@rule("full-relist-in-loop", "error",
      "apiserver .list() lexically inside a loop in the scheduler — "
      "the O(backlog)-per-decision class PR 19 burned down: per-event "
      "paths must consume watch deltas / maintained indexes; a "
      "deliberate resync site takes a pragma",
      scope=lambda p: p.startswith("mpi_operator_tpu/sched/"))
def check_full_relist_in_loop(ctx: FileContext) -> List[Finding]:
    findings = []

    def scan(node, in_loop):
        if isinstance(node, ast.Call) and in_loop:
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "list":
                findings.append(Finding(
                    "full-relist-in-loop", ctx.relpath, node.lineno,
                    ".list() inside a loop — relisting the world per "
                    "iteration is O(backlog) per decision; use the "
                    "watch mirror / maintained index (pragma "
                    "deliberate resyncs)"))
        # A nested def resets loop context (the loop runs the def,
        # not the list call).
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for child in ast.iter_child_nodes(node):
                scan(child, False)
            return
        # ``for x in client.list(...)`` evaluates its iterator ONCE —
        # the iter expression keeps the OUTER loop context; only the
        # body/orelse re-run per iteration.
        if isinstance(node, (ast.For, ast.AsyncFor)):
            scan(node.iter, in_loop)
            for child in ast.iter_child_nodes(node):
                if child is not node.iter:
                    scan(child, True)
            return
        if isinstance(node, ast.While):
            for child in ast.iter_child_nodes(node):
                scan(child, True)
            return
        for child in ast.iter_child_nodes(node):
            scan(child, in_loop)

    scan(ctx.tree, False)
    return findings


# ---------------------------------------------------------------------------
# metrics-catalog (project-level: collect per file, compare vs docs)

# Family names built with dynamic prefixes (f-strings the literal walk
# cannot see) or synthesized straight into the time-series store rather
# than a registry; keep in sync with telemetry/goodput.py and
# obsplane/scrape.py.
DYNAMIC_METRIC_FAMILIES = ("train_goodput_fraction", "train_step_seconds",
                           "mpi_operator_worker_steps_total")

_METRIC_FACTORIES = {"counter", "gauge", "histogram",
                     "counter_vec", "gauge_vec", "histogram_vec"}
_METRIC_CLASSES = {"Counter", "Gauge", "Histogram",
                   "CounterVec", "GaugeVec", "HistogramVec"}

_DOC_ROW = re.compile(r"^\|\s*`([a-z][a-z0-9_]+)(?:\{[^}]*\})?`")


# Obsplane rule classes (obsplane/rules.py): every metric they are
# handed as a string literal is an alert-rule reference the catalog
# must cover — a rule watching a series that will never exist alerts
# on nothing, forever, silently.
_ALERT_RULE_CLASSES = {"ThresholdRule", "BurnRateRule", "AbsentRule",
                       "StallRule", "StragglerRule"}


def _collect_metrics(ctx: FileContext) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = (f.attr if isinstance(f, ast.Attribute)
                 else f.id if isinstance(f, ast.Name) else None)
        if fname in _ALERT_RULE_CLASSES:
            refs = [kw.value.value for kw in node.keywords
                    if kw.arg and (kw.arg == "metric"
                                   or kw.arg.endswith("_metric"))
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)]
            # Rule(name, metric, ...) positional form.
            if len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                refs.append(node.args[1].value)
            for metric in refs:
                ctx.project.alert_rule_refs.append(
                    (metric, ctx.relpath, node.lineno))
        if not (node.args and
                isinstance(node.args[0], ast.Constant) and
                isinstance(node.args[0].value, str)):
            continue
        name = None
        if isinstance(f, ast.Attribute) and f.attr in _METRIC_FACTORIES:
            name = node.args[0].value
        elif isinstance(f, ast.Name) and f.id in _METRIC_CLASSES:
            name = node.args[0].value
        elif isinstance(f, ast.Attribute) and f.attr in _METRIC_CLASSES:
            name = node.args[0].value
        if name and re.match(r"^[a-z][a-z0-9_]+$", name):
            ctx.project.metric_sites.setdefault(
                name, (ctx.relpath, node.lineno))


def _finalize_metrics(project: ProjectContext) -> List[Finding]:
    doc_path = os.path.join(project.root, "docs", "OBSERVABILITY.md")
    if not os.path.exists(doc_path):
        if not project.metric_sites:
            return []  # nothing registered, nothing to document
        return [Finding("metrics-catalog", "docs/OBSERVABILITY.md", 1,
                        "metric catalog file missing")]
    documented: Dict[str, int] = {}
    with open(doc_path) as fh:
        for lineno, line in enumerate(fh, 1):
            m = _DOC_ROW.match(line.strip())
            if m:
                documented.setdefault(m.group(1), lineno)
    registered = dict(project.metric_sites)
    goodput = "mpi_operator_tpu/telemetry/goodput.py"
    if os.path.exists(os.path.join(project.root, goodput)):
        for fam in DYNAMIC_METRIC_FAMILIES:
            registered.setdefault(fam, (goodput, 1))
    findings = []
    for name, (relpath, lineno) in sorted(registered.items()):
        if name not in documented:
            findings.append(Finding(
                "metrics-catalog", relpath, lineno,
                f"metric family {name!r} registered in code but missing "
                f"from the docs/OBSERVABILITY.md catalog"))
    for name, lineno in sorted(documented.items()):
        # Single-word backticked cells (layer names in the lanes table)
        # are not metric families; every real family has an underscore.
        if name not in registered and "_" in name:
            findings.append(Finding(
                "metrics-catalog", "docs/OBSERVABILITY.md", lineno,
                f"metric family {name!r} documented in the catalog but "
                f"registered nowhere in mpi_operator_tpu/"))
    # Alert-rule references: a rule may only watch a family that is
    # both documented and actually registered (or a known dynamic
    # family) — both directions of the catalog contract extend to the
    # alerting policy.
    for metric, relpath, lineno in sorted(project.alert_rule_refs):
        problems = []
        if metric not in documented:
            problems.append("missing from the docs/OBSERVABILITY.md"
                            " catalog")
        if metric not in registered:
            problems.append("registered nowhere in mpi_operator_tpu/")
        if problems:
            findings.append(Finding(
                "metrics-catalog", relpath, lineno,
                f"alert rule references metric {metric!r} "
                + " and ".join(problems)))
    return findings


RULES.append(Rule(
    id="metrics-catalog", severity="error",
    doc="metric families registered in code and the docs/OBSERVABILITY.md "
        "catalog must match exactly, both directions (the obs-smoke drift "
        "check, promoted to static)",
    scope=_in_pkg, check=None, finalize=_finalize_metrics))


# ---------------------------------------------------------------------------
# Engine

WALK_ROOTS = ("mpi_operator_tpu", "tests", "tools", "examples")

# The analyzer's own corpus: these files deliberately spell violations
# (seeded snippets, rule unit tests) and are exempt from scanning —
# linting the lint corpus would force obfuscating every example.
CORPUS_FILES = frozenset((
    "mpi_operator_tpu/analysis/selftest.py",
    "tests/test_analysis.py",
))


def iter_py_files(root: str) -> List[str]:
    out = []
    for top in WALK_ROOTS:
        base = os.path.join(root, top)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), root).replace(os.sep,
                                                                 "/"))
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py"):
            out.append(fn)
    return sorted(set(out) - CORPUS_FILES)


@dataclass
class LintResult:
    findings: List[Finding]            # NOT suppressed: fail the run
    baselined: List[Finding]           # suppressed by baseline entries
    pragma_suppressed: List[Finding]
    stale_baseline: List[str]          # entries that matched nothing
    files_scanned: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline


def _run_rules(root: str, relpaths: Sequence[str]) -> List[Finding]:
    project = ProjectContext(root=root)
    findings: List[Finding] = []
    for relpath in relpaths:
        path = os.path.join(root, relpath)
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=relpath)
        except (OSError, SyntaxError) as exc:
            findings.append(Finding("parse-error", relpath, 1,
                                    f"cannot lint: {exc}"))
            continue
        ctx = FileContext(root=root, relpath=relpath, tree=tree,
                          lines=src.splitlines(), project=project)
        if _in_pkg(relpath):
            _collect_metrics(ctx)
        for r in RULES:
            if r.check is not None and r.scope(relpath):
                findings.extend(r.check(ctx))
    for r in RULES:
        if r.finalize is not None:
            findings.extend(r.finalize(project))
    return findings


def _apply_pragmas(root: str, findings: List[Finding]
                   ) -> Tuple[List[Finding], List[Finding]]:
    kept, suppressed = [], []
    cache: Dict[str, List[str]] = {}
    for f in findings:
        if f.path not in cache:
            try:
                with open(os.path.join(root, f.path),
                          encoding="utf-8") as fh:
                    cache[f.path] = fh.read().splitlines()
            except OSError:
                cache[f.path] = []
        lines = cache[f.path]
        allowed = frozenset()
        if 0 < f.line <= len(lines):
            allowed = _pragma_rules(lines[f.line - 1])
            if f.line >= 2:
                allowed = allowed | _pragma_rules(lines[f.line - 2])
        (suppressed if f.rule in allowed else kept).append(f)
    return kept, suppressed


def _finding_fingerprints(root: str, findings: List[Finding]
                          ) -> List[Tuple[Finding, str]]:
    cache: Dict[str, List[str]] = {}
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        if f.path not in cache:
            try:
                with open(os.path.join(root, f.path),
                          encoding="utf-8") as fh:
                    cache[f.path] = fh.read().splitlines()
            except OSError:
                cache[f.path] = []
        lines = cache[f.path]
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        key = (f.rule, f.path, text.strip())
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        out.append((f, fingerprint(f, text, occ)))
    return out


DEFAULT_BASELINE = "tools/analysis_baseline.txt"


def load_baseline(path: str) -> List[Tuple[str, str, str, str]]:
    """Entries: (rule, path, fingerprint, comment)."""
    entries = []
    if not os.path.exists(path):
        return entries
    with open(path) as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, comment = line.partition("#")
            parts = [p.strip() for p in body.strip().split("|")]
            if len(parts) != 3:
                raise ValueError(
                    f"malformed baseline entry {line!r} — expected "
                    f"'rule-id|path|fingerprint  # reason'")
            entries.append((parts[0], parts[1], parts[2], comment.strip()))
    return entries


def write_baseline(path: str, root: str, findings: List[Finding]) -> None:
    with open(path, "w") as fh:
        fh.write(
            "# Analysis baseline: grandfathered lint findings "
            "(docs/ANALYSIS.md).\n"
            "# Format: rule-id|path|fingerprint  # justification\n"
            "# New violations fail `make analyze`; entries here burn "
            "down — a stale\n"
            "# entry (matching nothing) also fails, so this file only "
            "shrinks.\n")
        for f, fp in _finding_fingerprints(root, findings):
            fh.write(f"{f.rule}|{f.path}|{fp}  # {f.message}\n")


def run_lint(root: str, baseline_path: Optional[str] = None) -> LintResult:
    relpaths = iter_py_files(root)
    raw = _run_rules(root, relpaths)
    raw, pragma_suppressed = _apply_pragmas(root, raw)
    baseline_path = baseline_path or os.path.join(root, DEFAULT_BASELINE)
    entries = load_baseline(baseline_path)
    budget: Dict[Tuple[str, str, str], int] = {}
    for rule_id, path, fp, _comment in entries:
        key = (rule_id, path, fp)
        budget[key] = budget.get(key, 0) + 1
    kept, baselined = [], []
    for f, fp in _finding_fingerprints(root, raw):
        key = (f.rule, f.path, fp)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            baselined.append(f)
        else:
            kept.append(f)
    stale = [f"{rule_id}|{path}|{fp}"
             for (rule_id, path, fp), n in sorted(budget.items())
             if n > 0 for _ in range(n)]
    return LintResult(findings=kept, baselined=baselined,
                      pragma_suppressed=pragma_suppressed,
                      stale_baseline=stale, files_scanned=len(relpaths))


def rule_catalog() -> List[Rule]:
    return list(RULES)
