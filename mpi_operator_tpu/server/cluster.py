"""LocalCluster — the whole control plane in one process.

Wires the in-memory API server, the MPIJob controller, the batch Job
controller and the LocalKubelet into a single runnable unit: the
standalone equivalent of "kind cluster + operator Deployment" from the
reference's e2e suite (test/e2e/e2e_suite_test.go:164-184).
"""

from __future__ import annotations

import time
from typing import Optional

from ..controller.controller import MPIJobController
from ..controller.podgroup import new_pod_group_ctrl
from ..k8s.apiserver import CLOSED, ApiServer, Clientset
from ..runtime.gangsim import GangSchedulerSim
from ..runtime.job_controller import JobController
from ..runtime.kubelet import LocalKubelet


class LocalCluster:
    def __init__(self, gang_scheduler: str = "",
                 cluster_domain: str = "",
                 namespace: Optional[str] = None,
                 threadiness: int = 2,
                 run_pods: bool = True,
                 gang_capacity: Optional[int] = None,
                 client: Optional[Clientset] = None,
                 sched_slices=None,
                 sched_options: Optional[dict] = None,
                 wal_dir: Optional[str] = None):
        # An injected client lets the identical stack run over a remote
        # transport (e.g. KubeApiServer against kube path grammar).
        # ``wal_dir`` makes the in-process apiserver DURABLE (WAL +
        # snapshots, docs/RESILIENCE.md "Durable apiserver") and arms
        # the crash_apiserver/respawn_apiserver chaos surface.
        if client is None:
            client = Clientset(server=ApiServer(wal_dir=wal_dir)) \
                if wal_dir is not None else Clientset()
        self.client = client
        # Respawn config (crash_controller/respawn_controller — the
        # chaos controller_restart surface, docs/RESILIENCE.md): what a
        # fresh controller process would read from its flags.
        self._cluster_domain = cluster_domain
        self._namespace = namespace
        self._sched_options = dict(sched_options or {})
        pod_group_ctrl = new_pod_group_ctrl(gang_scheduler, self.client)
        self._pod_group_ctrl = pod_group_ctrl
        self.controller = MPIJobController(
            self.client, pod_group_ctrl=pod_group_ctrl,
            cluster_domain=cluster_domain, namespace=namespace)
        self.job_controller = JobController(self.client, namespace=namespace)
        self.kubelet = LocalKubelet(self.client, namespace=namespace) \
            if run_pods else None
        # When gang scheduling is on, pods gate on the (simulated)
        # scheduler actually placing the gang — reference e2e contract
        # (e2e_suite_test.go:186-243); gang_capacity models allocatable
        # cluster slots (None = always satisfiable).
        self.gang_sim = GangSchedulerSim(
            self.client, capacity=gang_capacity, namespace=namespace) \
            if gang_scheduler and run_pods else None
        # The in-house gang scheduler (sched/, docs/SCHEDULING.md):
        # `sched_slices` (a list of TpuSlice) turns on quota/fair-share
        # admission over that capacity; queue-labeled MPIJobs then gate
        # on its Queued -> Admitted conditions.
        self.scheduler = None
        if sched_slices:
            from ..sched import GangScheduler, SlicePool
            self.scheduler = GangScheduler(
                self.client, SlicePool(list(sched_slices)),
                kubelet=self.kubelet, namespace=namespace,
                registry=self.controller.metrics.get("registry"),
                **(sched_options or {}))
        self._threadiness = threadiness
        self._started = False

    def start(self) -> "LocalCluster":
        self.controller.run(self._threadiness)
        self.job_controller.start()
        if self.kubelet is not None:
            self.kubelet.start()
        if self.gang_sim is not None:
            self.gang_sim.start()
        if self.scheduler is not None:
            self.scheduler.start()
        self._started = True
        return self

    def stop(self) -> None:
        if not self._started:
            return
        if self.scheduler is not None:
            self.scheduler.stop()
        if self.gang_sim is not None:
            self.gang_sim.stop()
        if self.kubelet is not None:
            self.kubelet.stop()
        self.job_controller.stop()
        self.controller.stop()
        self._started = False

    def __enter__(self) -> "LocalCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- control-plane crash/respawn (chaos restart surface) ---------------
    # The data plane (kubelet pods, serving replicas) and the apiserver
    # survive; only the reconcile/scheduler loops die and come back with
    # EMPTY in-memory state — recovery must rebuild everything from the
    # apiserver (docs/RESILIENCE.md "Macro-soak & crash recovery").

    def crash_controller(self) -> bool:
        """Kill the MPIJob controller and the batch Job controller
        mid-flight.  In-memory state (informer caches, workqueues,
        in-flight maps, the Job controller's pod-name serial) is gone;
        whatever half-finished writes the dying sync made stay in the
        apiserver for the next incarnation to reconcile.  Idempotent:
        a randomized plan may draw overlapping restart faults, and
        crashing an already-dead controller must not take out the one
        the first fault's heal just respawned.  Returns False for that
        no-op case (the chaos log and restart accounting must not
        count a crash that never happened)."""
        if getattr(self, "_controller_down", False):
            return False
        self._controller_down = True
        self.controller.stop()
        self.job_controller.stop()
        return True

    def respawn_controller(self) -> "MPIJobController":
        """Start a fresh controller against the same apiserver.  The
        metrics dict carries over (the monitoring system outlives the
        process; histograms/counters keep accumulating across the
        restart) and registered foreign-kind handlers re-attach, but
        caches, queues and adoption state all rebuild from a cold list:
        level-triggered sync + AlreadyExists-adoption must converge
        without duplicate creates."""
        if not getattr(self, "_controller_down", False):
            return self.controller  # already live (overlapping heals)
        self._controller_down = False
        old = self.controller
        self.controller = MPIJobController(
            self.client, pod_group_ctrl=self._pod_group_ctrl,
            cluster_domain=self._cluster_domain,
            namespace=self._namespace, metrics=old.metrics)
        for prefix, handler in old._kind_handlers.items():
            self.controller.register_kind_handler(prefix, handler)
        self.job_controller = JobController(self.client,
                                            namespace=self._namespace)
        try:
            self.controller.run(self._threadiness)
            self.job_controller.start()
        except Exception:
            # Same crash-loop contract as respawn_scheduler: a respawn
            # into an apiserver outage stays down until retried.
            self._controller_down = True
            raise
        return self.controller

    def crash_scheduler(self) -> bool:
        """Kill the gang scheduler mid-flight: admitted-set, quota
        usage, slice placements, open grace windows and the backfill
        reservation fence all evaporate with the process.  Idempotent,
        like crash_controller; False = nothing to crash."""
        if self.scheduler is None or getattr(self, "_scheduler_down",
                                             False):
            return False
        self._scheduler_down = True
        self.scheduler.stop()
        return True

    def respawn_scheduler(self):
        """Start a fresh GangScheduler over the SAME SlicePool — the
        pool is the hardware (slice topology + spot offline state
        persist across a control-plane restart) while its placements
        were the dead scheduler's in-memory view, so they are wiped and
        rebuilt from API object conditions/annotations: Admitted=True
        jobs re-place on their recorded slices, the reservation
        annotation re-arms the fence, and orphaned partial gangs are
        swept."""
        if self.scheduler is None:
            return None
        if not getattr(self, "_scheduler_down", False):
            return self.scheduler  # already live (overlapping heals)
        self._scheduler_down = False
        from ..sched import GangScheduler
        pool = self.scheduler.pool
        pool.clear_placements()
        self.scheduler = GangScheduler(
            self.client, pool, kubelet=self.kubelet,
            namespace=self._namespace,
            registry=self.controller.metrics.get("registry"),
            **self._sched_options)
        try:
            self.scheduler.start()
        except Exception:
            # Respawned into an apiserver outage: the fresh process
            # cannot re-list.  Restore crash state so a retry after the
            # apiserver comes back re-runs this whole path (the real
            # pod would crash-loop until the apiserver is reachable).
            self._scheduler_down = True
            raise
        return self.scheduler

    def apiserver_durable(self) -> bool:
        """True when the apiserver can survive a crash (WAL-backed)."""
        return getattr(self.client.server, "wal", None) is not None

    def crash_apiserver(self) -> bool:
        """Kill the apiserver itself — the last single point of total
        state loss.  Every verb fails Unavailable, the un-fsynced WAL
        tail is lost (never acknowledged), and every watch stream gets
        the CLOSED sentinel; controller, scheduler, kubelet and fleet
        all survive on their resumed watches once the respawn replays
        the store.  Idempotent; False when already down or when the
        server is memory-only (nothing could be recovered — the chaos
        injector logs that as a no-op)."""
        if not self.apiserver_durable() \
                or getattr(self, "_apiserver_down", False):
            return False
        self._apiserver_down = True
        self.client.server.crash()
        return True

    def respawn_apiserver(self) -> ApiServer:
        """Construct a fresh ApiServer over the SAME wal_dir: replay
        snapshot + WAL tail back to the exact acknowledged revision
        (byte-identical store, uid/ownership indexes, per-kind event
        history), then swap it into the shared clientset — every
        component's next verb and every resumed watch lands on the
        replayed store.  The chaos fault bank carries over (the engine
        installed it on the old incarnation)."""
        if not getattr(self, "_apiserver_down", False):
            return self.client.server  # already live (overlapping heals)
        old = self.client.server
        fresh = ApiServer(clock=old.clock, wal_dir=old.wal_dir,
                          wal_fsync=old.wal_fsync,
                          wal_snapshot_every=old.wal_snapshot_every)
        fresh.fault_injector = old.fault_injector
        self.client.server = fresh
        self._apiserver_down = False
        return fresh

    # -- conveniences ------------------------------------------------------
    def submit(self, mpi_job):
        return self.client.mpi_jobs(
            mpi_job.metadata.namespace or "default").create(mpi_job)

    def wait_for(self, api_version: str, kind: str, namespace: str,
                 predicate, timeout: float = 60.0, describe: str = ""):
        """Watch-driven wait: subscribe FIRST, then evaluate current
        state, then consume events until `predicate(obj)` holds for some
        object — no sleep-polling, no missed-transition races (events
        between the initial list and the stream are already queued)."""
        watch = self.client.server.watch(api_version, kind)
        try:
            for obj in self.client.server.list(api_version, kind, namespace):
                if predicate(obj):
                    return obj
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"{kind} in {namespace} never satisfied: "
                        f"{describe or predicate}")
                ev = watch.next(timeout=min(remaining, 1.0))
                if ev is None:
                    continue
                if ev.type == CLOSED:
                    # Apiserver restarted mid-wait: re-dial against the
                    # respawned store and re-evaluate (the predicate may
                    # have been satisfied inside the outage gap).
                    watch = self._redial(api_version, kind, deadline)
                    for obj in self.client.server.list(api_version,
                                                       kind, namespace):
                        if predicate(obj):
                            return obj
                    continue
                if ev.type == "RELIST":
                    # Watch lost replay continuity (410, obj is None):
                    # re-evaluate current state so a predicate satisfied
                    # inside the gap isn't waited on forever.
                    for obj in self.client.server.list(api_version, kind,
                                                       namespace):
                        if predicate(obj):
                            return obj
                    continue
                if ev.type == "DELETED":
                    continue
                if ev.obj.metadata.namespace == namespace \
                        and predicate(ev.obj):
                    return ev.obj
        finally:
            watch.stop()

    def wait_until(self, api_version: str, kind: str, fn,
                   timeout: float = 60.0, describe: str = "") -> None:
        """Event-driven aggregate wait: re-evaluate `fn()` (any predicate
        over cluster state) after every event on the given kind instead
        of sleep-polling.  A coarse 0.5s tick guards predicates that
        also depend on other kinds."""
        watch = self.client.server.watch(api_version, kind)
        try:
            if fn():
                return
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"never satisfied: {describe or fn}")
                ev = watch.next(timeout=min(remaining, 0.5))
                if ev is not None and ev.type == CLOSED:
                    # Apiserver restarted mid-wait: re-dial so events
                    # keep driving the predicate re-evaluation.
                    watch = self._redial(api_version, kind, deadline)
                if fn():
                    return
        finally:
            watch.stop()

    def _redial(self, api_version: str, kind: str, deadline: float):
        """Re-open a wait helper's watch after a CLOSED stream, riding
        out the crash->respawn window (bounded by the wait deadline)."""
        from ..k8s.apiserver import redial_watch
        return redial_watch(self.client, api_version, kind,
                            deadline=deadline)

    def wait_for_condition(self, namespace: str, name: str, cond_type: str,
                           status: str = "True", timeout: float = 60.0):
        """Watch the MPIJob until the condition appears (e2e helper,
        analogue of waitForCompletion at test/e2e/mpi_job_test.go:595-631)."""
        def has_condition(job):
            return job.metadata.name == name and any(
                c.type == cond_type and c.status == status
                for c in job.status.conditions)

        try:
            return self.wait_for("kubeflow.org/v2beta1", "MPIJob", namespace,
                                 has_condition, timeout=timeout)
        except TimeoutError:
            job = self.client.mpi_jobs(namespace).get(name)
            conds = [(c.type, c.status, c.reason)
                     for c in job.status.conditions]
            raise TimeoutError(
                f"MPIJob {namespace}/{name} never reached "
                f"{cond_type}={status}; conditions={conds}") from None

    def launcher_logs(self, namespace: str, name: str) -> str:
        """Concatenated logs of the launcher Job's pods (debugJob analogue,
        test/e2e/mpi_job_test.go:680)."""
        if self.kubelet is None:
            return ""
        out = []
        for pod in self.client.server.list("v1", "Pod", namespace):
            if pod.metadata.labels.get("job-name") == f"{name}-launcher":
                out.append(self.kubelet.logs(namespace, pod.metadata.name))
        return "\n".join(out)
