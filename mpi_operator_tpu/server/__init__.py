"""Operator server process: options, leader election, healthz/metrics,
and the all-in-one LocalCluster runtime."""

from .cluster import LocalCluster  # noqa: F401
