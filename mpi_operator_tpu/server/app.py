"""Operator server process.

Parity with /root/reference/cmd/mpi-operator/app/server.go:79-314 +
cmd/mpi-operator/main.go: flag parsing, client construction, CRD
existence check, healthz endpoint, optional /metrics endpoint, leader
election gating the controller, namespace scoping, graceful shutdown.
"""

from __future__ import annotations

import http.server
import json
import logging
import os
import socket
import threading
import uuid
from typing import Optional

from .. import version
from ..api import constants
from ..controller.controller import MPIJobController
from ..controller.metrics import new_operator_metrics
from ..controller.podgroup import new_pod_group_ctrl
from ..k8s.apiserver import Clientset
from .leader_election import LeaderElector
from .options import ServerOption, parse_options

logger = logging.getLogger("mpi_operator_tpu.server")


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "mpi-operator-tpu"

    def log_message(self, *args):  # quiet
        pass

    def do_GET(self):
        app: "OperatorApp" = self.server.app  # type: ignore[attr-defined]
        if self.path == "/healthz":
            # Wired to leader-election liveness (server.go:188-204).
            healthy = app.healthy()
            self._respond(200 if healthy else 500,
                          b"ok" if healthy else b"unhealthy")
        elif self.path == "/metrics":
            from ..telemetry.metrics import expose_with_defaults
            body = expose_with_defaults(app.metrics["registry"]).encode()
            self._respond(200, body, "text/plain; version=0.0.4")
        elif self.path == "/version":
            self._respond(200, json.dumps(version.info()).encode(),
                          "application/json")
        else:
            self._respond(404, b"not found")

    def _respond(self, code: int, body: bytes,
                 content_type: str = "text/plain"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _wants_remote(opt: ServerOption) -> bool:
    return bool(opt.master_url or opt.kubeconfig
                or os.environ.get("KUBERNETES_SERVICE_HOST"))


def build_api_transport(opt: ServerOption):
    """Client construction (server.go:108,258-299 equivalent): kubeconfig,
    explicit --master (kube or native grammar, autodetected by default),
    or in-cluster serviceaccount config — in that precedence order."""
    from ..k8s.kube_transport import (KubeApiServer, KubeConfig,
                                      probe_is_kube)

    def fatal_auth(exc):
        # Reference parity (mpi_job_controller.go:374-388): persistent
        # 401/403 on watch streams -> die so the pod restarts with fresh
        # serviceaccount credentials/RBAC.
        logger.error("watch auth failure (%s); exiting for credential "
                     "refresh", exc)
        os._exit(1)

    if opt.kubeconfig:
        cfg = KubeConfig.from_kubeconfig(opt.kubeconfig)
        if opt.master_url:
            cfg.server = opt.master_url.rstrip("/")
        return KubeApiServer(cfg, auth_failure_handler=fatal_auth)
    if opt.master_url:
        grammar = opt.api_grammar
        if grammar == "auto":
            grammar = "kube" if probe_is_kube(opt.master_url) else "native"
        if grammar == "native":
            from ..k8s.http_api import RemoteApiServer
            return RemoteApiServer(opt.master_url)
        token = ""
        if opt.token_file:
            with open(opt.token_file) as f:
                token = f.read().strip()
        return KubeApiServer(KubeConfig(
            server=opt.master_url, token=token, ca_file=opt.ca_file or None,
            insecure_skip_tls_verify=opt.insecure_skip_tls_verify),
            auth_failure_handler=fatal_auth)
    return KubeApiServer(KubeConfig.in_cluster(),
                         auth_failure_handler=fatal_auth)


class OperatorApp:
    """app.Run equivalent (server.go:79-188)."""

    def __init__(self, opt: ServerOption, clientset: Optional[Clientset] = None):
        self.opt = opt
        if clientset is None:
            clientset = Clientset(server=build_api_transport(opt)) \
                if _wants_remote(opt) else Clientset()
        self.client = clientset
        self.metrics = new_operator_metrics()
        # Build identity on /metrics from process start — the shard
        # count is recalled by the controller once leadership is won.
        from ..telemetry.metrics import record_build_info
        record_build_info()
        self.controller: Optional[MPIJobController] = None
        self._http: Optional[http.server.ThreadingHTTPServer] = None
        self._metrics_http: Optional[http.server.ThreadingHTTPServer] = None
        identity = f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"
        self.elector = LeaderElector(
            self.client, identity=identity,
            namespace=opt.lock_namespace or opt.namespace or "default",
            on_started_leading=self._start_controller,
            on_stopped_leading=self._stop_controller)

    # -- health -------------------------------------------------------------
    def healthy(self) -> bool:
        return self.elector._thread is not None and \
            self.elector._thread.is_alive()

    # -- CRD existence check (server.go:121-124,302-314) --------------------
    def check_crd_exists(self) -> bool:
        """With the in-memory API server the MPIJob kind always exists;
        against a real cluster this probes the CRD object itself
        (server.go:302-314) and falls back to a list probe."""
        from ..k8s.kube_transport import KubeApiServer
        server = self.client.server
        if isinstance(server, KubeApiServer):
            if server.check_crd("mpijobs.kubeflow.org"):
                return True
            logger.error("CRD mpijobs.kubeflow.org not found; install "
                         "manifests/base/kubeflow.org_mpijobs.yaml first")
            return False
        try:
            self.client.mpi_jobs(self.opt.namespace or "default").list()
            return True
        except Exception as exc:
            logger.error("CRD check failed: %s", exc)
            return False

    # -- lifecycle ------------------------------------------------------------
    def _start_controller(self) -> None:
        logger.info("became leader, starting controller")
        self.metrics["is_leader"].set(1)
        pod_group_ctrl = new_pod_group_ctrl(self.opt.gang_scheduling_name,
                                            self.client)
        self.controller = MPIJobController(
            self.client,
            pod_group_ctrl=pod_group_ctrl,
            cluster_domain=self.opt.cluster_domain,
            namespace=self.opt.namespace or None,
            metrics=self.metrics)
        self.controller.run(self.opt.threadiness)

    def _stop_controller(self) -> None:
        logger.warning("lost leadership, stopping controller")
        self.metrics["is_leader"].set(0)
        if self.controller is not None:
            self.controller.stop()
            self.controller = None

    def _serve(self, port: int, name: str):
        # Bind all interfaces: kubelet probes and Prometheus scrape the
        # pod IP, not loopback (reference listens on :8080 / :monitoring).
        srv = http.server.ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        srv.app = self  # type: ignore[attr-defined]
        thread = threading.Thread(target=srv.serve_forever, daemon=True,
                                  name=name)
        thread.start()
        return srv

    def start(self) -> "OperatorApp":
        if not self.check_crd_exists():
            raise SystemExit(1)
        if self.opt.healthz_port:
            self._http = self._serve(self.opt.healthz_port, "healthz")
        # A distinct metrics listener, as in the reference (main.go:29-40
        # serves /metrics on --monitoring-port when nonzero).
        if self.opt.monitoring_port and \
                self.opt.monitoring_port != self.opt.healthz_port:
            self._metrics_http = self._serve(self.opt.monitoring_port,
                                             "metrics")
        self.elector.run()
        return self

    def stop(self) -> None:
        self.elector.stop()
        self._stop_controller()
        for srv in (self._http, self._metrics_http):
            if srv is not None:
                srv.shutdown()
                srv.server_close()


def run(argv=None) -> OperatorApp:
    """main() equivalent (cmd/mpi-operator/main.go:42)."""
    opt = parse_options(argv)
    if opt.print_version:
        version.print_version_and_exit()
    app = OperatorApp(opt)
    return app.start()
