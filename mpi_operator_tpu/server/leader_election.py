"""Lease-based leader election.

Parity with the reference's leaderelection.RunOrDie setup
(cmd/mpi-operator/app/server.go:206-253: LeaseLock "mpi-operator",
leaseDuration 15s / renewDeadline 5s / retryPeriod 3s, release on
cancel): multiple operator replicas coordinate through a Lease object in
the API server; only the leader runs the controller.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..k8s.apiserver import (TRANSPORT_ERRORS, Clientset, is_conflict,
                             is_not_found)
from ..k8s.meta import Clock, ObjectMeta

LEASE_NAME = "mpi-operator"
DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 5.0
DEFAULT_RETRY_PERIOD = 3.0


@dataclass
class Lease:
    api_version: str = "coordination.k8s.io/v1"
    kind: str = "Lease"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict = field(default_factory=dict)


class LeaderElector:
    def __init__(self, clientset: Clientset, identity: str,
                 namespace: str = "default",
                 name: str = LEASE_NAME,
                 lease_duration: float = DEFAULT_LEASE_DURATION,
                 renew_deadline: float = DEFAULT_RENEW_DEADLINE,
                 retry_period: float = DEFAULT_RETRY_PERIOD,
                 on_started_leading: Optional[Callable] = None,
                 on_stopped_leading: Optional[Callable] = None,
                 clock: Optional[Clock] = None):
        self.client = clientset
        self.identity = identity
        self.namespace = namespace
        self.name = name
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.clock = clock or Clock()
        self.is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lease manipulation -------------------------------------------------
    def _try_acquire_or_renew(self) -> bool:
        now = self.clock.now()
        leases = self.client.leases(self.namespace)
        try:
            lease = leases.get(self.name)
        except Exception as exc:
            if not is_not_found(exc):
                return False
            lease = Lease(metadata=ObjectMeta(name=self.name,
                                              namespace=self.namespace),
                          spec={"holderIdentity": self.identity,
                                "acquireTime": now.isoformat(),
                                "renewTime": now.isoformat(),
                                "leaseDurationSeconds": self.lease_duration})
            try:
                leases.create(lease)
                return True
            except TRANSPORT_ERRORS:
                return False  # lost the create race / API weather

        holder = lease.spec.get("holderIdentity")
        renew = lease.spec.get("renewTime")
        expired = True
        if renew is not None:
            import datetime
            last = datetime.datetime.fromisoformat(renew)
            expired = (now - last).total_seconds() > self.lease_duration
        # A voluntarily-released lease (empty holder) is immediately free.
        if holder and holder != self.identity and not expired:
            return False
        lease.spec["holderIdentity"] = self.identity
        lease.spec["renewTime"] = now.isoformat()
        if holder != self.identity:
            lease.spec["acquireTime"] = now.isoformat()
        try:
            leases.update(lease)
            return True
        except Exception as exc:
            if is_conflict(exc):
                return False
            raise

    def release(self) -> None:
        """Voluntarily release on shutdown (ReleaseOnCancel,
        server.go:236-239)."""
        if not self.is_leader:
            return
        try:
            lease = self.client.leases(self.namespace).get(self.name)
            if lease.spec.get("holderIdentity") == self.identity:
                lease.spec["holderIdentity"] = ""
                # Drop renewTime too so standbys take over immediately
                # instead of waiting out the lease duration.
                lease.spec.pop("renewTime", None)
                self.client.leases(self.namespace).update(lease)
        except TRANSPORT_ERRORS:
            pass  # best-effort release; the lease expires on its own
        self.is_leader = False

    # -- run loop ------------------------------------------------------------
    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="leader-elector")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            # Any API failure counts as "did not acquire/renew": a leader
            # steps down (on_stopped_leading fires) instead of the thread
            # dying with is_leader stuck True (split-brain guard).
            try:
                acquired = self._try_acquire_or_renew()
            except Exception:
                acquired = False
            if acquired and not self.is_leader:
                self.is_leader = True
                if self.on_started_leading:
                    self.on_started_leading()
            elif not acquired and self.is_leader:
                # Lost the lease (leaderelection fatal path,
                # server.go:240-244).
                self.is_leader = False
                if self.on_stopped_leading:
                    self.on_stopped_leading()
            interval = (self.renew_deadline / 2 if self.is_leader
                        else self.retry_period)
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        self.release()
