"""Server options / flags.

Parity with /root/reference/cmd/mpi-operator/app/options/options.go:31-96
(ServerOption + AddFlags): namespace (or KUBEFLOW_NAMESPACE env),
threadiness, monitoring port, gang-scheduling name, lock namespace,
QPS/burst knobs, cluster domain, plus -version.
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field

from ..api import constants


@dataclass
class ServerOption:
    """options.go:31-59."""
    kubeconfig: str = ""
    master_url: str = ""
    threadiness: int = 2
    monitoring_port: int = 0
    print_version: bool = False
    gang_scheduling_name: str = ""
    namespace: str = ""                       # "" = all namespaces
    lock_namespace: str = ""
    healthz_port: int = 8080
    cluster_domain: str = ""
    kube_api_qps: float = 5.0
    kube_api_burst: int = 10
    controller_rate_limit: float = 10.0
    controller_burst: int = 100
    # Transport selection for --master: "kube" = real kube-apiserver REST
    # grammar, "native" = the framework's own ApiHttpServer protocol,
    # "auto" = probe GET /apis (an APIGroupList means kube).
    api_grammar: str = "auto"
    token_file: str = ""
    ca_file: str = ""
    insecure_skip_tls_verify: bool = False


def add_flags(parser: argparse.ArgumentParser) -> None:
    """AddFlags (options.go:61-96)."""
    parser.add_argument("--kubeconfig", default="",
                        help="Path to a kubeconfig. Only required if"
                             " out-of-cluster.")
    parser.add_argument("--master", dest="master_url", default="",
                        help="The address of the API server.")
    parser.add_argument("--threadiness", type=int, default=2,
                        help="How many worker goroutines process the work"
                             " queue.")
    parser.add_argument("--monitoring-port", type=int, default=0,
                        help="Port for the metrics endpoint; 0 disables.")
    parser.add_argument("--version", dest="print_version",
                        action="store_true", help="Print version and exit.")
    parser.add_argument("--gang-scheduling", dest="gang_scheduling_name",
                        default="",
                        help="Gang scheduler: 'volcano' or a"
                             " scheduler-plugins scheduler name.")
    parser.add_argument("--namespace", default="",
                        help="Namespace to monitor (empty = all; env"
                             " KUBEFLOW_NAMESPACE).")
    parser.add_argument("--lock-namespace", default="",
                        help="Namespace for the leader-election lock.")
    parser.add_argument("--healthz-port", type=int, default=8080,
                        help="Port for the healthz endpoint.")
    parser.add_argument("--cluster-domain", default="",
                        help="Cluster DNS domain appended to host FQDNs.")
    parser.add_argument("--kube-api-qps", type=float, default=5.0)
    parser.add_argument("--kube-api-burst", type=int, default=10)
    parser.add_argument("--controller-rate-limit", type=float, default=10.0)
    parser.add_argument("--controller-burst", type=int, default=100)
    parser.add_argument("--api-grammar", dest="api_grammar", default="auto",
                        choices=("auto", "kube", "native"),
                        help="Wire protocol for --master: real kube REST"
                             " grammar, the native protocol, or autodetect.")
    parser.add_argument("--token-file", dest="token_file", default="",
                        help="Bearer token file for the kube transport.")
    parser.add_argument("--ca-file", dest="ca_file", default="",
                        help="CA bundle for the kube transport.")
    parser.add_argument("--insecure-skip-tls-verify", action="store_true",
                        dest="insecure_skip_tls_verify",
                        help="Skip TLS verification (kube transport).")


def parse_options(argv=None) -> ServerOption:
    parser = argparse.ArgumentParser(prog="mpi-operator-tpu")
    add_flags(parser)
    ns = parser.parse_args(argv)
    opt = ServerOption(**{f: getattr(ns, f) for f in
                          ServerOption.__dataclass_fields__
                          if hasattr(ns, f)})
    # Env override (options.go:69).
    if not opt.namespace:
        opt.namespace = os.environ.get(constants.ENV_KUBEFLOW_NAMESPACE, "")
    return opt
