"""ssh — a libssh-backed client with OpenSSH's CLI shape.

The launcher's rsh agent default is ``ssh`` (rsh_launcher.py; reference
mpirun uses `ssh <host> <cmd>` with OMPI_MCA_plm_rsh_args, e.g.
`-o ConnectionAttempts=10`, mpi_job_controller.go:181-215).  The image
has no OpenSSH binary, so this module is that agent: same positional
grammar (``[user@]host command...``), the ``-p/-i/-l/-o/-q`` flags the
operator's env matrices use, publickey auth with the per-job Secret's
private key, remote stdout/stderr streamed through, and the remote exit
status as the local exit code — the contract mpirun's rsh tree and
rsh_launcher both assume.

    python -m mpi_operator_tpu.bootstrap.ssh_client \
        -p 2222 -i ~/.ssh/id_rsa -o ConnectionAttempts=10 host cmd...
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from ctypes import create_string_buffer
from typing import Optional

from . import libssh as L


def run(host: str, command: str, port: int = 22,
        identity: Optional[str] = None, user: Optional[str] = None,
        connection_attempts: int = 1, timeout_s: int = 30,
        out=None, err=None) -> int:
    """Execute ``command`` on ``host``; returns the remote exit status.
    Raises SSHError when the transport itself fails."""
    out = out or sys.stdout.buffer
    # Hermetic runtime: cluster-DNS worker names resolve through netsim
    # to per-pod loopback IPs (the sshd side binds the same IP via
    # --bind-pod-ip); outside a sandbox the system resolver is used.
    if os.environ.get("K_SANDBOX_DIR"):
        try:
            from ..runtime import netsim
            host = netsim.resolve(host) or host
        except (ImportError, OSError):
            pass  # no netsim/socket weather: use the name as-is
    if not identity:
        raise L.SSHError("no identity file provided")
    key = L.import_privkey_file(identity)  # fail before any connect
    try:
        last_error = "connect never attempted"
        for attempt in range(max(1, connection_attempts)):
            if attempt:
                time.sleep(min(1.0 * attempt, 5.0))
            session = L.lib.ssh_new()
            try:
                L._opt_str(session, L.SSH_OPTIONS_HOST, host)
                L._opt_str(session, L.SSH_OPTIONS_PORT_STR, str(port))
                if user:
                    L._opt_str(session, L.SSH_OPTIONS_USER, user)
                # StrictHostKeyChecking=no + no config files: worker host
                # keys are ephemeral by design (see sshd.py docstring).
                L._opt_int(session, L.SSH_OPTIONS_STRICTHOSTKEYCHECK, 0)
                L._opt_int(session, L.SSH_OPTIONS_PROCESS_CONFIG, 0)
                L._opt_str(session, L.SSH_OPTIONS_KNOWNHOSTS, "/dev/null")
                L._opt_long(session, L.SSH_OPTIONS_TIMEOUT, timeout_s)
                if L.lib.ssh_connect(session) != L.SSH_OK:
                    last_error = L.session_error(session)
                    continue
                try:
                    rc = L.lib.ssh_userauth_publickey(session, None, key)
                    if rc != L.SSH_AUTH_SUCCESS:
                        last_error = (f"publickey auth failed (rc={rc}): "
                                      f"{L.session_error(session)}")
                        continue
                    return _exec(session, command, out, err)
                finally:
                    L.lib.ssh_disconnect(session)
            finally:
                L.lib.ssh_free(session)
        raise L.SSHError(f"ssh {host}:{port}: {last_error}")
    finally:
        L.lib.ssh_key_free(key)


def _exec(session, command: str, out, err=None) -> int:
    err = err or sys.stderr.buffer
    channel = L.lib.ssh_channel_new(session)
    if not channel:
        raise L.SSHError("cannot allocate channel")
    try:
        if L.lib.ssh_channel_open_session(channel) != L.SSH_OK:
            raise L.SSHError(
                f"channel open: {L.session_error(session)}")
        if L.lib.ssh_channel_request_exec(channel, command.encode()) \
                != L.SSH_OK:
            raise L.SSHError(f"exec request: {L.session_error(session)}")
        buf = create_string_buffer(65536)
        # Drain BOTH streams (a standard sshd keeps stderr separate;
        # leaving it unread would drop rank diagnostics and stall the
        # remote on a full window).  Alternate short timed reads until
        # both report EOF/closed.
        def drain(is_stderr: int, sink) -> bool:
            """One timed read; True when this stream is finished."""
            n = L.lib.ssh_channel_read_timeout(
                channel, buf, len(buf) - 1, is_stderr, 50)
            if n > 0:
                sink.write(buf.raw[:n])
                sink.flush()
                return False
            if n < 0 and n != L.SSH_AGAIN:
                return True  # error / channel closed
            # n == 0: EOF or just the timeout with no data.
            return bool(L.lib.ssh_channel_is_eof(channel))

        done_out = done_err = False
        while not (done_out and done_err):
            if not done_out:
                done_out = drain(0, out)
            if not done_err:
                done_err = drain(1, err)
        L.lib.ssh_channel_send_eof(channel)
        status = L.lib.ssh_channel_get_exit_status(channel)
        # -1 means "no exit-status received" (connection torn down).
        return status if status >= 0 else 255
    finally:
        L.lib.ssh_channel_close(channel)
        L.lib.ssh_channel_free(channel)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ssh", add_help=False)
    ap.add_argument("-p", "--port", type=int, default=22)
    ap.add_argument("-i", "--identity", default=None)
    ap.add_argument("-l", "--login", default=None)
    ap.add_argument("-o", "--option", action="append", default=[])
    ap.add_argument("-q", action="store_true")  # compat: quiet
    ap.add_argument("host")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    host, user = args.host, args.login
    if "@" in host:
        user, host = host.split("@", 1)

    attempts = 1
    for opt in args.option:
        k, _, v = opt.partition("=")
        if k.strip().lower() == "connectionattempts" and v.strip().isdigit():
            attempts = int(v)
        # StrictHostKeyChecking / UserKnownHostsFile are accepted and
        # already the built-in behavior; other options are ignored like
        # unknown-but-harmless config (BatchMode etc.).

    command = " ".join(args.command) if args.command else ""
    if not command:
        print("ssh_client: interactive shells unsupported (exec only)",
              file=sys.stderr)
        return 2
    try:
        return run(host, command, port=args.port, identity=args.identity,
                   user=user, connection_attempts=attempts)
    except L.SSHError as exc:
        print(f"ssh_client: {exc}", file=sys.stderr)
        return 255


if __name__ == "__main__":
    sys.exit(main())
