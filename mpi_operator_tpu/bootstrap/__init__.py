"""Workload-side bootstrap helpers (the in-pod half of the contract)."""

from .distributed import (initialize_from_env, process_env,  # noqa: F401
                          launch_latency_seconds, submit_time, ProcessEnv)
