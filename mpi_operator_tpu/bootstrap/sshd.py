"""sshd — an exec-only SSH daemon over libssh for worker pods.

The image ships no OpenSSH server, so this is the worker-side process
behind the reference's `/usr/sbin/sshd -De` default worker command
(mpi_job_controller.go:1529-1531; build/base/Dockerfile:3-24): it
listens on a high port, authenticates clients by public key against the
operator-generated authorized_keys projection of the per-job SSH
Secret, and executes the requested command with stdout/stderr streamed
back and the exit status propagated — everything mpirun's rsh tree
needs from a remote shell daemon.

    python -m mpi_operator_tpu.bootstrap.sshd \
        --port 2222 --authorized-keys ~/.ssh/authorized_keys \
        [--host-key pem] [--bind 127.0.0.1] [-D] [--ready-file f]

Matches build/ssh/sshd_config semantics: pubkey-only auth (no
passwords), no PTY, no shell — exec requests only.
"""

from __future__ import annotations

import argparse
import logging
import os
import socket
import subprocess
import sys
import threading
import time
from ctypes import byref, c_void_p, create_string_buffer
from typing import Optional

from . import libssh as L

logger = logging.getLogger("mpi_operator_tpu.bootstrap.sshd")


def parse_chaos_spec(spec: str) -> tuple:
    """Parse the CHAOS_SSHD env knob into (drop_first_n, delay_s).

    ``drop:N`` refuses the first N connections (flaky daemon mid-
    restart), ``slow:S`` sleeps S seconds before serving each session
    (overloaded node); comma-combine: ``drop:2,slow:0.5``.  Invalid
    parts are ignored — chaos must never break a production start."""
    drop, delay = 0, 0.0
    for part in (spec or "").split(","):
        key, _, val = part.strip().partition(":")
        try:
            if key == "drop":
                drop = int(val)
            elif key == "slow":
                delay = float(val)
        except ValueError:
            continue
    return drop, delay


class SSHServer:
    """Threaded exec-only SSH server.

    ``authorized_keys`` — path to the authorized_keys file (re-read per
    connection, like sshd, so Secret rotation takes effect live).
    ``host_key_path`` — PEM private key; generated in-memory when None
    (host identity is per-process then, which clients in this framework
    accept: the rsh agent pins no known_hosts, exactly like the
    reference's `StrictHostKeyChecking no` in OMPI rsh args).
    """

    def __init__(self, port: int, authorized_keys: str,
                 host_key_path: Optional[str] = None,
                 bind_addr: str = "127.0.0.1"):
        self.port = port
        self.bind_addr = bind_addr
        self.authorized_keys = authorized_keys
        self._host_key = (L.import_privkey_file(host_key_path)
                          if host_key_path else self._generate_host_key())
        self._bind = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conn_threads: list = []
        # Chaos knobs (docs/RESILIENCE.md): the rsh tree must tolerate a
        # daemon that is briefly flaky (drops early connections) or slow
        # (delayed key exchange) — mpirun retries rsh; the agent's
        # connect loop owns the backoff.
        self._chaos_drop, self._chaos_delay = parse_chaos_spec(
            os.environ.get("CHAOS_SSHD", ""))
        self._chaos_seen = 0
        self._chaos_lock = threading.Lock()

    @staticmethod
    def _generate_host_key():
        # enum ssh_keytypes_e: ECDSA_P256 = 8 in libssh 0.10's ABI — but
        # generate via the portable path: type ECDSA(4)+bits works across
        # builds; fall back to P256 enum if the legacy enum is rejected.
        key = c_void_p()
        for ktype, bits in ((4, 256), (8, 256)):  # ECDSA legacy, P256
            if L.lib.ssh_pki_generate(ktype, bits, byref(key)) == L.SSH_OK:
                return key
        raise L.SSHError("cannot generate host key")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "SSHServer":
        self._bind = L.lib.ssh_bind_new()
        L.lib.ssh_bind_options_set(self._bind, L.SSH_BIND_OPTIONS_BINDADDR,
                                   self.bind_addr.encode())
        L.lib.ssh_bind_options_set(self._bind,
                                   L.SSH_BIND_OPTIONS_BINDPORT_STR,
                                   str(self.port).encode())
        rc = L.lib.ssh_bind_options_set(self._bind,
                                        L.SSH_BIND_OPTIONS_IMPORT_KEY,
                                        self._host_key)
        if rc != L.SSH_OK:
            raise L.SSHError("cannot set host key on bind")
        if L.lib.ssh_bind_listen(self._bind) != L.SSH_OK:
            raise L.SSHError(
                f"listen {self.bind_addr}:{self.port}: "
                f"{L.session_error(self._bind)}")
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="sshd-accept")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # Unblock ssh_bind_accept with a throwaway connection.
        try:
            with socket.create_connection((self.bind_addr, self.port),
                                          timeout=2):
                pass
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        for t in list(self._conn_threads):
            t.join(timeout=5)
        if self._bind is not None:
            if self._thread is not None and self._thread.is_alive():
                # Accept thread still inside ssh_bind_accept: freeing the
                # bind under it would be use-after-free; leak it instead
                # (process is exiting anyway).
                logger.warning("accept loop did not stop; leaking bind")
                return
            L.lib.ssh_bind_free(self._bind)
            self._bind = None

    # -- accept + per-connection protocol ----------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            session = L.lib.ssh_new()
            if L.lib.ssh_bind_accept(self._bind, session) != L.SSH_OK:
                L.lib.ssh_free(session)
                if self._stop.is_set():
                    return
                continue
            if self._stop.is_set():
                L.lib.ssh_free(session)
                return
            t = threading.Thread(target=self._serve_session,
                                 args=(session,), daemon=True)
            t.start()
            # Prune finished connections so a long-lived daemon does not
            # retain one Thread object per connection forever.
            self._conn_threads = [c for c in self._conn_threads
                                  if c.is_alive()]
            self._conn_threads.append(t)

    def _serve_session(self, session) -> None:
        try:
            with self._chaos_lock:
                self._chaos_seen += 1
                seen = self._chaos_seen
            if seen <= self._chaos_drop:
                logger.info("chaos: dropping connection %d/%d", seen,
                            self._chaos_drop)
                return  # finally disconnects; the client retries
            if self._chaos_delay > 0:
                time.sleep(self._chaos_delay)
            if L.lib.ssh_handle_key_exchange(session) != L.SSH_OK:
                logger.warning("kex failed: %s", L.session_error(session))
                return
            authed = self._authenticate(session)
            if not authed:
                return
            self._serve_channels(session)
        finally:
            L.lib.ssh_disconnect(session)
            L.lib.ssh_free(session)

    def _authenticate(self, session) -> bool:
        """Publickey-only auth against authorized_keys (two-phase probe
        then signature, per RFC 4252 §7)."""
        try:
            allowed = L.read_authorized_keys(self.authorized_keys)
        except OSError as exc:
            logger.error("authorized_keys unreadable: %s", exc)
            allowed = []
        try:
            while True:
                msg = L.lib.ssh_message_get(session)
                if not msg:
                    return False  # client gave up
                try:
                    mtype = L.lib.ssh_message_type(msg)
                    if mtype == L.SSH_REQUEST_AUTH and \
                            L.lib.ssh_message_subtype(msg) == \
                            L.SSH_AUTH_METHOD_PUBLICKEY:
                        offered = L.lib.ssh_message_auth_pubkey(msg)
                        state = L.lib.ssh_message_auth_publickey_state(msg)
                        ok = offered and any(
                            L.keys_equal(offered, k) for k in allowed)
                        if ok and state == L.SSH_PUBLICKEY_STATE_NONE:
                            L.lib.ssh_message_auth_reply_pk_ok_simple(msg)
                            continue
                        if ok and state == L.SSH_PUBLICKEY_STATE_VALID:
                            L.lib.ssh_message_auth_reply_success(msg, 0)
                            return True
                    # Anything else (incl. password): publickey only.
                    L.lib.ssh_message_auth_set_methods(
                        msg, L.SSH_AUTH_METHOD_PUBLICKEY)
                    L.lib.ssh_message_reply_default(msg)
                finally:
                    L.lib.ssh_message_free(msg)
        finally:
            for k in allowed:
                L.lib.ssh_key_free(k)

    def _serve_channels(self, session) -> None:
        """One session channel, env + exec requests (sshd_config:
        no PTY, no shell, no forwarding)."""
        channel = None
        env: dict = {}
        while True:
            msg = L.lib.ssh_message_get(session)
            if not msg:
                return
            command = None
            try:
                mtype = L.lib.ssh_message_type(msg)
                sub = L.lib.ssh_message_subtype(msg)
                if mtype == L.SSH_REQUEST_CHANNEL_OPEN \
                        and sub == L.SSH_CHANNEL_SESSION:
                    channel = \
                        L.lib.ssh_message_channel_request_open_reply_accept(
                            msg)
                elif mtype == L.SSH_REQUEST_CHANNEL and channel:
                    if sub == L.SSH_CHANNEL_REQUEST_ENV:
                        name = L.lib.ssh_message_channel_request_env_name(msg)
                        val = L.lib.ssh_message_channel_request_env_value(msg)
                        if name:
                            env[name.decode()] = (val or b"").decode()
                        L.lib.ssh_message_channel_request_reply_success(msg)
                    elif sub == L.SSH_CHANNEL_REQUEST_EXEC:
                        cmd = L.lib.ssh_message_channel_request_command(msg)
                        L.lib.ssh_message_channel_request_reply_success(msg)
                        command = (cmd or b"").decode()
                    else:
                        L.lib.ssh_message_reply_default(msg)
                else:
                    L.lib.ssh_message_reply_default(msg)
            finally:
                L.lib.ssh_message_free(msg)
            if command is not None:
                self._run_exec(channel, command, env)
                return

    def _run_exec(self, channel, command: str, extra_env: dict) -> None:
        """Execute like sshd: through the shell, env merged, stdout and
        stderr streamed over the channel, exit status sent back."""
        logger.info("exec: %s", command)
        env = dict(os.environ)
        env.update(extra_env)
        proc = subprocess.Popen(
            ["/bin/sh", "-c", command], env=env,
            stdin=subprocess.DEVNULL,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)

        # libssh sessions are not thread-safe: the two pumps must never
        # be inside ssh_channel_write concurrently (cipher/sequence
        # state would race) — one lock serializes them.
        write_lock = threading.Lock()

        def pump(stream, is_stderr: int):
            for chunk in iter(lambda: stream.read(4096), b""):
                with write_lock:
                    if is_stderr:
                        L.lib.ssh_channel_write_stderr(channel, chunk,
                                                       len(chunk))
                    else:
                        L.lib.ssh_channel_write(channel, chunk, len(chunk))

        threads = [threading.Thread(target=pump, args=(proc.stdout, 0)),
                   threading.Thread(target=pump, args=(proc.stderr, 1))]
        for t in threads:
            t.start()
        rc = proc.wait()
        for t in threads:
            t.join()
        L.lib.ssh_channel_request_send_exit_status(channel, rc)
        L.lib.ssh_channel_send_eof(channel)
        L.lib.ssh_channel_close(channel)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="sshd", description=__doc__)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--bind", default="127.0.0.1")
    ap.add_argument("--authorized-keys", required=True)
    ap.add_argument("--host-key", default=None,
                    help="PEM host key (generated when omitted)")
    ap.add_argument("-D", "--foreground", action="store_true",
                    help="compat flag (always foreground)")
    ap.add_argument("-e", "--log-stderr", action="store_true",
                    help="compat flag (always logs to stderr)")
    ap.add_argument("--ready-file", default=None,
                    help="touched once listening (test synchronization)")
    ap.add_argument("--bind-pod-ip", action="store_true",
                    help="bind this pod's netsim per-pod IP (hermetic"
                         " runtime: K_POD_NAMESPACE/K_POD_NAME)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="sshd[%(process)d]: %(message)s")

    bind = args.bind
    if args.bind_pod_ip:
        from ..runtime import netsim
        bind = netsim.pod_ip(os.environ["K_POD_NAMESPACE"],
                             os.environ["K_POD_NAME"])
    server = SSHServer(args.port, args.authorized_keys,
                       host_key_path=args.host_key, bind_addr=bind)
    server.start()
    logger.info("listening on %s:%d", bind, args.port)
    if args.ready_file:
        with open(args.ready_file, "w", encoding="utf-8") as fh:
            fh.write(str(args.port))
    try:
        threading.Event().wait()  # -De: serve until killed
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
