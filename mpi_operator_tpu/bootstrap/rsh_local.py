"""Local rsh agent: run the "remote" command in-place.

``rsh_launcher --rsh "python -m mpi_operator_tpu.bootstrap.rsh_local"``
turns the SSH gang launch into local process spawns — the single-host /
hermetic-CI analogue of mpirun's ``plm_rsh_agent`` override.  Contract
matches rsh/ssh: ``agent HOST CMD...`` executes CMD (the host argument
is accepted and ignored).
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 2:
        print("usage: rsh_local HOST CMD...", file=sys.stderr)
        return 2
    cmd = argv[1:]  # drop the host
    os.execvp(cmd[0], cmd)


if __name__ == "__main__":
    raise SystemExit(main())
