"""Elastic host discovery — the workload-side consumer of the
controller's discover_hosts.sh artifact.

Parity with the Horovod elastic flow (reference
proposals/elastic-horovod.md:21-30: horovodrun polls
/etc/mpi/discover_hosts.sh).  The controller regenerates the script from
*running* worker pods on every sync; this module parses it and watches it
for membership changes so workloads can react (re-form the world at a
checkpoint boundary — see docs/proposals/elastic-multislice.md).
"""

from __future__ import annotations

import os
import time
from typing import Iterator, List, Optional

from ..telemetry.metrics import default_registry

DISCOVER_SCRIPT = "discover_hosts.sh"


def _elastic_metrics(registry=None):
    """Get-or-create the elastic counters on `registry` (default: the
    process default registry, so they ride any /metrics endpoint the
    process serves)."""
    registry = registry or default_registry()
    return {
        "resyncs": registry.counter(
            "elastic_resyncs_total",
            "Membership changes observed by watch_hosts (world"
            " re-forms at a checkpoint boundary)"),
        "restarts": registry.counter(
            "elastic_restarts_total",
            "Workload restarts recorded via record_restart()"),
        "hosts": registry.gauge(
            "elastic_hosts", "Current discovered host count"),
        "read_errors": registry.counter(
            "elastic_read_errors_total",
            "discover_hosts.sh reads that failed (partition /"
            " volume refresh in flight); membership is held, not"
            " flapped to empty"),
    }


def record_restart(registry=None) -> None:
    """Count a workload restart (call at process start when resuming
    from a checkpoint after preemption/rescheduling)."""
    _elastic_metrics(registry)["restarts"].inc()


def discover_hosts_path() -> Optional[str]:
    """Locate the mounted discover_hosts.sh: the declared mount path
    (/etc/mpi) on a real cluster, or the kubelet's sandboxed remap
    (K_MOUNT_* env) on the local runtime."""
    for key, val in os.environ.items():
        if key.startswith("K_MOUNT_") and not key.startswith("K_MOUNT_PATH_"):
            candidate = os.path.join(val, DISCOVER_SCRIPT)
            if os.path.exists(candidate):
                return candidate
    legacy = "/etc/mpi/" + DISCOVER_SCRIPT
    return legacy if os.path.exists(legacy) else None


def _read_hosts(path: Optional[str]) -> Optional[List[str]]:
    """Parse the script, or None when it cannot be read at all — the
    distinction watch_hosts needs: an *empty* script is a legitimate
    zero-member world (the controller wrote it), an *unreadable* one is
    a partition / mid-refresh volume and says nothing about
    membership."""
    if path is None:
        return None
    hosts: List[str] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith("echo "):
                    hosts.append(line[len("echo "):].strip())
    except OSError:
        return None
    return hosts


def current_hosts(path: Optional[str] = None) -> List[str]:
    """Parse the script's `echo <fqdn>` lines into a host list."""
    return _read_hosts(path or discover_hosts_path()) or []


def watch_hosts(path: Optional[str] = None, poll: float = 1.0,
                stop=None, registry=None) -> Iterator[List[str]]:
    """Yield the host list whenever membership changes (poll-based, like
    horovodrun's discovery loop).  Yields the initial membership first.
    Each change after the initial yield counts as an elastic resync.

    Partition-tolerant: a failed read (script unreadable — control
    plane partitioned, ConfigMap volume mid-refresh) HOLDS the last
    known membership instead of yielding [].  Flapping to empty would
    tear the world down at the next checkpoint boundary and re-form it
    when the partition heals — two full gang restarts for a fault that
    changed nothing (counted in elastic_read_errors_total instead)."""
    explicit_path = path
    metrics = _elastic_metrics(registry)
    last: Optional[List[str]] = None
    first = True
    while stop is None or not stop.is_set():
        # Re-resolve each poll when not pinned: the mount may appear
        # after startup (kubelet materializes volumes asynchronously).
        current = explicit_path or discover_hosts_path()
        if current is None:
            # No channel at all (no mount, no explicit path): a
            # legitimate empty world, not a read failure.
            hosts: Optional[List[str]] = []
        else:
            hosts = _read_hosts(current)
            if hosts is None:
                # Unreadable channel = partition, even on the FIRST
                # poll (a worker restarting mid-partition must wait for
                # a successful read, not boot into an empty world).
                metrics["read_errors"].inc()
        if hosts is not None and hosts != last:
            last = hosts
            metrics["hosts"].set(len(hosts))
            if not first:
                metrics["resyncs"].inc()
            first = False
            yield hosts
        time.sleep(poll)
