"""ctypes binding for the system libssh — a real SSH transport with no
OpenSSH on the image.

The reference's rank-formation path is mpirun → ssh → orted with sshd
running in every worker (build/base/Dockerfile:3-24, sshd port 2222).
This image ships no OpenSSH, no dropbear and no paramiko — only the
libssh C library (libssh-gcrypt.so.4, version 0.10) — so the framework
binds it directly: `SSHServer` below is the sshd equivalent the worker
pods run, and `SSHClient` the ssh side the launcher's rsh agent uses.
Both speak the genuine SSH2 wire protocol (curve25519/ECDH kex, ECDSA
host keys, publickey auth, session channels with exec requests), so the
operator's generated ECDSA Secret, authorized_keys projection and
hostfile chain are exercised against a real implementation, matching
/root/reference/test/e2e/mpi_job_test.go:87-205 in spirit.

Only the stable public API is used (declared here by hand — the image
has no libssh headers); enum values are fixed by libssh's ABI.
"""

from __future__ import annotations

import ctypes
import os
from ctypes import (POINTER, byref, c_char_p, c_int, c_uint32, c_void_p,
                    create_string_buffer)
from typing import Optional

_LIB_CANDIDATES = (
    "libssh-gcrypt.so.4",   # debian's gcrypt/gnutls flavour (this image)
    "libssh.so.4",
    "libssh.so",
)


def _load() -> ctypes.CDLL:
    last: Optional[Exception] = None
    for name in _LIB_CANDIDATES:
        try:
            return ctypes.CDLL(name)
        except OSError as exc:
            last = exc
    raise OSError(f"no libssh found (tried {_LIB_CANDIDATES}): {last}")


lib = _load()

# -- return codes -----------------------------------------------------------
SSH_OK = 0
SSH_ERROR = -1
SSH_AGAIN = -2
SSH_EOF = -127

# -- auth results -----------------------------------------------------------
SSH_AUTH_SUCCESS = 0
SSH_AUTH_DENIED = 1
SSH_AUTH_PARTIAL = 2
SSH_AUTH_INFO = 3
SSH_AUTH_AGAIN = 4
SSH_AUTH_ERROR = -1

# -- auth methods (bitmask) -------------------------------------------------
SSH_AUTH_METHOD_NONE = 0x0001
SSH_AUTH_METHOD_PASSWORD = 0x0002
SSH_AUTH_METHOD_PUBLICKEY = 0x0004

# -- server message types (enum ssh_requests_e) -----------------------------
SSH_REQUEST_AUTH = 1
SSH_REQUEST_CHANNEL_OPEN = 2
SSH_REQUEST_CHANNEL = 3
SSH_REQUEST_SERVICE = 4
SSH_REQUEST_GLOBAL = 5

# -- channel open subtypes (enum ssh_channel_type_e) ------------------------
SSH_CHANNEL_SESSION = 1

# -- channel request subtypes (enum ssh_channel_requests_e) -----------------
SSH_CHANNEL_REQUEST_UNKNOWN = 0
SSH_CHANNEL_REQUEST_PTY = 1
SSH_CHANNEL_REQUEST_EXEC = 2
SSH_CHANNEL_REQUEST_SHELL = 3
SSH_CHANNEL_REQUEST_ENV = 4

# -- publickey auth states (enum ssh_publickey_state_e) ---------------------
SSH_PUBLICKEY_STATE_NONE = 0    # probe: "would this key be acceptable?"
SSH_PUBLICKEY_STATE_VALID = 1   # signature verified

# -- ssh_bind options (enum ssh_bind_options_e) -----------------------------
SSH_BIND_OPTIONS_BINDADDR = 0
SSH_BIND_OPTIONS_BINDPORT = 1
SSH_BIND_OPTIONS_BINDPORT_STR = 2
SSH_BIND_OPTIONS_HOSTKEY = 3
SSH_BIND_OPTIONS_IMPORT_KEY = 10

# -- session options (enum ssh_options_e) -----------------------------------
SSH_OPTIONS_HOST = 0
SSH_OPTIONS_PORT_STR = 2
SSH_OPTIONS_USER = 4
SSH_OPTIONS_KNOWNHOSTS = 8
SSH_OPTIONS_TIMEOUT = 9
SSH_OPTIONS_STRICTHOSTKEYCHECK = 21
SSH_OPTIONS_PROCESS_CONFIG = 38

# -- key comparison ---------------------------------------------------------
SSH_KEY_CMP_PUBLIC = 0

_sig = lambda fn, res, args: (setattr(fn, "restype", res),
                              setattr(fn, "argtypes", args))

# session lifecycle
_sig(lib.ssh_init, c_int, [])
_sig(lib.ssh_new, c_void_p, [])
_sig(lib.ssh_free, None, [c_void_p])
_sig(lib.ssh_connect, c_int, [c_void_p])
_sig(lib.ssh_disconnect, None, [c_void_p])
_sig(lib.ssh_options_set, c_int, [c_void_p, c_int, c_void_p])
_sig(lib.ssh_get_error, c_char_p, [c_void_p])
_sig(lib.ssh_userauth_publickey, c_int, [c_void_p, c_char_p, c_void_p])

# keys
_sig(lib.ssh_pki_import_privkey_base64, c_int,
     [c_char_p, c_char_p, c_void_p, c_void_p, POINTER(c_void_p)])
_sig(lib.ssh_pki_import_privkey_file, c_int,
     [c_char_p, c_char_p, c_void_p, c_void_p, POINTER(c_void_p)])
_sig(lib.ssh_pki_import_pubkey_base64, c_int,
     [c_char_p, c_int, POINTER(c_void_p)])
_sig(lib.ssh_pki_generate, c_int, [c_int, c_int, POINTER(c_void_p)])
_sig(lib.ssh_key_type_from_name, c_int, [c_char_p])
_sig(lib.ssh_key_cmp, c_int, [c_void_p, c_void_p, c_int])
_sig(lib.ssh_key_free, None, [c_void_p])

# server side
_sig(lib.ssh_bind_new, c_void_p, [])
_sig(lib.ssh_bind_free, None, [c_void_p])
_sig(lib.ssh_bind_options_set, c_int, [c_void_p, c_int, c_void_p])
_sig(lib.ssh_bind_listen, c_int, [c_void_p])
_sig(lib.ssh_bind_accept, c_int, [c_void_p, c_void_p])
_sig(lib.ssh_bind_get_fd, c_int, [c_void_p])
_sig(lib.ssh_handle_key_exchange, c_int, [c_void_p])

# server messages
_sig(lib.ssh_message_get, c_void_p, [c_void_p])
_sig(lib.ssh_message_free, None, [c_void_p])
_sig(lib.ssh_message_type, c_int, [c_void_p])
_sig(lib.ssh_message_subtype, c_int, [c_void_p])
_sig(lib.ssh_message_auth_user, c_char_p, [c_void_p])
_sig(lib.ssh_message_auth_pubkey, c_void_p, [c_void_p])
_sig(lib.ssh_message_auth_publickey_state, c_int, [c_void_p])
_sig(lib.ssh_message_auth_reply_pk_ok_simple, c_int, [c_void_p])
_sig(lib.ssh_message_auth_reply_success, c_int, [c_void_p, c_int])
_sig(lib.ssh_message_auth_set_methods, c_int, [c_void_p, c_int])
_sig(lib.ssh_message_reply_default, c_int, [c_void_p])
_sig(lib.ssh_message_channel_request_open_reply_accept, c_void_p, [c_void_p])
_sig(lib.ssh_message_channel_request_command, c_char_p, [c_void_p])
_sig(lib.ssh_message_channel_request_env_name, c_char_p, [c_void_p])
_sig(lib.ssh_message_channel_request_env_value, c_char_p, [c_void_p])
_sig(lib.ssh_message_channel_request_reply_success, c_int, [c_void_p])

# channels
_sig(lib.ssh_channel_new, c_void_p, [c_void_p])
_sig(lib.ssh_channel_free, None, [c_void_p])
_sig(lib.ssh_channel_open_session, c_int, [c_void_p])
_sig(lib.ssh_channel_request_exec, c_int, [c_void_p, c_char_p])
_sig(lib.ssh_channel_read, c_int, [c_void_p, c_void_p, c_uint32, c_int])
_sig(lib.ssh_channel_read_timeout, c_int,
     [c_void_p, c_void_p, c_uint32, c_int, c_int])
_sig(lib.ssh_channel_write, c_int, [c_void_p, c_void_p, c_uint32])
_sig(lib.ssh_channel_write_stderr, c_int, [c_void_p, c_void_p, c_uint32])
_sig(lib.ssh_channel_send_eof, c_int, [c_void_p])
_sig(lib.ssh_channel_is_eof, c_int, [c_void_p])
_sig(lib.ssh_channel_is_open, c_int, [c_void_p])
_sig(lib.ssh_channel_close, c_int, [c_void_p])
_sig(lib.ssh_channel_get_exit_status, c_int, [c_void_p])
_sig(lib.ssh_channel_request_send_exit_status, c_int, [c_void_p, c_int])

lib.ssh_init()


class SSHError(RuntimeError):
    pass


def session_error(session) -> str:
    err = lib.ssh_get_error(session)
    return err.decode("utf-8", "replace") if err else "unknown libssh error"


def _opt_str(session, opt: int, value: str) -> None:
    if lib.ssh_options_set(session, opt, value.encode()) != SSH_OK:
        raise SSHError(f"ssh_options_set({opt}): {session_error(session)}")


def _opt_int(session, opt: int, value: int) -> None:
    v = c_int(value)
    if lib.ssh_options_set(session, opt, byref(v)) != SSH_OK:
        raise SSHError(f"ssh_options_set({opt}): {session_error(session)}")


def _opt_long(session, opt: int, value: int) -> None:
    # SSH_OPTIONS_TIMEOUT is read as a long* by libssh's options.c; a
    # c_int buffer would make it read 4 bytes of adjacent garbage on
    # LP64.
    v = ctypes.c_long(value)
    if lib.ssh_options_set(session, opt, byref(v)) != SSH_OK:
        raise SSHError(f"ssh_options_set({opt}): {session_error(session)}")


def import_privkey_pem(pem: str):
    """ssh_key from PEM text (the operator Secret's ssh-privatekey)."""
    key = c_void_p()
    rc = lib.ssh_pki_import_privkey_base64(pem.encode(), None, None, None,
                                           byref(key))
    if rc != SSH_OK:
        raise SSHError("cannot import private key (PEM)")
    return key


def import_privkey_file(path: str):
    key = c_void_p()
    rc = lib.ssh_pki_import_privkey_file(path.encode(), None, None, None,
                                         byref(key))
    if rc != SSH_OK:
        raise SSHError(f"cannot import private key {path}")
    return key


def import_pubkey_line(line: str):
    """ssh_key from an authorized_keys / .pub line
    ("<type> <base64> [comment]")."""
    parts = line.strip().split()
    if len(parts) < 2:
        raise SSHError(f"malformed public key line: {line!r}")
    ktype = lib.ssh_key_type_from_name(parts[0].encode())
    key = c_void_p()
    rc = lib.ssh_pki_import_pubkey_base64(parts[1].encode(), ktype,
                                          byref(key))
    if rc != SSH_OK:
        raise SSHError(f"cannot import public key ({parts[0]})")
    return key


def keys_equal(a, b) -> bool:
    return lib.ssh_key_cmp(a, b, SSH_KEY_CMP_PUBLIC) == 0


def read_authorized_keys(path: str) -> list:
    """Parsed ssh_keys from an authorized_keys file (the Secret's
    ssh-publickey projected as authorized_keys; reference
    mpi_job_controller.go:142-155)."""
    keys = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            keys.append(import_pubkey_line(line))
    return keys
