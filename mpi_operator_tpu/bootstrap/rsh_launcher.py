"""rsh launcher — mpirun's rank-formation contract without an MPI runtime.

The reference's launcher runs ``mpirun``, which reads the operator's
hostfile, dials each worker over SSH (sshd in the worker image,
/root/reference/build/base/Dockerfile:1-31) and execs one process per
slot with rank env.  This module is that exact contract, TPU-native:

    python -m mpi_operator_tpu.bootstrap.rsh_launcher -- CMD ARGS...

* hostfile discovered from the operator-injected env
  (OMPI_MCA_orte_default_hostfile / I_MPI_HYDRA_HOST_FILE /
  HYDRA_HOST_FILE), with both "host slots=N" and "host:N" formats;
* a DNS-readiness gate retries until every host resolves (the
  entrypoint.sh:7-37 analogue);
* each rank is launched through a pluggable rsh agent — ``ssh`` by
  default (with OMPI_MCA_plm_rsh_args, e.g. -o ConnectionAttempts=10),
  or any ``agent host cmd...`` program via --rsh (OpenMPI's
  plm_rsh_agent knob; bootstrap.rsh_local runs ranks locally for
  single-host/hermetic use);
* every rank gets coordinator env (JAX_COORDINATOR_ADDRESS=host0:port,
  JAX_PROCESS_ID, JAX_NUM_PROCESSES) plus OMPI_COMM_WORLD_RANK/SIZE, so
  both tpucoll-native and jax.distributed workloads form the group.

Exit status is the first nonzero rank status; on any failure the rest of
the gang is terminated (gang semantics, like mpirun).
"""

from __future__ import annotations

import argparse
import os
import re
import shlex
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

HOSTFILE_ENV_VARS = ("OMPI_MCA_orte_default_hostfile",
                     "I_MPI_HYDRA_HOST_FILE", "HYDRA_HOST_FILE")
# Per-family rsh-agent extra args, paired with the hostfile var that
# selects the family (mpirun: plm_rsh_args; mpiexec.hydra: bootstrap
# exec args — reference operator injects these, mpi_job_controller.go
# env matrices).
AGENT_ARGS_ENV_VARS = ("OMPI_MCA_plm_rsh_args",
                       "I_MPI_HYDRA_BOOTSTRAP_EXEC_EXTRA_ARGS",
                       "HYDRA_LAUNCH_EXTRA_ARGS")


@dataclass
class HostSlots:
    host: str
    slots: int = 1


def resolve_hostfile_env(env=None):
    """(matched hostfile env var, declared path) from the operator env
    matrices, or (None, None) — the var identifies the MPI family, so
    the agent-args var can be chosen from the SAME family."""
    env = env if env is not None else os.environ
    for var in HOSTFILE_ENV_VARS:
        if env.get(var):
            return var, env[var]
    return None, None


def resolve_hostfile_path(env=None) -> Optional[str]:
    """Hostfile path from the operator env matrices; inside the local
    kubelet the declared mount path (/etc/mpi) is translated through the
    K_MOUNT_PATH_*/K_MOUNT_* sandbox mapping."""
    env = env if env is not None else os.environ
    _, declared = resolve_hostfile_env(env)
    if declared is None:
        return None
    if os.path.exists(declared):
        return declared
    # Sandbox translation: find a mount whose declared path prefixes the
    # hostfile path and rebase onto the materialized volume dir.
    for key, mount_path in env.items():
        if not key.startswith("K_MOUNT_PATH_"):
            continue
        if declared.startswith(mount_path.rstrip("/") + "/"):
            host_dir = env.get("K_MOUNT_" + key[len("K_MOUNT_PATH_"):])
            if host_dir:
                rel = declared[len(mount_path.rstrip("/")) + 1:]
                candidate = os.path.join(host_dir, rel)
                if os.path.exists(candidate):
                    return candidate
    return declared  # let the open() failure carry the real path


def parse_hostfile(text: str) -> List[HostSlots]:
    """Both wire formats the operator emits (controller/builders.py
    host_line): OpenMPI "host slots=N", Intel/MPICH "host:N", bare
    host lines (JAX informational hostfile)."""
    out = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^(\S+?)\s+slots=(\d+)$", line)
        if m:
            out.append(HostSlots(m.group(1), int(m.group(2))))
            continue
        m = re.match(r"^([^\s:]+):(\d+)$", line)
        if m:
            out.append(HostSlots(m.group(1), int(m.group(2))))
            continue
        out.append(HostSlots(line))
    return out


def wait_for_dns(hosts: List[str], timeout: float, required: bool = True,
                 log=print) -> bool:
    """Retry until every host resolves (entrypoint.sh DNS gate analogue).
    With required=False (non-ssh agents that do not dial the host name)
    failure downgrades to a warning."""
    # Inside the hermetic kubelet sandbox there is no cluster DNS; pod
    # FQDNs resolve through the deterministic netsim mapping instead, so
    # the gate still validates that every hostfile entry is a well-formed
    # cluster name.  Only for non-ssh agents (required=False): ssh does
    # its own getaddrinfo, which netsim cannot satisfy, so passing the
    # gate would just defer the failure to every rank.
    in_sandbox = "K_SANDBOX_DIR" in os.environ and not required

    def _resolves(host: str) -> bool:
        try:
            socket.getaddrinfo(host, None)
            return True
        except OSError:
            if in_sandbox:
                from ..runtime import netsim
                return netsim.resolve(host) is not None
            return False

    deadline = time.monotonic() + timeout
    pending = list(dict.fromkeys(hosts))
    while pending and time.monotonic() < deadline:
        pending = [h for h in pending if not _resolves(h)]
        if pending:
            time.sleep(min(1.0, max(0.0, deadline - time.monotonic())))
    if not pending:
        return True
    msg = f"hosts never resolved: {', '.join(pending)}"
    if required:
        raise RuntimeError(msg)
    log(f"rsh_launcher: warning: {msg} (continuing: non-ssh agent)")
    return False


def _is_ssh_like(agent: List[str]) -> bool:
    """ssh-shaped agents (OpenSSH, or the framework's ssh_client module)
    JOIN remote tokens for a remote shell and accept -o style args;
    exec-style agents (rsh_local) do neither.  Only the program token
    and a python -m module name are examined — an ssh-ish path in some
    VALUE (--key-dir /etc/ssh) must not flip the classification."""
    if not agent:
        return False
    candidates = [os.path.basename(agent[0])]
    if "-m" in agent:
        i = agent.index("-m")
        if i + 1 < len(agent):
            candidates.append(agent[i + 1].rsplit(".", 1)[-1])
    return any("ssh" in c for c in candidates)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def build_rank_commands(hosts: List[HostSlots], workload: List[str],
                        agent: List[str], agent_args: List[str],
                        coordinator_port: int,
                        np: Optional[int] = None,
                        coordinator: Optional[str] = None,
                        shell_quote: bool = False) -> List[List[str]]:
    """One command per rank: agent + args + host + env assignments +
    workload (the rsh contract: everything after the host is the remote
    command line).

    shell_quote: ssh-style agents JOIN the remote tokens into one string
    that a remote /bin/sh re-parses, so tokens with spaces/quotes must
    be shell-quoted here (what mpirun does for its rsh tree); exec-style
    agents (rsh_local) pass tokens straight to execvp and must NOT get
    quoting baked in."""
    total = sum(h.slots for h in hosts)
    if np is not None:
        total = min(total, np)
    if coordinator is None:
        coordinator = f"{hosts[0].host}:{coordinator_port}"
    elif ":" not in coordinator:
        coordinator = f"{coordinator}:{coordinator_port}"
    cmds = []
    rank = 0
    for h in hosts:
        for _ in range(h.slots):
            if rank >= total:
                break
            assignments = [
                f"JAX_COORDINATOR_ADDRESS={coordinator}",
                f"JAX_PROCESS_ID={rank}",
                f"JAX_NUM_PROCESSES={total}",
                f"OMPI_COMM_WORLD_RANK={rank}",
                f"OMPI_COMM_WORLD_SIZE={total}",
                # hydra-family (Intel/MPICH) rank contract.
                f"PMI_RANK={rank}",
                f"PMI_SIZE={total}",
            ]
            remote = ["env"] + assignments + workload
            if shell_quote:
                remote = [shlex.quote(tok) for tok in remote]
            cmds.append(agent + agent_args + [h.host] + remote)
            rank += 1
    return cmds


def run_gang(cmds: List[List[str]], log=print) -> int:
    """Launch every rank, stream prefixed output, enforce gang semantics:
    first nonzero status terminates the rest."""
    procs = []
    for rank, cmd in enumerate(cmds):
        procs.append((rank, subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)))

    failures = []
    lock = threading.Lock()

    def pump(rank: int, proc: subprocess.Popen) -> None:
        for line in proc.stdout:
            log(f"[rank {rank}] {line.rstrip()}")
        code = proc.wait()
        if code != 0:
            with lock:
                failures.append((rank, code))
            for _, other in procs:
                if other.poll() is None:
                    other.terminate()

    threads = [threading.Thread(target=pump, args=(r, p), daemon=True)
               for r, p in procs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if failures:
        rank, code = failures[0]
        log(f"rsh_launcher: rank {rank} failed with exit code {code}")
        return code
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="rsh_launcher",
        description="mpirun-style gang launcher over a pluggable rsh agent")
    parser.add_argument("--rsh", default="ssh",
                        help="rsh agent (OpenMPI plm_rsh_agent analogue);"
                             " invoked as: AGENT [args] HOST CMD...")
    parser.add_argument("--hostfile", default=None,
                        help="override the env-discovered hostfile path")
    parser.add_argument("--np", type=int, default=None,
                        help="cap the number of ranks")
    parser.add_argument("--port", type=int, default=None,
                        help="coordinator port (default: "
                             "JAX_COORDINATOR_PORT or a free port)")
    parser.add_argument("--coordinator", default=None,
                        help="override the rank-0 coordinator host[:port]"
                             " (default: first hostfile entry; use"
                             " 127.0.0.1 with a local agent)")
    parser.add_argument("--dns-timeout", type=float, default=300.0)
    parser.add_argument("workload", nargs="+",
                        help="rank command (prefix with -- )")
    args = parser.parse_args(argv)

    hostfile = args.hostfile or resolve_hostfile_path()
    if hostfile is None:
        print("rsh_launcher: no hostfile (set --hostfile or run under the"
              " operator's MPI env matrix)", file=sys.stderr)
        return 2
    with open(hostfile) as f:
        hosts = parse_hostfile(f.read())
    if not hosts:
        print(f"rsh_launcher: hostfile {hostfile} is empty",
              file=sys.stderr)
        return 2

    agent = shlex.split(args.rsh)
    ssh_like = _is_ssh_like(agent)
    agent_args = []
    if agent and ssh_like:
        # Extra args come from the SAME family as the hostfile var (a
        # stray OMPI_MCA_plm_rsh_args in a preconfigured base image must
        # not override an MPICH job's HYDRA_LAUNCH_EXTRA_ARGS); with a
        # --hostfile override and no matched family, first-set wins.
        hostfile_var, _ = resolve_hostfile_env()
        if hostfile_var is not None:
            candidates = (AGENT_ARGS_ENV_VARS[
                HOSTFILE_ENV_VARS.index(hostfile_var)],)
        else:
            candidates = AGENT_ARGS_ENV_VARS
        for var in candidates:
            if os.environ.get(var):
                agent_args = shlex.split(os.environ[var])
                break
    # Only real OpenSSH hard-requires system DNS; the framework's
    # ssh_client resolves cluster names through netsim itself.
    wait_for_dns([h.host for h in hosts], args.dns_timeout,
                 required=os.path.basename(agent[0]) == "ssh")

    port = args.port
    if port is None:
        declared = os.environ.get("JAX_COORDINATOR_PORT")
        port = int(declared) if declared else _free_port()

    coordinator = args.coordinator
    if coordinator is None and "K_SANDBOX_DIR" in os.environ:
        # Hermetic runtime: the first hostfile entry is a cluster-DNS pod
        # name with no real DNS behind it — hand ranks its netsim address
        # (the per-pod loopback IP the kubelet also injects), so the
        # FQDN-coordinator path works exactly as it would under cluster
        # DNS.
        from ..runtime import netsim
        coordinator = netsim.resolve(hosts[0].host)

    cmds = build_rank_commands(hosts, args.workload, agent, agent_args,
                               port, np=args.np, coordinator=coordinator,
                               shell_quote=ssh_like)
    print(f"rsh_launcher: launching {len(cmds)} ranks across "
          f"{len(hosts)} hosts (agent: {' '.join(agent)})", flush=True)
    return run_gang(cmds)


if __name__ == "__main__":
    raise SystemExit(main())
