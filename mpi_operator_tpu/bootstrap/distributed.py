"""jax.distributed bootstrap from operator-injected env.

The in-pod counterpart of the controller's JAX env injection
(mpi_operator_tpu/controller/builders.py jax_env): reads
JAX_COORDINATOR_ADDRESS / JAX_PROCESS_ID / JAX_NUM_PROCESSES and calls
``jax.distributed.initialize`` so XLA collectives form over ICI (intra
slice) or DCN (multislice) — the TPU-native replacement for the
reference's mpirun → ssh → orted launch path
(/root/reference/build/base/entrypoint.sh:7-37).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Optional

from ..api import constants


@dataclass
class ProcessEnv:
    coordinator_address: str
    process_id: int
    num_processes: int
    local_device_count: int
    # Multislice identity (spec.slices > 1); slice_id is -1 outside a
    # multislice job.
    num_slices: int = 1
    slice_id: int = -1

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1


def process_env() -> Optional[ProcessEnv]:
    """Parse the injected env; None when running outside an MPIJob."""
    addr = os.environ.get(constants.JAX_COORDINATOR_ADDRESS_ENV)
    if not addr:
        return None
    return ProcessEnv(
        coordinator_address=addr,
        process_id=int(os.environ.get(constants.JAX_PROCESS_ID_ENV, "0")),
        num_processes=int(os.environ.get(constants.JAX_NUM_PROCESSES_ENV, "1")),
        local_device_count=int(os.environ.get(
            constants.JAX_LOCAL_DEVICE_COUNT_ENV, "0")),
        num_slices=int(os.environ.get(
            constants.MEGASCALE_NUM_SLICES_ENV, "1")),
        slice_id=int(os.environ.get(
            constants.MEGASCALE_SLICE_ID_ENV, "-1")))


def submit_time() -> Optional[float]:
    """Epoch seconds at which the MPIJob was submitted (injected by the
    controller as MPIJOB_SUBMIT_TIME); None outside an operator-run pod.
    Workloads use it to report launch-to-first-allreduce latency."""
    raw = os.environ.get(constants.MPIJOB_SUBMIT_TIME_ENV)
    return float(raw) if raw else None


def launch_latency_seconds() -> Optional[float]:
    """Seconds elapsed since job submission (None outside an MPIJob).
    Call right after the first collective completes to measure
    submit -> first-allreduce, BASELINE.md's second target metric."""
    t0 = submit_time()
    return None if t0 is None else time.time() - t0


def initialize_from_env(timeout_seconds: float = 120.0) -> Optional[ProcessEnv]:
    """Initialize jax.distributed from the injected env (no-op outside an
    MPIJob or for single-process jobs).  Retries while the coordinator's
    DNS/socket comes up — the analogue of entrypoint.sh's nslookup loop.

    The wait is a causal-trace span (``distributed_init``, parented to
    the job context in ``MPI_OPERATOR_TRACE_CONTEXT``): the DNS-wait /
    group-formation seconds show up named in the job's critical-path
    decomposition instead of vanishing into "pod was slow"."""
    env = process_env()
    if env is None or env.num_processes <= 1:
        return env
    import jax

    from ..telemetry.trace import env_context, span

    deadline = time.monotonic() + timeout_seconds
    last_err: Optional[Exception] = None
    delay = 0.1  # quick first retries (the coordinator is usually a
    with span("distributed_init", ctx=env_context(),
              process_id=env.process_id,
              num_processes=env.num_processes):
        while time.monotonic() < deadline:  # fraction of a second behind
            try:
                jax.distributed.initialize(
                    coordinator_address=env.coordinator_address,
                    num_processes=env.num_processes,
                    process_id=env.process_id)
                return env
            except Exception as exc:  # coordinator not up yet
                last_err = exc
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
        raise TimeoutError(
            f"jax.distributed.initialize did not connect to "
            f"{env.coordinator_address} within {timeout_seconds}s: "
            f"{last_err}")
