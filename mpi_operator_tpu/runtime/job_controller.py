"""Mini batch/v1 Job controller.

Kubernetes provides this for free to the reference operator (the launcher
is a batch Job, mpi_job_controller.go:1554-1580, and the operator reads
its Complete/Failed conditions).  Our standalone runtime needs one: it
reconciles Jobs into pods and maintains Job status with the semantics the
operator depends on — backoffLimit (default 6) with Failed reason
"BackoffLimitExceeded", suspend (delete active pods, clear nothing),
activeDeadlineSeconds ("DeadlineExceeded"), TTLSecondsAfterFinished, and
completion on one succeeded pod.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..k8s import batch, core
from ..k8s.apiserver import Clientset, is_not_found
from ..k8s.meta import (Clock, ObjectMeta, deep_copy, get_controller_of,
                        new_controller_ref)

logger = logging.getLogger("mpi_operator_tpu.runtime.job")

DEFAULT_BACKOFF_LIMIT = 6


class JobController:
    def __init__(self, clientset: Clientset, clock: Optional[Clock] = None,
                 namespace: Optional[str] = None):
        self.client = clientset
        self.clock = clock or Clock()
        self.namespace = namespace
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pod_serial = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self, resync_interval: float = 1.0) -> None:
        """Event-driven: Job/Pod watch events trigger targeted syncs; a
        periodic full resync drives the time-based paths (deadline, TTL)."""
        self._job_watch = self.client.server.watch("batch/v1", "Job")
        self._pod_watch = self.client.server.watch("v1", "Pod")
        self._thread = threading.Thread(target=self._loop,
                                        args=(resync_interval,),
                                        daemon=True, name="job-controller")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        for w in (getattr(self, "_job_watch", None),
                  getattr(self, "_pod_watch", None)):
            if w is not None:
                w.stop()
        if self._thread:
            self._thread.join(timeout=2)

    def _loop(self, resync_interval: float) -> None:
        import time as _time
        from ..k8s.apiserver import CLOSED, redial_watch
        next_resync = 0.0
        while not self._stop.is_set():
            dirty = False
            for attr, gv, kind in (("_job_watch", "batch/v1", "Job"),
                                   ("_pod_watch", "v1", "Pod")):
                w = getattr(self, attr)
                while True:
                    ev = w.next(timeout=0)
                    if ev is None:
                        break
                    dirty = True
                    if ev.type == CLOSED:
                        # Apiserver restarted: re-dial (the loop's
                        # relist-shaped sync_all covers the gap).
                        fresh = redial_watch(self.client, gv, kind,
                                             stop=self._stop)
                        if fresh is not None:
                            setattr(self, attr, fresh)
                        break
            now = _time.monotonic()
            if dirty or now >= next_resync:
                try:
                    self.sync_all()
                except Exception as exc:  # keep reconciling
                    logger.warning("job controller sync error: %s", exc)
                next_resync = now + resync_interval
            self._stop.wait(0.02)

    # -- reconcile ---------------------------------------------------------
    def sync_all(self) -> None:
        for job in self.client.server.list("batch/v1", "Job", self.namespace):
            try:
                self.sync_job(job)
            except Exception as exc:
                # Isolate per-job failures (apiserver error bursts,
                # conflicts): one job's bad sync must not starve every
                # job behind it in the list until the next resync.
                logger.warning("sync of job %s/%s failed: %s",
                               job.metadata.namespace, job.metadata.name,
                               exc)

    def _job_pods(self, job: batch.Job) -> list:
        pods = self.client.server.list("v1", "Pod", job.metadata.namespace)
        out = []
        for p in pods:
            ref = get_controller_of(p)
            if ref is not None and ref.uid == job.metadata.uid:
                out.append(p)
        return out

    def sync_job(self, job: batch.Job) -> None:
        pods = self._job_pods(job)
        self._reseed_pod_serial(pods)
        active = [p for p in pods if p.status.phase in (core.POD_PENDING,
                                                        core.POD_RUNNING)]
        succeeded = sum(1 for p in pods if p.status.phase == core.POD_SUCCEEDED)
        # backoffLimit counts failed pods AND container restarts of live
        # pods (k8s semantics: restartPolicy=OnFailure retries in-place).
        failed = sum(1 for p in pods if p.status.phase == core.POD_FAILED)
        failed += sum(cs.restart_count for p in active
                      for cs in p.status.container_statuses)

        if batch.is_job_finished(job):
            self._maybe_ttl_delete(job)
            return

        ns = job.metadata.namespace
        changed = deep_copy(job)

        # Suspension (KEP-2232 semantics the operator relies on).
        if job.spec.suspend:
            for p in active:
                try:
                    self.client.pods(ns).delete(p.metadata.name)
                except Exception as exc:
                    if not is_not_found(exc):
                        raise
            changed.status.active = 0
            # KEP-2232: suspension resets startTime so activeDeadlineSeconds
            # never counts suspended wall time.
            changed.status.start_time = None
            self._set_condition(changed, batch.JOB_SUSPENDED, "True",
                                "JobSuspended", "Job suspended")
            self._update_status_if_changed(job, changed)
            return
        else:
            cond = self._get_condition(changed, batch.JOB_SUSPENDED)
            if cond is not None and cond.status == "True":
                self._set_condition(changed, batch.JOB_SUSPENDED, "False",
                                    "JobResumed", "Job resumed")
            if changed.status.start_time is None:
                changed.status.start_time = self.clock.now()

        # Completion.
        completions = job.spec.completions if job.spec.completions is not None else 1
        if succeeded >= completions:
            changed.status.succeeded = succeeded
            changed.status.active = 0
            changed.status.completion_time = self.clock.now()
            self._set_condition(changed, batch.JOB_COMPLETE, "True", "",
                                "Job completed")
            self._update_status_if_changed(job, changed)
            return

        # Failure: backoff limit.
        backoff = (job.spec.backoff_limit
                   if job.spec.backoff_limit is not None
                   else DEFAULT_BACKOFF_LIMIT)
        if failed > backoff:
            changed.status.failed = failed
            changed.status.active = 0
            changed.status.completion_time = self.clock.now()
            self._set_condition(changed, batch.JOB_FAILED, "True",
                                "BackoffLimitExceeded",
                                "Job has reached the specified backoff limit")
            self._update_status_if_changed(job, changed)
            for p in active:
                try:
                    self.client.pods(ns).delete(p.metadata.name)
                except Exception as exc:
                    if not is_not_found(exc):
                        raise
            return

        # Failure: active deadline.
        if (job.spec.active_deadline_seconds is not None
                and changed.status.start_time is not None):
            elapsed = (self.clock.now() - changed.status.start_time).total_seconds()
            if elapsed > job.spec.active_deadline_seconds:
                changed.status.failed = failed
                changed.status.active = 0
                changed.status.completion_time = self.clock.now()
                self._set_condition(changed, batch.JOB_FAILED, "True",
                                    "DeadlineExceeded",
                                    "Job was active longer than specified"
                                    " deadline")
                self._update_status_if_changed(job, changed)
                for p in active:
                    try:
                        self.client.pods(ns).delete(p.metadata.name)
                    except Exception as exc:
                        if not is_not_found(exc):
                            raise
                return

        # Ensure parallelism (launcher Jobs use 1).
        parallelism = (job.spec.parallelism
                       if job.spec.parallelism is not None else 1)
        terminating_excluded = active  # PodReplacementPolicy=Failed: only
        # count failed pods as replaceable; our runtime has no graceful
        # deletion window so active is the right set either way.
        while len(terminating_excluded) < parallelism:
            pod = self._new_pod(changed)
            try:
                self.client.pods(ns).create(pod)
            except Exception as exc:
                logger.warning("creating pod for job %s: %s",
                               job.metadata.name, exc)
                break
            terminating_excluded.append(pod)

        changed.status.active = len(terminating_excluded)
        changed.status.succeeded = succeeded
        changed.status.failed = failed
        self._update_status_if_changed(job, changed)

    def _reseed_pod_serial(self, pods: list) -> None:
        """Restart recovery: the pod-name serial is in-memory, so a
        respawned controller would restart at 0 and collide with pods
        its previous incarnation created — a finished pod's name then
        blocks every subsequent create (AlreadyExists forever, the job
        wedges).  Advance the serial past every name already in the
        apiserver before creating."""
        for p in pods:
            suffix = p.metadata.name.rsplit("-", 1)[-1]
            try:
                seen = int(suffix, 16)
            except ValueError:
                continue
            if seen > self._pod_serial:
                self._pod_serial = seen

    def _new_pod(self, job: batch.Job):
        self._pod_serial += 1
        template = deep_copy(job.spec.template)
        labels = dict(template.metadata.labels)
        labels.setdefault("job-name", job.metadata.name)
        pod = core.Pod(
            metadata=ObjectMeta(
                name=f"{job.metadata.name}-{self._pod_serial:05x}",
                namespace=job.metadata.namespace,
                labels=labels,
                annotations=dict(template.metadata.annotations),
                owner_references=[new_controller_ref(job, "batch/v1", "Job")]),
            spec=template.spec)
        return pod

    # -- helpers -----------------------------------------------------------
    def _get_condition(self, job: batch.Job, ctype: str):
        for c in job.status.conditions:
            if c.type == ctype:
                return c
        return None

    def _set_condition(self, job: batch.Job, ctype: str, status: str,
                       reason: str, message: str) -> None:
        cond = self._get_condition(job, ctype)
        if cond is not None and cond.status == status:
            return
        job.status.conditions = [c for c in job.status.conditions
                                 if c.type != ctype]
        job.status.conditions.append(batch.JobCondition(
            type=ctype, status=status, reason=reason, message=message,
            last_transition_time=self.clock.now()))

    def _update_status_if_changed(self, old: batch.Job, new: batch.Job) -> None:
        if old.status != new.status:
            try:
                self.client.jobs(new.metadata.namespace).update_status(new)
            except Exception as exc:
                if not is_not_found(exc):
                    logger.warning("updating job status %s: %s",
                                   new.metadata.name, exc)

    def _maybe_ttl_delete(self, job: batch.Job) -> None:
        ttl = job.spec.ttl_seconds_after_finished
        if ttl is None or job.status.completion_time is None:
            return
        if (self.clock.now() - job.status.completion_time).total_seconds() >= ttl:
            try:
                self.client.jobs(job.metadata.namespace).delete(
                    job.metadata.name)
            except Exception as exc:
                if not is_not_found(exc):
                    raise
