"""Local cluster runtime.

The reference delegates pod execution to Kubernetes (kubelet + batch Job
controller).  This package provides the standalone equivalents so the
framework runs end-to-end on a single host — the hermetic analogue of the
reference's kind-based e2e (test/e2e/e2e_suite_test.go):

- ``job_controller``: reconciles batch/v1 Jobs into pods (backoffLimit,
  suspend, activeDeadlineSeconds, TTL, Complete/Failed conditions).
- ``kubelet``: runs pods as local subprocesses, materializes
  ConfigMap/Secret volumes into a sandbox, resolves service DNS to
  loopback, manages phases/restart policies and captures logs.
"""

from .job_controller import JobController  # noqa: F401
from .kubelet import LocalKubelet  # noqa: F401
