"""Deterministic per-pod loopback addressing for the hermetic runtime.

A real cluster gives every pod its own IP, and cluster DNS maps the
headless-Service names the controller builds
(``<pod>.<service>.<ns>.svc[.<cluster-domain>]``, reference
mpi_job_controller.go:1409-1438 + build/base/entrypoint.sh's DNS gate)
to those IPs.  The local runtime used to collapse every such name to
127.0.0.1, which meant the stable-hostname machinery was never really
exercised (every "host" was literally the same address).

Linux accepts the entire 127.0.0.0/8 on the loopback interface with no
configuration, so instead each (namespace, pod) pair maps to its own
stable loopback address via a keyed hash.  The mapping is computable in
ANY process with no coordination — the kubelet (env injection), the rsh
launcher (DNS gate), bootstrap and tests all derive the same answer for
the same name, which is exactly the property cluster DNS provides.

Address layout: 127.X.Y.Z with X in [64, 127], Z in [1, 254] — ~4.2M
distinct addresses, disjoint from the conventional 127.0.0.1 so a
collision with unrelated local services is impossible.
"""

from __future__ import annotations

import hashlib
import re
from typing import Optional

# <label>(.<label>)*.svc[.<domain>] — the shape of every cluster-DNS name
# the controller injects (meta.validation guarantees DNS-1035 labels).
# Both regexes derive from one label pattern so the substring search
# (rewrite) and the anchored parse (resolve) cannot drift apart.
_LABEL = r"[a-z0-9](?:[-a-z0-9]*[a-z0-9])?"
_SEARCH_RE = re.compile(
    _LABEL + r"(?:\." + _LABEL + r")*" + r"\.svc(?:\.[a-z0-9.]+)?")
_ANCHORED_RE = re.compile(
    r"^(" + _LABEL + r")((?:\." + _LABEL + r")*)"
    r"\.svc(?:\.[a-z0-9.]+?)?\.?$")


def pod_ip(namespace: str, pod_name: str) -> str:
    """Stable loopback IP for a pod, identical in every process."""
    digest = hashlib.blake2s(
        f"{namespace}/{pod_name}".encode(), digest_size=3).digest()
    return (f"127.{64 + digest[0] % 64}.{digest[1]}"
            f".{1 + digest[2] % 254}")


def resolve(fqdn: str) -> Optional[str]:
    """Resolve a cluster-DNS name to its simulated address.

    ``<pod>.<service>.<ns>.svc[...]`` (three or more labels before
    ``.svc``) resolves to the pod's address; a bare service name
    (``<service>.<ns>.svc[...]``) has no single backing pod — headless
    Services resolve to every member — and returns None, as does any
    non-cluster name.
    """
    m = _ANCHORED_RE.match(fqdn)
    if not m:
        return None
    labels = [m.group(1)] + [p for p in m.group(2).split(".") if p]
    if len(labels) < 3:
        return None
    # <pod>.<service>.<ns>: the pod lives in the trailing namespace label.
    return pod_ip(labels[-1], labels[0])


def rewrite(value: str, fallback: str = "127.0.0.1") -> str:
    """Rewrite every embedded cluster-DNS name in ``value`` to its
    simulated address (pod names) or ``fallback`` (service names — a
    headless Service has no single address)."""
    return _SEARCH_RE.sub(
        lambda m: resolve(m.group(0)) or fallback, value)
