"""LocalKubelet — runs pods as host subprocesses.

Standalone analogue of kubelet for the hermetic runtime: watches pods,
launches ``spec.containers[0].command + args`` as a subprocess, reflects
phases (Pending → Running → Succeeded/Failed) and the Ready condition,
honors restartPolicy (Always/OnFailure restart with backoff; Never
fails), materializes ConfigMap/Secret volumes into a per-pod sandbox and
captures logs.

Network model: every pod gets its own deterministic loopback address
(netsim, 127.X.Y.Z — Linux routes all of 127.0.0.0/8 over lo), surfaced
as ``status.podIP``.  Service DNS names
(``<pod>.<svc>.<ns>.svc[...]``, reference build/base/entrypoint.sh relies
on cluster DNS here) are resolved at pod start by rewriting env values to
the named pod's address, so distinct hosts really are distinct
endpoints; per-job coordinator ports are still allocated to avoid
cross-job collisions (the JAX_COORDINATOR_PORT / :port suffix pair is
rewritten together) — the local stand-in for the headless Service +
stable pod hostname machinery (mpi_job_controller.go:1409-1438).
"""

from __future__ import annotations

import logging
import os
import re
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

from ..api import constants as api_constants
from ..k8s import core
from ..k8s.apiserver import (TRANSPORT_ERRORS, ApiServer, Clientset,
                             is_conflict, is_not_found)
from ..telemetry import flight
from . import gangsim, netsim

logger = logging.getLogger("mpi_operator_tpu.runtime.kubelet")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _PodRunner:
    def __init__(self, kubelet: "LocalKubelet", pod: core.Pod):
        self.kubelet = kubelet
        self.pod_name = pod.metadata.name
        self.namespace = pod.metadata.namespace
        self.spec = pod.spec
        self.sandbox = tempfile.mkdtemp(
            prefix=f"pod-{self.namespace}-{self.pod_name}-",
            dir=kubelet.root_dir)
        self.log_path = os.path.join(self.sandbox, "container.log")
        self.preemption_notice_path = os.path.join(self.sandbox,
                                                   "preemption.notice")
        self.resize_notice_path = os.path.join(self.sandbox,
                                               "resize.notice")
        self.proc: Optional[subprocess.Popen] = None
        self.restart_count = 0
        self.stopped = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name=f"pod-{self.pod_name}")

    # -- volume materialization -------------------------------------------
    def refresh_config_volumes(self, config_map_name: str) -> None:
        """Re-materialize ConfigMap-backed volumes after the ConfigMap
        changed (kubelet eventually-consistent volume update parity —
        this is what makes the elastic discover_hosts.sh artifact live
        inside running pods)."""
        for vol in self.spec.volumes:
            if vol.config_map is not None and \
                    vol.config_map.name == config_map_name:
                self._materialize_volumes(only=vol.name)

    def _materialize_volumes(self, only: str = None) -> dict:
        """Write ConfigMap/Secret volumes under the sandbox; returns a map
        of volume name -> host dir."""
        dirs = {}
        for vol in self.spec.volumes:
            if only is not None and vol.name != only:
                continue
            vol_dir = os.path.join(self.sandbox, "volumes", vol.name)
            os.makedirs(vol_dir, exist_ok=True)
            if vol.config_map is not None:
                try:
                    cm = self.kubelet.client.config_maps(self.namespace).get(
                        vol.config_map.name)
                except TRANSPORT_ERRORS:
                    continue  # not created yet / API weather: skip volume
                items = vol.config_map.items or [
                    core.KeyToPath(k, k) for k in cm.data]
                for item in items:
                    if item.key not in cm.data:
                        continue
                    path = os.path.join(vol_dir, item.path)
                    with open(path, "w") as f:
                        f.write(cm.data[item.key])
                    mode = item.mode or vol.config_map.default_mode
                    if mode is not None:
                        os.chmod(path, mode)
            elif vol.secret is not None:
                try:
                    secret = self.kubelet.client.secrets(self.namespace).get(
                        vol.secret.secret_name)
                except TRANSPORT_ERRORS:
                    continue  # not created yet / API weather: skip volume
                items = vol.secret.items or [
                    core.KeyToPath(k, k) for k in secret.data]
                for item in items:
                    if item.key not in secret.data:
                        continue
                    path = os.path.join(vol_dir, item.path)
                    data = secret.data[item.key]
                    mode = "wb" if isinstance(data, bytes) else "w"
                    with open(path, mode) as f:
                        f.write(data)
                    # items[].mode takes precedence over defaultMode
                    # (Kubernetes semantics).
                    os.chmod(path, (item.mode or vol.secret.default_mode
                                    or 0o644))
            dirs[vol.name] = vol_dir
        return dirs

    # -- env resolution ----------------------------------------------------
    def _build_env(self, volume_dirs: dict) -> dict:
        env = dict(os.environ)
        container = self.spec.containers[0]
        # Mount paths become sandbox paths, exported via K_MOUNT_<name>.
        for mount in container.volume_mounts:
            if mount.name in volume_dirs:
                safe = re.sub(r"[^A-Za-z0-9]", "_", mount.name).upper()
                env[f"K_MOUNT_{safe}"] = volume_dirs[mount.name]
                # Also expose the declared mount path mapping so workloads
                # can translate /etc/mpi-style paths.
                env[f"K_MOUNT_PATH_{safe}"] = mount.mount_path
        env["K_POD_NAME"] = self.pod_name
        env["K_POD_NAMESPACE"] = self.namespace
        env["K_SANDBOX_DIR"] = self.sandbox
        # Preemption notice channel (the local stand-in for the GCE
        # metadata preemption event / SIGTERM grace window): chaos (or a
        # node drainer) touches this file; preemption-aware workloads
        # (parallel/train.run_train_loop) checkpoint-then-exit on it.
        env["K_PREEMPTION_NOTICE_FILE"] = self.preemption_notice_path
        # Elastic-resize notice channel (sched/elastic.py): the
        # scheduler touches this file on DEPARTING workers of a shrink
        # — the file's content is the target worker count — so the
        # workload can drain its optimizer-state shards and exit
        # cleanly inside the drain window (parallel/train.py
        # resize_requested; docs/SCHEDULING.md "Elastic gangs").
        env["K_RESIZE_NOTICE_FILE"] = self.resize_notice_path

        for ev in container.env:
            env[ev.name] = self.kubelet.resolve_env_value(ev.value)

        # Per-job coordinator port remap to avoid cross-job collisions.
        addr = env.get(api_constants.JAX_COORDINATOR_ADDRESS_ENV)
        if addr and ":" in addr:
            host, _, port = addr.rpartition(":")
            mapped = self.kubelet.job_port(self.namespace,
                                           self.spec.subdomain or host, port)
            env[api_constants.JAX_COORDINATOR_ADDRESS_ENV] = f"{host}:{mapped}"
            if api_constants.JAX_COORDINATOR_PORT_ENV in env:
                env[api_constants.JAX_COORDINATOR_PORT_ENV] = str(mapped)
            # resolve the coordinator hostname itself
            env[api_constants.JAX_COORDINATOR_ADDRESS_ENV] = \
                self.kubelet.resolve_env_value(
                    env[api_constants.JAX_COORDINATOR_ADDRESS_ENV])
        return env

    # -- main loop ---------------------------------------------------------
    def _run(self) -> None:
        try:
            self._run_inner()
        except Exception as exc:
            logger.exception("pod %s runner crashed: %s", self.pod_name, exc)
            self.kubelet._set_phase(self.namespace, self.pod_name,
                                    core.POD_FAILED, reason="RunnerError",
                                    message=str(exc))

    def _run_inner(self) -> None:
        container = self.spec.containers[0]
        command = list(container.command) + list(container.args)
        if not command:
            # No command: images' entrypoints don't exist locally.
            self.kubelet._set_phase(self.namespace, self.pod_name,
                                    core.POD_FAILED, reason="NoCommand",
                                    message="local runtime requires an"
                                            " explicit command")
            return
        if self.kubelet.claim_pod_ip(self.namespace, self.pod_name) is None:
            self.kubelet._set_phase(
                self.namespace, self.pod_name, core.POD_FAILED,
                reason="PodIPCollision",
                message="netsim address already assigned to a live pod")
            return
        volume_dirs = self._materialize_volumes()
        env = self._build_env(volume_dirs)

        while not self.stopped.is_set():
            # A preemption notice is per-incarnation: an in-place
            # restart (Always/OnFailure) must start clean, or the
            # replacement would see the stale notice and exit again —
            # an infinite checkpoint/exit/restart loop.
            try:
                os.unlink(self.preemption_notice_path)
            except OSError:
                pass
            # Resize notices are per-incarnation for the same reason.
            try:
                os.unlink(self.resize_notice_path)
            except OSError:
                pass
            with open(self.log_path, "ab") as log:
                self.proc = subprocess.Popen(
                    command, env=env, stdout=log, stderr=subprocess.STDOUT,
                    cwd=container.working_dir or self.sandbox)
            self.kubelet._set_phase(self.namespace, self.pod_name,
                                    core.POD_RUNNING, ready=True,
                                    restart_count=self.restart_count)
            code = self.proc.wait()
            if self.stopped.is_set():
                return  # deletion already handled
            if code == 0:
                if self.spec.restart_policy == core.RESTART_POLICY_ALWAYS:
                    self.restart_count += 1
                    time.sleep(min(0.2 * self.restart_count, 2.0))
                    continue
                self.kubelet._set_phase(self.namespace, self.pod_name,
                                        core.POD_SUCCEEDED,
                                        restart_count=self.restart_count)
                return
            if self.spec.restart_policy in (core.RESTART_POLICY_ALWAYS,
                                            core.RESTART_POLICY_ON_FAILURE):
                self.restart_count += 1
                time.sleep(min(0.2 * self.restart_count, 2.0))
                continue
            # Popen reports signal deaths as -signum; container runtimes
            # report 128+signum (137/143...).  Match the runtime contract
            # so ExitCode policy classifies signal kills as retryable.
            if code < 0:
                code = 128 - code
            self.kubelet._set_phase(
                self.namespace, self.pod_name, core.POD_FAILED,
                reason="Error",
                message=f"container exited with code {code}",
                restart_count=self.restart_count, exit_code=code)
            return

    def start(self) -> None:
        self.thread.start()

    def stop(self) -> None:
        self.stopped.set()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    def logs(self) -> str:
        try:
            with open(self.log_path) as f:
                return f.read()
        except OSError:
            return ""


class LocalKubelet:
    def __init__(self, clientset: Clientset, root_dir: Optional[str] = None,
                 namespace: Optional[str] = None):
        self.client = clientset
        self.namespace = namespace
        self.root_dir = root_dir or tempfile.mkdtemp(prefix="tpu-kubelet-")
        self._runners: dict = {}
        self._ports: dict = {}
        self._pod_ips: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watch = None
        self._thread: Optional[threading.Thread] = None

    # -- DNS / ports -------------------------------------------------------
    def resolve_env_value(self, value: str) -> str:
        """Rewrite cluster-DNS hostnames to their simulated addresses.

        Pod names (``<pod>.<svc>.<ns>.svc[.domain]``) get the pod's own
        per-pod loopback address (netsim; the namespace comes from the
        FQDN itself), so distinct "hosts" really are distinct endpoints;
        bare service names keep 127.0.0.1 (a headless Service has no
        single address)."""
        return netsim.rewrite(value) if value else value

    def claim_pod_ip(self, namespace: str, name: str) -> Optional[str]:
        """Claim the pod's deterministic netsim address before launch.

        The hash space is ~4.2M addresses, so a collision between two
        live pods is vanishingly unlikely — but it would silently
        collapse the distinct-endpoint guarantee, so a colliding claim
        returns None and the runner refuses to launch.  Claims are
        released when the pod object is deleted."""
        ip = netsim.pod_ip(namespace, name)
        with self._lock:
            owner = self._pod_ips.setdefault(ip, (namespace, name))
        if owner != (namespace, name):
            logger.error(
                "pod %s/%s: netsim address %s already assigned to pod "
                "%s/%s", namespace, name, ip, owner[0], owner[1])
            return None
        return ip

    def release_pod_ip(self, namespace: str, name: str) -> None:
        ip = netsim.pod_ip(namespace, name)
        with self._lock:
            if self._pod_ips.get(ip) == (namespace, name):
                del self._pod_ips[ip]

    def job_port(self, namespace: str, job_key: str, declared_port: str) -> int:
        with self._lock:
            key = (namespace, job_key, declared_port)
            if key not in self._ports:
                self._ports[key] = _free_port()
            return self._ports[key]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._watch = self.client.server.watch("v1", "Pod")
        self._cm_watch = self.client.server.watch("v1", "ConfigMap")
        # pick up pre-existing pods
        for pod in self.client.server.list("v1", "Pod", self.namespace):
            self._on_pod(pod)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="kubelet")
        self._thread.start()
        self._cm_thread = threading.Thread(target=self._cm_loop, daemon=True,
                                           name="kubelet-cm")
        self._cm_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._watch:
            self._watch.stop()
        if getattr(self, "_cm_watch", None):
            self._cm_watch.stop()
        if self._thread:
            self._thread.join(timeout=2)
        with self._lock:
            runners = list(self._runners.values())
        for r in runners:
            r.stop()
        shutil.rmtree(self.root_dir, ignore_errors=True)

    def _redial_watch(self, api_version: str, kind: str):
        """Re-open a watch the server closed (apiserver restart);
        None when the kubelet is stopping."""
        from ..k8s.apiserver import redial_watch
        return redial_watch(self.client, api_version, kind,
                            stop=self._stop)

    def _loop(self) -> None:
        from ..k8s.apiserver import (ADDED, CLOSED, DELETED, MODIFIED,
                                     RELIST, WatchEvent)
        while not self._stop.is_set():
            ev = self._watch.next(timeout=0.1)
            if ev is None:
                continue
            if ev.type == CLOSED:
                # Apiserver restarted: re-dial against the respawned
                # server, then reconcile the outage gap exactly like a
                # RELIST (runners are the surviving data plane — only
                # the watch stream died).
                w = self._redial_watch("v1", "Pod")
                if w is None:
                    return
                self._watch = w
                ev = WatchEvent(RELIST, None)
            if ev.type == RELIST:
                # Watch lost replay continuity (410): reconcile against a
                # fresh list so gap events aren't missed (obj is None) —
                # both creations (start) and deletions (stop orphans).
                try:
                    live = self.client.server.list("v1", "Pod",
                                                   self.namespace)
                except TRANSPORT_ERRORS:
                    continue  # transient API failure; next event heals
                live_keys = set()
                for pod in live:
                    live_keys.add((pod.metadata.namespace,
                                   pod.metadata.name))
                    self._on_pod(pod)
                with self._lock:
                    orphans = [(k, r) for k, r in self._runners.items()
                               if k not in live_keys]
                    for k, _ in orphans:
                        self._runners.pop(k, None)
                for k, runner in orphans:
                    runner.stop()
                    self.release_pod_ip(*k)
                continue
            pod = ev.obj
            if self.namespace is not None and pod.metadata.namespace != self.namespace:
                continue
            key = (pod.metadata.namespace, pod.metadata.name)
            if ev.type in (ADDED, MODIFIED):
                # MODIFIED matters for gated pods: removing schedulingGates
                # (Kueue's unsuspend flow) must start the pod.
                self._on_pod(pod)
            elif ev.type == DELETED:
                with self._lock:
                    runner = self._runners.pop(key, None)
                if runner is not None:
                    runner.stop()
                self.release_pod_ip(*key)

    def _cm_loop(self) -> None:
        from ..k8s.apiserver import CLOSED, MODIFIED
        while not self._stop.is_set():
            ev = self._cm_watch.next(timeout=0.1)
            if ev is not None and ev.type == CLOSED:
                w = self._redial_watch("v1", "ConfigMap")
                if w is None:
                    return
                self._cm_watch = w
                continue
            if ev is None or ev.type != MODIFIED:
                continue
            cm = ev.obj
            if self.namespace is not None and \
                    cm.metadata.namespace != self.namespace:
                continue
            with self._lock:
                runners = [r for (ns, _), r in self._runners.items()
                           if ns == cm.metadata.namespace]
            for runner in runners:
                try:
                    runner.refresh_config_volumes(cm.metadata.name)
                except Exception as exc:
                    logger.warning("refreshing volumes for %s: %s",
                                   runner.pod_name, exc)

    def _on_pod(self, pod: core.Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        with self._lock:
            if key in self._runners:
                return
            if pod.status.phase in (core.POD_SUCCEEDED, core.POD_FAILED):
                return
            if pod.spec.scheduling_gates:
                return  # gated pods wait (Kueue semantics)
            if gangsim.pod_gang_name(pod) is not None and \
                    (pod.metadata.annotations or {}).get(
                        gangsim.BOUND_ANNOTATION) != "true":
                # Gang-decorated pods (PodGroup annotation/label) stay
                # Pending until the gang scheduler binds them (reference
                # e2e contract: test/e2e/mpi_job_test.go:341-436 — pods
                # of an unsatisfiable PodGroup never run).  Pods with a
                # custom schedulerName but no gang membership run
                # normally — only the gang contract is simulated.
                return
            runner = _PodRunner(self, pod)
            self._runners[key] = runner
        runner.start()

    # -- chaos hooks -------------------------------------------------------
    def kill_pod(self, namespace: str, name: str, sig: int = 9) -> bool:
        """Kill the pod's container process with ``sig`` (default
        SIGKILL) WITHOUT touching the pod object — the node-crash /
        OOM-kill fault.  The runner's own wait() then reflects the
        signal death (exit 128+signum) and restart policy takes over.
        Returns False when no live process matches."""
        with self._lock:
            runner = self._runners.get((namespace, name))
        proc = runner.proc if runner is not None else None
        if proc is None or proc.poll() is not None:
            return False
        try:
            proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            return False
        return True

    def inject_preemption(self, namespace: str, name: str,
                          grace: float = 1.0) -> bool:
        """Deliver a preemption notice to the pod (touch its notice
        file, the K_PREEMPTION_NOTICE_FILE channel) and enforce the
        grace window: after ``grace`` seconds, SIGTERM the container if
        it has not exited on its own.  Mirrors a cloud provider's
        spot/preemption flow (notice -> grace -> termination)."""
        with self._lock:
            runner = self._runners.get((namespace, name))
        if runner is None:
            return False
        try:
            with open(runner.preemption_notice_path, "w") as f:
                f.write("preempted\n")
        except OSError:
            return False
        # Bind the grace enforcement to THIS incarnation: reading
        # runner.proc at fire time could SIGTERM an innocent
        # replacement process after an in-place restart.
        noticed_proc = runner.proc

        def _enforce():
            if noticed_proc is not None and noticed_proc.poll() is None:
                try:
                    noticed_proc.terminate()
                except (ProcessLookupError, OSError):
                    pass

        timer = threading.Timer(grace, _enforce)
        timer.daemon = True
        timer.start()
        return True

    def inject_resize(self, namespace: str, name: str, target: int,
                      deadline: float = 5.0) -> bool:
        """Deliver an elastic-resize notice to a DEPARTING worker pod
        (touch its K_RESIZE_NOTICE_FILE with the target worker count).
        Unlike a preemption notice there is NO kill timer — the
        scheduler owns the drain deadline and falls back to the full
        checkpoint-evict protocol if the worker never exits
        (sched/elastic.py).  Returns False when no runner matches."""
        with self._lock:
            runner = self._runners.get((namespace, name))
        if runner is None:
            return False
        try:
            with open(runner.resize_notice_path, "w") as f:
                f.write(f"{int(target)}\n")
        except OSError:
            return False
        flight.record("kubelet", "resize_notice",
                      pod=f"{namespace}/{name}", target=int(target),
                      deadline=deadline)
        return True

    # -- status reflection -------------------------------------------------
    def _set_phase(self, namespace: str, name: str, phase: str,
                   ready: bool = False, reason: str = "", message: str = "",
                   restart_count: int = 0,
                   exit_code: Optional[int] = None) -> None:
        # Conflicts retry immediately (informer-staleness normal case);
        # transient API failures (error bursts, partitions) retry with
        # backoff instead of abandoning the write — a dropped terminal
        # phase would leave the pod Running in the API forever while
        # the process is long gone.  The budget (~60s) must outlast any
        # realistic brown-out; on exhaustion give up with a logged
        # error rather than raising — this runs on the daemon runner
        # thread, and an unwound thread drops the write just the same
        # but silently.
        transient_left = 600
        conflicts = 0
        while True:
            try:
                pod = self.client.pods(namespace).get(name)
            except Exception as exc:
                if is_not_found(exc):
                    return
                transient_left -= 1
                if transient_left <= 0 or self._stop.is_set():
                    logger.error("giving up reflecting %s/%s -> %s: %s",
                                 namespace, name, phase, exc)
                    return
                time.sleep(0.1)
                continue
            pod.status.phase = phase
            pod.status.reason = reason
            pod.status.message = message
            if phase == core.POD_RUNNING and not pod.status.pod_ip:
                # Real kubelet semantics: podIP appears once the sandbox
                # is up; uniqueness was claimed before launch
                # (claim_pod_ip), so this is pure status reflection.
                pod.status.pod_ip = netsim.pod_ip(namespace, name)
                pod.status.host_ip = "127.0.0.1"
            pod.status.conditions = [c for c in pod.status.conditions
                                     if c.type != "Ready"]
            pod.status.conditions.append(core.PodCondition(
                type="Ready",
                status=core.CONDITION_TRUE if ready else core.CONDITION_FALSE))
            # Restart counts feed the Job backoffLimit accounting (real
            # kubelet/Job-controller semantics for restartPolicy=OnFailure).
            # Terminated exit codes feed RestartPolicy=ExitCode semantics
            # (retryable 128-255 vs permanent 1-127 gang decisions).
            state = None
            if exit_code is not None:
                state = core.ContainerState(
                    terminated=core.ContainerStateTerminated(
                        exit_code=exit_code, reason=reason, message=message))
            pod.status.container_statuses = [core.ContainerStatus(
                name=pod.spec.containers[0].name if pod.spec.containers else "",
                ready=ready, restart_count=restart_count, state=state)]
            try:
                self.client.pods(namespace).update_status(pod)
                flight.record("kubelet", "pod_phase",
                              pod=f"{namespace}/{name}", phase=phase,
                              reason=reason, restart_count=restart_count,
                              exit_code=exit_code)
                if phase == core.POD_RUNNING and restart_count == 0:
                    self._trace_pod_start(namespace, name, pod)
                return
            except Exception as exc:
                if is_not_found(exc):
                    return
                if is_conflict(exc):
                    conflicts += 1
                    if conflicts >= 20:
                        logger.error("giving up reflecting %s/%s -> %s:"
                                     " conflicts exhausted",
                                     namespace, name, phase)
                        return
                    continue
                transient_left -= 1
                if transient_left <= 0 or self._stop.is_set():
                    logger.error("giving up reflecting %s/%s -> %s: %s",
                                 namespace, name, phase, exc)
                    return
                time.sleep(0.1)

    @staticmethod
    def _trace_pod_start(namespace: str, name: str, pod) -> None:
        """Causal-trace milestone: pod object create → first Running —
        the kubelet hop of the bootstrap path, parented explicitly to
        the job context the controller stamped on the pod (the
        scheduler-decision → kubelet handoff has no shared thread)."""
        from ..telemetry.trace import annotation_context, default_tracer
        ctx = annotation_context(pod)
        created = pod.metadata.creation_timestamp
        if ctx is None or created is None:
            return
        t0 = created.timestamp()
        default_tracer().emit("pod_start", ts=t0,
                              dur=max(0.0, time.time() - t0), ctx=ctx,
                              pod=f"{namespace}/{name}")

    def logs(self, namespace: str, name: str) -> str:
        with self._lock:
            runner = self._runners.get((namespace, name))
        return runner.logs() if runner else ""
