"""GangSchedulerSim — a minimal Volcano / coscheduling stand-in.

The reference e2e installs the real Volcano and scheduler-plugins via
helm and verifies pods actually *gate* on the PodGroup — stay Pending
until the whole gang fits (test/e2e/e2e_suite_test.go:186-243,
test/e2e/mpi_job_test.go:341-436).  The hermetic runtime reproduces the
same observable contract without a cluster:

- Pods whose ``spec.schedulerName`` names a gang scheduler are ignored
  by the LocalKubelet (exactly like the default kube-scheduler ignores
  them) until this simulator *binds* them, which it records as the
  ``scheduling.local/bound`` pod annotation.
- The simulator binds a gang only when every member exists AND the gang
  fits the configured capacity (``minMember <= capacity``); capacity is
  the stand-in for allocatable cluster resources.
- Until then it publishes honest PodGroup status — Volcano
  ``status.phase: Pending`` with an ``Unschedulable`` condition, or the
  scheduler-plugins phase grammar — which the controller consumes back
  into the MPIJob ``WorkersGated`` condition
  (controller.py ``_sync_pod_group_feedback``).

This closes the loop the round-2 review flagged: PodGroup status is no
longer write-only, and the e2e scheduler-sim refuses to run pods until
minMember is satisfiable instead of relying on hand-cleared gates.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..k8s.apiserver import Clientset, is_conflict, is_not_found
from ..k8s.scheduling import (SCHED_PLUGINS_API_VERSION,
                              SCHED_PLUGINS_POD_GROUP_LABEL,
                              VOLCANO_API_VERSION,
                              VOLCANO_POD_GROUP_NAME_ANNOTATION)

logger = logging.getLogger("mpi_operator_tpu.runtime.gangsim")

# Pods carrying this annotation with value "true" have been placed by
# the gang scheduler; the LocalKubelet refuses to run gang-scheduled
# pods without it (the binding act of a real scheduler).
BOUND_ANNOTATION = "scheduling.local/bound"

_VOLCANO = (VOLCANO_API_VERSION, "PodGroup")
_SCHED_PLUGINS = (SCHED_PLUGINS_API_VERSION, "PodGroup")


def pod_gang_name(pod) -> Optional[str]:
    """The PodGroup a pod belongs to, per the decoration the controller
    applied (podgroup.py decorate_pod_template)."""
    name = (pod.metadata.annotations or {}).get(
        VOLCANO_POD_GROUP_NAME_ANNOTATION)
    if name:
        return name
    return (pod.metadata.labels or {}).get(SCHED_PLUGINS_POD_GROUP_LABEL)


class GangSchedulerSim:
    """Watches PodGroups + member pods; binds whole gangs or reports
    why it can't.

    ``capacity`` is the number of pods the simulated cluster can place
    at once (None = unbounded).  ``set_capacity`` mid-run models nodes
    joining/leaving — the next reconcile re-evaluates every gang.
    """

    def __init__(self, clientset: Clientset, capacity: Optional[int] = None,
                 namespace: Optional[str] = None):
        self.client = clientset
        self.namespace = namespace
        self._capacity = capacity
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watches: list = []

    # -- capacity ----------------------------------------------------------
    @property
    def capacity(self) -> Optional[int]:
        with self._lock:
            return self._capacity

    def set_capacity(self, capacity: Optional[int]) -> None:
        with self._lock:
            self._capacity = capacity
        self._kick.set()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "GangSchedulerSim":
        for api_version, kind in (_VOLCANO, _SCHED_PLUGINS, ("v1", "Pod")):
            self._watches.append(
                self.client.server.watch(api_version, kind))
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="gang-scheduler-sim")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for w in self._watches:
            w.stop()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        # Reconcile every ~0.15s tick (cheap and idempotent; relists, so
        # the watches exist only to bound memory, not to carry state) —
        # set_capacity kicks an immediate pass.
        from ..k8s.apiserver import CLOSED, redial_watch
        kinds = (_VOLCANO, _SCHED_PLUGINS, ("v1", "Pod"))
        while not self._stop.is_set():
            # Drain watch queues fully: one event per tick would let the
            # backlog grow without bound under pod churn (reconcile's own
            # binds generate events too).
            for i, w in enumerate(self._watches):
                while True:
                    ev = w.next(timeout=0)
                    if ev is None:
                        break
                    if ev.type == CLOSED:
                        # Apiserver restarted: re-dial; the relist-
                        # shaped reconcile covers the gap.
                        fresh = redial_watch(self.client, *kinds[i],
                                             stop=self._stop)
                        if fresh is not None:
                            self._watches[i] = fresh
                        break
            self._kick.clear()
            try:
                self.reconcile_once()
            except Exception:
                logger.exception("gang reconcile failed")
            self._kick.wait(timeout=0.15)

    # -- the scheduler -----------------------------------------------------
    def reconcile_once(self) -> None:
        # Capacity is a cluster-wide pool: pods already bound (placed)
        # debit it, so concurrent gangs cannot over-commit.  Gangs are
        # visited in creation order — FIFO admission, like a real queue.
        used = sum(
            1 for p in self.client.server.list("v1", "Pod", self.namespace)
            if (p.metadata.annotations or {}).get(BOUND_ANNOTATION) == "true"
            and p.status.phase not in ("Succeeded", "Failed"))
        groups = []
        for api_version, _ in (_VOLCANO, _SCHED_PLUGINS):
            for pg in self.client.server.list(
                    api_version, "PodGroup", self.namespace):
                groups.append((api_version, pg))
        groups.sort(key=lambda item: (
            str(item[1].metadata.creation_timestamp or ""),
            item[1].metadata.name))
        for api_version, pg in groups:
            used += self._sync_group(api_version, pg, used)

    def _members(self, namespace: str, group: str) -> list:
        return [p for p in self.client.server.list("v1", "Pod", namespace)
                if pod_gang_name(p) == group]

    def _sync_group(self, api_version: str, pg, used: int) -> int:
        """Reconcile one gang; returns how many *new* placements it made
        so the caller can debit the shared capacity pool."""
        ns = pg.metadata.namespace
        members = self._members(ns, pg.metadata.name)
        min_member = pg.spec.min_member or 0
        capacity = self.capacity
        volcano = api_version == VOLCANO_API_VERSION

        members.sort(key=lambda p: p.metadata.name)  # deterministic order
        bound = [p for p in members
                 if (p.metadata.annotations or {}).get(
                     BOUND_ANNOTATION) == "true"]
        unbound = [p for p in members if p not in bound]
        # `used` already counts this gang's bound pods; free slots are
        # what the rest of the cluster leaves over.
        free = None if capacity is None else capacity - used

        if len(bound) >= min_member > 0:
            # Gang is placed; keep reporting the placed phase, and bind
            # stragglers (replacement pods after a scale-up) only as
            # capacity allows — they still debit the pool.
            extra = unbound if free is None else unbound[:max(0, free)]
            for pod in extra:
                self._bind(pod)
            self._set_status(api_version, pg, "Running" if volcano
                             else "Scheduled")
            return len(extra)

        if free is not None and min_member > free + len(bound):
            reason = (f"{min_member}/{min_member} tasks unschedulable: "
                      f"gang needs {min_member} slots, cluster capacity "
                      f"is {capacity} ({free} free)")
            phase = "Pending" if volcano else "Unschedulable"
            self._set_status(api_version, pg, phase, unschedulable=reason)
            return 0
        if len(members) < min_member:
            # Gang incomplete — a real gang scheduler waits for all
            # members before placing any (the whole point).
            phase = "Pending" if volcano else "PreScheduling"
            self._set_status(api_version, pg, phase)
            return 0

        # Gang fits (min_member - len(bound) <= free): bind members up
        # to the free slots — minMember guaranteed, extras while
        # capacity remains (a real scheduler places what fits beyond
        # the gang minimum).
        placeable = unbound if free is None else unbound[:free]
        for pod in placeable:
            self._bind(pod)
        self._set_status(api_version, pg, "Running" if volcano
                         else "Scheduled")
        return len(placeable)

    def _bind(self, pod) -> None:
        if (pod.metadata.annotations or {}).get(BOUND_ANNOTATION) == "true":
            return
        for _ in range(5):
            try:
                fresh = self.client.pods(pod.metadata.namespace).get(
                    pod.metadata.name)
                fresh.metadata.annotations = dict(
                    fresh.metadata.annotations or {})
                fresh.metadata.annotations[BOUND_ANNOTATION] = "true"
                self.client.pods(pod.metadata.namespace).update(fresh)
                return
            except Exception as exc:
                if is_not_found(exc):
                    return
                if not is_conflict(exc):
                    raise
        # The 0.15s reconcile tick retries the bind, but a persistently
        # conflicting pod should be visible in test output, not silent.
        logger.warning("bind retry budget exhausted for pod %s/%s",
                       pod.metadata.namespace, pod.metadata.name)

    def _set_status(self, api_version: str, pg, phase: str,
                    unschedulable: str = "") -> None:
        conditions = []
        if unschedulable:
            conditions = [{"type": "Unschedulable", "status": "True",
                           "reason": "NotEnoughResources",
                           "message": unschedulable}]
        status = {"phase": phase, "conditions": conditions}
        if (pg.status or {}) == status:
            return
        ctl = (self.client.volcano_pod_groups
               if api_version == VOLCANO_API_VERSION
               else self.client.sched_plugins_pod_groups)
        for _ in range(5):
            try:
                fresh = ctl(pg.metadata.namespace).get(pg.metadata.name)
                if (fresh.status or {}) == status:
                    return
                fresh.status = status
                ctl(pg.metadata.namespace).update_status(fresh)
                return
            except Exception as exc:
                if is_not_found(exc):
                    return
                if not is_conflict(exc):
                    raise
        logger.warning("status retry budget exhausted for podgroup %s/%s"
                       " (phase %s)", pg.metadata.namespace,
                       pg.metadata.name, phase)
