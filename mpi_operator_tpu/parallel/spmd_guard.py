"""Guard against silent GSPMD performance regressions.

XLA's SPMD partitioner emits ``[SPMD] Involuntary full rematerialization``
(spmd_partitioner.cc) when it cannot move a tensor between two shardings
efficiently and falls back to replicate-then-reshard — on real hardware
that is a full all-gather of the tensor every step, silently.  The
warning goes to the C-level stderr (abseil logging), not through Python,
so catching it requires an fd-level capture.

``forbid_full_remat()`` wraps a compile region: fd 2 is teed through a
pipe — every byte still reaches the real stderr *live* (driver timeouts /
SIGKILL lose nothing) while a copy accumulates for the marker scan — and
the block raises if the warning appeared.  Used by ``__graft_entry__
.dryrun_multichip`` so the driver gate *fails* on the regression instead
of tolerating it in its own log, and by tests/test_spmd_guard.py.

Note: XLA caches compilations per process — wrap the *first* compile of
a computation, or the warning will already have been emitted.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading

REMAT_MARKER = b"Involuntary full rematerialization"


@contextlib.contextmanager
def capture_stderr_fd():
    """Tee OS-level fd 2 through a pipe for the duration: bytes flow to
    the original stderr immediately AND accumulate in a buffer.  Yields a
    zero-arg callable returning the bytes captured so far."""
    sys.stderr.flush()
    saved = os.dup(2)
    rd, wr = os.pipe()
    chunks: list = []
    lock = threading.Lock()

    def pump():
        while True:
            try:
                chunk = os.read(rd, 65536)
            except OSError:
                break
            if not chunk:
                break
            with lock:
                chunks.append(chunk)
            os.write(saved, chunk)
        os.close(rd)

    pumper = threading.Thread(target=pump, daemon=True)
    pumper.start()
    os.dup2(wr, 2)
    os.close(wr)

    def read() -> bytes:
        sys.stderr.flush()
        with lock:
            return b"".join(chunks)

    try:
        yield read
    finally:
        sys.stderr.flush()
        # Restoring fd 2 drops the pipe's last write end -> pump sees EOF.
        os.dup2(saved, 2)
        pumper.join(timeout=10)
        os.close(saved)


@contextlib.contextmanager
def forbid_full_remat():
    """Fail loudly if XLA emits an involuntary-full-rematerialization
    warning inside the block.  stderr flows through live (teed), so
    nothing disappears from driver logs even on a mid-run kill.

    The marker scan happens AFTER the capture context closes: its exit
    restores fd 2 (EOF to the pump) and joins the pump thread, so the
    buffer is complete — a mid-capture read would race the tee."""
    body_raised = True
    try:
        with capture_stderr_fd() as read:
            yield
            body_raised = False
    finally:
        captured = read()
        if not body_raised and REMAT_MARKER in captured:
            lines = [ln for ln in
                     captured.decode("utf-8", "replace").splitlines()
                     if REMAT_MARKER.decode() in ln]
            raise RuntimeError(
                "XLA SPMD fell back to involuntary full rematerialization "
                "(a hidden per-step all-gather of the whole tensor); fix "
                "the PartitionSpecs or add a with_sharding_constraint.  "
                "Warnings:\n" + "\n".join(lines))
