"""Device mesh construction and sharding helpers.

The scaling recipe (jax-ml "How to Scale Your Model"): pick a mesh whose
inner axes ride ICI (tp, sp) and outer axes ride DCN (dp across slices),
annotate shardings, and let XLA place the collectives.  On GKE the
operator schedules one process per TPU host (slotsPerWorker chips each);
inside the workload this module turns those processes + local chips into
one global mesh.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass
class MeshConfig:
    """Mesh axis sizes; -1 on dp means "use all remaining devices"."""
    dp: int = -1     # data parallel (gradients psum; DCN-friendly)
    fsdp: int = 1    # parameter/optimizer sharding (ZeRO-3; ICI)
    pp: int = 1      # pipeline parallel (stage ring via ppermute; ICI)
    ep: int = 1      # expert parallel (MoE all-to-all; ICI)
    tp: int = 1      # tensor parallel (Megatron matmul sharding; ICI)
    sp: int = 1      # sequence/context parallel (ring attention; ICI)

    def resolve(self, n_devices: int) -> tuple:
        fixed = self.fsdp * self.pp * self.ep * self.tp * self.sp
        dp = self.dp
        if dp == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by"
                    f" fsdp*pp*ep*tp*sp={fixed}")
            dp = n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"mesh {dp}x{self.fsdp}x{self.pp}x{self.ep}x{self.tp}"
                f"x{self.sp} != {n_devices} devices")
        return (dp, self.fsdp, self.pp, self.ep, self.tp, self.sp)


AXIS_NAMES = ("dp", "fsdp", "pp", "ep", "tp", "sp")
# Axes over which the batch is sharded (gradient reduction axes).
BATCH_AXES = ("dp", "fsdp")


def create_mesh(config: Optional[MeshConfig] = None, devices=None):
    """Build a Mesh with axes (dp, fsdp, tp, sp).

    Device order matters for ICI locality: the innermost mesh axes map to
    the fastest-varying device coordinates, so tp/sp neighbors are
    ICI-adjacent on a real slice while dp spans hosts/slices (DCN).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config or MeshConfig()
    shape = config.resolve(len(devices))
    return Mesh(np.asarray(devices).reshape(shape), AXIS_NAMES)


def create_multislice_mesh(config: Optional[MeshConfig] = None,
                           num_slices: int = 1, devices=None):
    """Mesh spanning TPU slices: dp rides DCN (outer, across slices),
    every other axis rides ICI (inner, within a slice).

    On real multislice hardware jax devices carry slice_index and
    mesh_utils.create_hybrid_device_mesh places them; on a flat device
    set (CPU dryrun, single slice) the devices are grouped into
    num_slices contiguous blocks — same topology, virtual slices.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config or MeshConfig()
    if num_slices <= 1:
        return create_mesh(config, devices)
    if len(devices) % num_slices != 0:
        raise ValueError(f"{len(devices)} devices not divisible by "
                         f"{num_slices} slices")
    per_slice = len(devices) // num_slices
    shape = config.resolve(len(devices))
    dp = shape[0]
    if dp % num_slices != 0:
        raise ValueError(
            f"dp={dp} must be a multiple of num_slices={num_slices}: dp is "
            f"the only DCN-friendly axis, so every slice boundary must land"
            f" on it")
    if all(hasattr(d, "slice_index") for d in devices):
        from jax.experimental import mesh_utils
        dcn = (num_slices,) + (1,) * (len(AXIS_NAMES) - 1)
        ici = (dp // num_slices,) + shape[1:]
        mesh_devices = mesh_utils.create_hybrid_device_mesh(
            ici, dcn, devices=devices)
        return Mesh(mesh_devices, AXIS_NAMES)
    # Virtual slices: contiguous per-slice blocks; dp's outer dimension
    # iterates slices, its inner dimension iterates within a slice.
    arr = np.asarray(devices).reshape((num_slices, per_slice))
    arr = arr.reshape((num_slices, dp // num_slices) + shape[1:])
    return Mesh(arr.reshape(shape), AXIS_NAMES)


def placement_from_env():
    """The gang scheduler's topology surface, as injected into worker
    pods by controller/builders.propagate_placement: returns
    ``{"placement": {slice: [Block, ...]}, "num_slices": int,
    "slice": str|None, "coords": tuple|None}`` or None when this
    process runs outside a scheduler-placed gang.  ``num_slices`` is
    the natural argument for :func:`create_multislice_mesh` (and > 1
    means ``build_train_step(hierarchical_allreduce=True)`` has a DCN
    tier to win on)."""
    import os

    from ..api import constants
    from ..sched.topology import decode_placement

    raw = os.environ.get(constants.PLACEMENT_ENV)
    if not raw:
        return None
    placement = decode_placement(raw)
    if not placement:
        return None
    coords_raw = os.environ.get(constants.CHIP_COORDS_ENV, "")
    coords = None
    if coords_raw:
        try:
            coords = tuple(int(v) for v in coords_raw.split("."))
        except ValueError:
            coords = None
    # NUM_SLICES_ENV is the authoritative injected value (also the
    # surface non-Python workloads read without a placement decoder);
    # the decoded placement is the fallback.
    try:
        num_slices = int(os.environ.get(constants.NUM_SLICES_ENV, ""))
    except ValueError:
        num_slices = len(placement)
    return {
        "placement": placement,
        "num_slices": num_slices,
        "slice": os.environ.get(constants.SLICE_NAME_ENV) or None,
        "coords": coords,
    }


def batch_sharding(mesh, extra_dims: int = 1):
    """NamedSharding for [batch, ...]: batch over (dp, fsdp), rest
    replicated (activations within a layer get their own constraints)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(BATCH_AXES, *([None] * extra_dims)))


def seq_batch_sharding(mesh):
    """[batch, seq] sharding for token ids under sequence parallelism."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(BATCH_AXES, "sp"))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def shard_params(params, param_specs, mesh):
    """Apply a PartitionSpec pytree to a param pytree as NamedShardings."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda p, spec: jax.device_put(p, NamedSharding(mesh, spec)),
        params, param_specs)
