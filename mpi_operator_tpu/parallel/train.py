"""Sharded training step builder.

One jit-compiled SPMD train step over the (dp, fsdp, tp, sp) mesh:
parameters live in their PartitionSpec shardings (fsdp/tp sharded), the
batch is sharded over (dp, fsdp) [+ seq over sp], and XLA derives every
collective (gradient psum over dp, reduce-scatter/all-gather for fsdp,
tp matmul collectives) from the sharding annotations — the Horovod
allreduce of the reference's examples (SURVEY.md §2.3) with the compiler
holding the pen.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .mesh import shard_params


@dataclass
class TrainState:
    """Minimal train state (flax TrainState without the apply coupling)."""
    step: Any
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def _spec_axes(entry):
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


def graft_spec(shape, base_spec, axis: str, size: int):
    """Base PartitionSpec with ``axis`` grafted onto the first free
    dimension divisible by ``size``; base unchanged when no dimension
    qualifies or the axis already appears.  Shared by the ZeRO update
    sharding, the hierarchical-allreduce layout, and the elastic
    re-shard (reshard_train_state)."""
    from jax.sharding import PartitionSpec as P
    base = tuple(base_spec) if base_spec is not None else ()
    base = base + (None,) * (len(shape) - len(base))
    used = {n for e in base for n in _spec_axes(e)}
    if axis not in used:
        for d, dim in enumerate(shape):
            if base[d] is None and dim > 0 and dim % size == 0:
                base = base[:d] + (axis,) + base[d + 1:]
                break
    return P(*base)


def zero_shape_specs(params, base_specs, dp_size: int) -> dict:
    """shape -> ZeRO spec map for optimizer-state leaves (optax state
    trees don't share the params' treedef, so leaves match by SHAPE).
    Two same-shape params with different base specs make the mapping
    ambiguous — those shapes are dropped and XLA propagates a
    consistent sharding from the constrained grads/params instead."""
    zspecs = jax.tree_util.tree_map(
        lambda p, s: graft_spec(p.shape, s, "dp", dp_size),
        params, base_specs)
    seen, conflicts = {}, set()
    for leaf, spec in zip(jax.tree_util.tree_leaves(params),
                          jax.tree_util.tree_leaves(zspecs)):
        if seen.setdefault(leaf.shape, spec) != spec:
            conflicts.add(leaf.shape)
    return {
        shape: spec for shape, spec in seen.items()
        if shape not in conflicts
        and "dp" in {n for e in spec for n in _spec_axes(e)}}


def build_train_step(loss_fn: Callable, optimizer, mesh,
                     param_specs=None,
                     donate: bool = True,
                     remat: bool = False,
                     accum_steps: int = 1,
                     shard_update: bool = False,
                     hierarchical_allreduce: bool = False,
                     ici_axis: str = "fsdp",
                     goodput=None,
                     telemetry_registry=None,
                     sync_every: Optional[int] = None):
    """Build (init_fn, step_fn).

    - loss_fn(params, batch) -> scalar loss (called under jit/mesh).
    - optimizer: an optax GradientTransformation.
    - param_specs: pytree of PartitionSpec for params (None = replicated).
    - remat: wrap loss in jax.checkpoint to trade FLOPs for HBM.
    - accum_steps: >1 runs the batch as that many gradient-accumulation
      microbatches under one optimizer update (lax.scan, f32 gradient
      accumulator) — activation memory drops ~accum_steps x for the
      same effective batch.  The microbatch split is strided (row r ->
      microbatch r % accum_steps), so each microbatch keeps the full
      batch's (dp, fsdp) sharding instead of collapsing onto a fraction
      of the mesh — which requires the batch dim to divide by
      accum_steps x (dp*fsdp), enforced at trace time.  Gradients equal
      the full-batch step's exactly (for the usual mean-reduction
      losses) up to f32 reassociation.

    - shard_update: ZeRO-style cross-replica sharding of the weight
      update (arXiv:2004.13336).  Optimizer state and the update
      computation are partitioned over the ``dp`` axis via
      PartitionSpec annotations: each dp replica applies the update for
      its 1/dp slice of the params (XLA lowers the annotations to a
      reduce-scatter of the gradients and an all-gather of the updated
      shards), cutting optimizer-state HBM by ~dp x.  Elementwise
      optimizer math is unchanged per parameter, so results are
      numerically equivalent to the replicated update.  Per-leaf: the
      first spec-free dimension divisible by dp is sharded; leaves with
      no such dimension (odd shapes, scalars like adam's count) stay on
      their base sharding.  A 1-sized dp axis degenerates to the plain
      replicated update.

    - hierarchical_allreduce: the MLPerf TPU-pod gradient schedule for
      bandwidth-asymmetric hierarchies (arXiv:1909.09756,
      arXiv:1802.05799; docs/PERF.md "Hierarchical collectives").  The
      mesh convention puts ``dp`` across slices (DCN) and ``ici_axis``
      (default ``fsdp``) within a slice (ICI) — parallel/mesh.py.
      Instead of allreducing the full gradient across both tiers, the
      gradients are constrained onto an ``ici_axis``-sharded layout
      FIRST: XLA lowers the cross-replica reduction as a reduce-scatter
      over the fast intra-slice tier, an allreduce of only the
      1/ici-sized shard across slices over DCN, and an allgather back
      over ICI — the slow tier is crossed exactly once with 1/n of the
      bytes.  Composes with ``shard_update`` (the ZeRO update then
      consumes the ICI-sharded gradients directly) and is numerically
      equivalent to the flat schedule up to f32 reassociation
      (allclose-asserted in tests; the step-time win is priced by the
      sched/topology.py cost model and proven in bench_topo.py).  A
      1-sized ``ici_axis`` degenerates to the flat schedule.

    - goodput / telemetry_registry: when either is set, the returned
      step_fn is wrapped by telemetry.goodput.instrument_step — async
      dispatch with a sliding goodput sync every ``sync_every`` steps
      (``sync_every=1`` restores blocking per-step timing; see
      telemetry/goodput.py).

    step_fn(state, batch) -> (state, metrics) with donated state buffers.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    _batch_shards = 1
    for axis in ("dp", "fsdp"):
        _batch_shards *= mesh.shape.get(axis, 1)
    if remat:
        loss_fn = jax.checkpoint(loss_fn)

    dp_size = mesh.shape.get("dp", 1)
    zero = shard_update and dp_size > 1
    ici_size = mesh.shape.get(ici_axis, 1)
    hier = hierarchical_allreduce and ici_size > 1

    def _base_specs(params):
        if param_specs is not None:
            return param_specs
        return jax.tree_util.tree_map(lambda p: P(), params)

    def _zero_spec(shape, base_spec):
        """Base spec with 'dp' grafted onto the first free dimension
        divisible by dp (the ZeRO shard axis); base unchanged when no
        dimension qualifies or dp already appears."""
        return graft_spec(shape, base_spec, "dp", dp_size)

    def _hier_spec(shape, base_spec):
        """Base spec with the intra-slice axis grafted (the
        hierarchical reduce-scatter layout)."""
        return graft_spec(shape, base_spec, ici_axis, ici_size)

    def _zero_plan(params):
        """(param zero specs, base specs, shape->zero spec map for
        optimizer-state leaves).  Computed from shapes only, so it works
        identically on concrete arrays (init) and tracers (step)."""
        base_specs = _base_specs(params)
        zspecs = jax.tree_util.tree_map(
            lambda p, s: _zero_spec(p.shape, s), params, base_specs)
        shape_spec = zero_shape_specs(params, base_specs, dp_size)
        return zspecs, base_specs, shape_spec

    def _constrain(tree, specs):
        return jax.tree_util.tree_map(
            lambda x, s: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, s)),
            tree, specs)

    def _constrain_opt(opt_state, shape_spec):
        def f(x):
            spec = shape_spec.get(getattr(x, "shape", None))
            if spec is None:
                return x
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec))
        return jax.tree_util.tree_map(f, opt_state)

    def init_fn(params):
        if param_specs is not None:
            params = shard_params(params, param_specs, mesh)
        opt_state = optimizer.init(params)
        # Every leaf must carry a mesh sharding (param-shaped moments
        # inherit it from zeros_like; scalars like adam's count do not):
        # checkpoint-restore commits arrays to their saved shardings, and a
        # single-device-committed scalar would then conflict with mesh-wide
        # params under jit.
        replicated = NamedSharding(mesh, P())

        def _pin(x):
            if hasattr(x, "sharding") and isinstance(x.sharding,
                                                     NamedSharding):
                return x
            return jax.device_put(x, replicated)

        opt_state = jax.tree_util.tree_map(_pin, opt_state)
        if zero:
            # ZeRO: each dp replica holds only its 1/dp shard of the
            # param-shaped optimizer-state leaves from step 0 on.
            _, _, shape_spec = _zero_plan(params)

            def _place(x):
                spec = shape_spec.get(getattr(x, "shape", None))
                if spec is None:
                    return x
                return jax.device_put(x, NamedSharding(mesh, spec))

            opt_state = jax.tree_util.tree_map(_place, opt_state)
        step = jax.device_put(jnp.zeros((), jnp.int32), replicated)
        return TrainState(step=step, params=params, opt_state=opt_state)

    def _accumulate(params, batch):
        """Mean loss/grads over accum_steps strided microbatches."""
        def split(x):
            b = x.shape[0]
            if b % (accum_steps * _batch_shards):
                # Divisibility by accum_steps alone would trace, but the
                # strided microbatches could no longer keep every
                # (dp, fsdp) shard populated — XLA would insert a batch
                # reshuffle per microbatch, silently defeating the point
                # of the strided split.
                raise ValueError(
                    f"batch dim {b} not divisible by accum_steps"
                    f" {accum_steps} x batch shards {_batch_shards}"
                    f" (dp*fsdp)")
            # [B, ...] -> [A, B/A, ...] with row r in microbatch
            # r % A: dim 0 of the original stays the contiguous-major
            # axis of the reshape, so the microbatch rows remain spread
            # over every (dp, fsdp) shard.
            return jnp.moveaxis(
                x.reshape((b // accum_steps, accum_steps) + x.shape[1:]),
                1, 0)

        micro = jax.tree_util.tree_map(split, batch)
        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)

        def body(carry, mb):
            loss_acc, g_acc = carry
            loss, g = jax.value_and_grad(loss_fn)(params, mb)
            g_acc = jax.tree_util.tree_map(
                lambda a, x: a + x.astype(jnp.float32), g_acc, g)
            return (loss_acc + loss.astype(jnp.float32), g_acc), None

        (loss_sum, g_sum), _ = jax.lax.scan(
            body, (jnp.float32(0.0), g0), micro)
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / accum_steps).astype(p.dtype), g_sum, params)
        return loss_sum / accum_steps, grads

    def _step(state: TrainState, batch):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        else:
            loss, grads = _accumulate(state.params, batch)
        if hier:
            # Hierarchical allreduce: land the cross-replica gradient
            # reduction on an ICI-sharded layout, so the partitioner
            # emits reduce-scatter(ICI) + allreduce(DCN, 1/ici shard)
            # instead of a flat allreduce whose full payload crosses
            # the slow tier.  Non-ZeRO steps gather the shards back to
            # the base layout for the replicated update; the ZeRO path
            # re-shards onto dp below and keeps the update sharded.
            base_specs = _base_specs(state.params)
            hspecs = jax.tree_util.tree_map(
                lambda p, s: _hier_spec(p.shape, s),
                state.params, base_specs)
            grads = _constrain(grads, hspecs)
            if not zero:
                grads = _constrain(grads, base_specs)
        if zero:
            # ZeRO-style sharded update: reduce-scatter the (already
            # dp-reduced) grads and the params onto their dp shards,
            # apply the optimizer on 1/dp of every leaf per replica,
            # then all-gather only the updated param shards.  The
            # optimizer state never materializes unsharded.
            zspecs, base_specs, shape_spec = _zero_plan(state.params)
            g_c = _constrain(grads, zspecs)
            p_c = _constrain(state.params, zspecs)
            o_c = _constrain_opt(state.opt_state, shape_spec)
            updates, new_opt_state = optimizer.update(g_c, o_c, p_c)
            new_params = jax.tree_util.tree_map(
                lambda p, u: (p + u).astype(p.dtype), p_c, updates)
            new_params = _constrain(new_params, base_specs)
            new_opt_state = _constrain_opt(new_opt_state, shape_spec)
        else:
            updates, new_opt_state = optimizer.update(
                grads, state.opt_state, state.params)
            new_params = jax.tree_util.tree_map(
                lambda p, u: (p + u).astype(p.dtype), state.params, updates)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt_state)
        metrics = {"loss": loss.astype(jnp.float32),
                   "grad_norm": optax_global_norm(grads)}
        return new_state, metrics

    # Params arrive sharded via init_fn; jit propagates those shardings to
    # the outputs (and the optimizer state inherits them), so no explicit
    # out_shardings are needed — donation keeps buffers in place.
    step_fn = jax.jit(_step, donate_argnums=(0,) if donate else ())
    if goodput is not None or telemetry_registry is not None:
        from ..telemetry.goodput import instrument_step
        step_fn = instrument_step(step_fn, goodput=goodput,
                                  registry=telemetry_registry,
                                  sync_every=sync_every)
    return init_fn, step_fn


def optax_global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in leaves))


# ---------------------------------------------------------------------------
# Preemption-aware training loop
# ---------------------------------------------------------------------------

# The kubelet (runtime/kubelet.py) exports the pod's notice-file path in
# this env var; a cloud deployment points it at whatever surface the
# provider's preemption notice lands on.  Existence of the file IS the
# notice.
PREEMPTION_NOTICE_ENV = "K_PREEMPTION_NOTICE_FILE"

# Retryable by RestartPolicy=ExitCode (128-255): a preemption exit must
# trigger gang repair + resume-from-checkpoint, never a permanent
# MPIJob failure.  143 = 128 + SIGTERM, the code an un-aware workload
# would die with anyway when the grace window closes.
PREEMPTION_EXIT_CODE = 143


def preemption_notice_path() -> Optional[str]:
    """Where this process's preemption notice appears (None when no
    channel is configured — bare-metal runs outside the runtime)."""
    path = os.environ.get(PREEMPTION_NOTICE_ENV)
    if path:
        return path
    sandbox = os.environ.get("K_SANDBOX_DIR")
    if sandbox:
        return os.path.join(sandbox, "preemption.notice")
    return None


def preemption_requested(path: Optional[str] = None) -> bool:
    path = path or preemption_notice_path()
    return bool(path) and os.path.exists(path)


# ---------------------------------------------------------------------------
# Elastic gang resize: live re-sharding, no checkpoint rewind
# ---------------------------------------------------------------------------

# The kubelet (runtime/kubelet.py) exports the pod's resize-notice path
# here; the scheduler touches the file on DEPARTING workers of a shrink
# and the content is the target worker count (docs/SCHEDULING.md
# "Elastic gangs").
RESIZE_NOTICE_ENV = "K_RESIZE_NOTICE_FILE"


def resize_notice_path() -> Optional[str]:
    """Where this process's elastic-resize notice appears (None when no
    channel is configured)."""
    path = os.environ.get(RESIZE_NOTICE_ENV)
    if path:
        return path
    sandbox = os.environ.get("K_SANDBOX_DIR")
    if sandbox:
        return os.path.join(sandbox, "resize.notice")
    return None


def resize_requested(path: Optional[str] = None) -> Optional[int]:
    """The target worker count from a delivered resize notice, or None
    when no (parsable) notice exists.  A departing worker (index >=
    target) should flush its state and exit 0 inside the drain window;
    survivors re-form the world at the next membership change
    (bootstrap/elastic.watch_hosts)."""
    path = path or resize_notice_path()
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return int(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        return None


def reshard_train_state(state: TrainState, mesh, param_specs=None,
                        shard_update: bool = False) -> TrainState:
    """Live elastic re-shard: move a TrainState onto a NEW mesh (the
    post-resize gang) and continue from the SAME step — no checkpoint
    rewind (docs/SCHEDULING.md "Elastic gangs", arXiv:2004.13336).

    ``jax.device_get`` materializes every leaf in full on the host —
    for the ZeRO-partitioned optimizer state that IS the all-gather of
    the per-replica shards onto the surviving members' coordinator.
    The gathered state is then re-placed exactly like init_fn would on
    the new mesh: params onto their base specs, optimizer-state leaves
    onto the new dp axis's ZeRO shards (``shard_update=True``) or
    replicated.  Pure data movement, no arithmetic: the resumed run is
    numerically identical to an uninterrupted one at the new size (up
    to f32 reassociation inside subsequent steps — allclose-asserted
    in tests/test_elastic.py and bench_elastic.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import shard_params

    host = jax.device_get(state)
    base_specs = param_specs
    if base_specs is None:
        base_specs = jax.tree_util.tree_map(lambda p: P(), host.params)
    params = shard_params(host.params, base_specs, mesh)
    replicated = NamedSharding(mesh, P())
    dp_size = mesh.shape.get("dp", 1)
    shape_spec = {}
    if shard_update and dp_size > 1:
        shape_spec = zero_shape_specs(params, base_specs, dp_size)

    def _place(x):
        spec = shape_spec.get(getattr(x, "shape", None))
        sharding = replicated if spec is None \
            else NamedSharding(mesh, spec)
        return jax.device_put(x, sharding)

    opt_state = jax.tree_util.tree_map(_place, host.opt_state)
    step = jax.device_put(jnp.asarray(host.step, jnp.int32), replicated)
    return TrainState(step=step, params=params, opt_state=opt_state)


class _NoticePoller:
    """Cached preemption-notice poll: at most one ``os.path.exists``
    stat per train step (plus forced re-polls right after an async save
    completes), and none at all once the notice has been seen or when
    no channel is configured."""

    def __init__(self, path: Optional[str]):
        self._path = path
        self._seen = False
        self.stats = 0

    def poll(self) -> bool:
        if self._seen:
            return True
        if not self._path:
            return False
        self.stats += 1
        self._seen = os.path.exists(self._path)
        return self._seen


def run_train_loop(state, step_fn, batches, checkpoint_manager=None,
                   max_steps: Optional[int] = None, start_step: int = 0,
                   preemption_file: Optional[str] = None,
                   exit_on_preemption: bool = True,
                   on_metrics: Optional[Callable] = None,
                   prefetch: int = 2):
    """Drive ``step_fn`` over ``batches`` with checkpointing and
    preemption-aware checkpoint-then-exit.

    Each step: run the step, let the checkpoint manager save on its
    schedule, then poll the preemption notice ONCE (the kubelet's
    K_PREEMPTION_NOTICE_FILE channel — cached helper, so the per-step
    cost is a single stat, placed post-step so a notice that landed
    mid-step is handled before fetching the next batch), re-polling
    immediately after any async checkpoint write completes (a notice
    that landed during a long write must not wait a further step); one
    extra stat before the first step stops a pre-existing notice from
    burning grace-window time on doomed work.  On a notice the loop
    checkpoints IMMEDIATELY
    (off-schedule, inside the grace window), drains any in-flight async
    write, and exits with the retryable code 143 so
    RestartPolicy=ExitCode restarts the gang and the job resumes from
    this exact step — the alternative is dying at SIGTERM with up to
    ``every - 1`` steps of lost work.  ``exit_on_preemption=False``
    returns instead of raising SystemExit (embedders that manage their
    own exit).

    ``prefetch`` (default 2, 0 disables) pulls batches ahead of the
    consumer on a background thread (utils.data.DevicePrefetcher), so
    host batch assembly + device_put overlap the in-flight device step.
    Note the prefetcher consumes up to ``prefetch`` batches beyond the
    last executed step; a data source that tracks its own cursor for
    resume must be re-created from the checkpointed step on restart
    (the repo's batch iterators are step-indexed and are), or pass
    ``prefetch=0`` to keep the one-batch-per-step consumption of the
    serialized loop.
    When ``step_fn`` was built with async dispatch
    (telemetry.goodput.instrument_step), its open goodput window is
    flushed via ``step_fn.sync()`` on every exit path.

    Returns ``(state, step)`` when batches are exhausted, ``max_steps``
    is reached, or a preemption was handled without exiting.
    """
    step = start_step
    poller = _NoticePoller(preemption_file or preemption_notice_path())

    def drain_checkpoints():
        drain = getattr(checkpoint_manager, "drain", None)
        if drain is not None:
            drain()

    def handle_preemption(saved_this_step: bool):
        # A checkpoint failure here must NOT abort the exit protocol:
        # leaving via any exception other than SystemExit(143) turns a
        # retryable preemption into a permanent job failure under
        # RestartPolicy=ExitCode.  Exiting 143 without the final save
        # merely resumes from the last committed step — strictly better.
        ckpt_error = None
        if checkpoint_manager is not None and not saved_this_step:
            try:
                checkpoint_manager.save(state, step)
            except Exception:
                # Most likely a STORED async-writer error re-raised at
                # the save point (already made loud by the writer's own
                # flight bundle); raising cleared it, so one retry
                # genuinely re-attempts the final-state save.
                try:
                    checkpoint_manager.save(state, step)
                except Exception as exc:
                    ckpt_error = exc
        # The grace window must cover the WRITE, not just the snapshot:
        # exiting with the async writer mid-flight would tear the very
        # checkpoint the restart resumes from.
        if ckpt_error is None:
            try:
                drain_checkpoints()
            except Exception as exc:
                ckpt_error = exc
        # Black-box the exit: record the preemption on the flight ring,
        # export it as a sidecar (so the controller's bundle gets a
        # train lane), and dump this process's own bundle — SystemExit
        # never reaches sys.excepthook, so this is the only shot.
        from ..telemetry import flight
        flight.record("train", "preemption", step=step,
                      checkpointed=(checkpoint_manager is not None
                                    and ckpt_error is None),
                      checkpoint_error=(repr(ckpt_error)
                                        if ckpt_error is not None else None),
                      exit_code=PREEMPTION_EXIT_CODE)
        flight.export_sidecar()
        flight.dump_bundle("train-preemption")
        if exit_on_preemption:
            raise SystemExit(PREEMPTION_EXIT_CODE)

    source = batches
    prefetcher = None
    if prefetch and prefetch > 0:
        from ..utils.data import DevicePrefetcher
        source = prefetcher = DevicePrefetcher(batches, depth=prefetch)

    save_completed = None
    if checkpoint_manager is not None:
        save_completed = getattr(checkpoint_manager,
                                 "completed_since_last_poll", None)
    try:
        # Startup check: a notice that already exists must not burn
        # grace-window time dispatching doomed work.
        if poller.poll():
            handle_preemption(saved_this_step=False)
            return state, step
        for batch in source:
            if max_steps is not None and step >= max_steps:
                break
            first = step == start_step
            if first:
                import time as _time
                first_t0 = _time.time()
            state, metrics = step_fn(state, batch)
            step += 1
            if first:
                # Causal-trace terminal milestone: the first productive
                # step of this incarnation, parented to the job context
                # injected into the pod env — closes the create →
                # first-step chain the `trace` verb decomposes.
                from ..telemetry.trace import (default_tracer,
                                               env_context)
                ctx = env_context()
                if ctx is not None:
                    import time as _time
                    default_tracer().emit(
                        "first_step", ts=first_t0,
                        dur=_time.time() - first_t0, ctx=ctx, step=step)
            if on_metrics is not None:
                on_metrics(step, metrics)
            saved = False
            if checkpoint_manager is not None:
                saved = checkpoint_manager.maybe_save(state, step)
                if save_completed is not None and save_completed():
                    # An async write just finished: force a re-poll so
                    # a notice that arrived mid-write is handled now.
                    poller.poll()
            # Post-step check (the one stat per step): a notice that
            # landed during the step is handled before fetching the
            # next batch — a slow data source must not eat the grace
            # window, and a notice during the FINAL step still exits
            # 143 instead of completing silently.
            if poller.poll():
                handle_preemption(saved_this_step=saved)
                return state, step
    finally:
        if prefetcher is not None:
            prefetcher.close()
        # Normal exit must be as durable as the preemption path: flush
        # the open goodput window and wait for the in-flight async
        # write (the last scheduled save would otherwise die with the
        # daemon writer thread), surfacing any stored writer error —
        # unless another exception is already unwinding, which takes
        # precedence (a sync() on a poisoned runtime raising its own
        # XlaRuntimeError must not mask the original failure).
        unwinding = sys.exc_info()[0] is not None
        sync_error = None
        sync = getattr(step_fn, "sync", None)
        if sync is not None:
            try:
                sync()
            except BaseException as exc:
                if not unwinding:
                    sync_error = exc
        try:
            drain_checkpoints()
        except BaseException:
            if not unwinding and sync_error is None:
                raise
        if sync_error is not None:
            raise sync_error
    return state, step
