"""Sharded training step builder.

One jit-compiled SPMD train step over the (dp, fsdp, tp, sp) mesh:
parameters live in their PartitionSpec shardings (fsdp/tp sharded), the
batch is sharded over (dp, fsdp) [+ seq over sp], and XLA derives every
collective (gradient psum over dp, reduce-scatter/all-gather for fsdp,
tp matmul collectives) from the sharding annotations — the Horovod
allreduce of the reference's examples (SURVEY.md §2.3) with the compiler
holding the pen.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .mesh import shard_params


@dataclass
class TrainState:
    """Minimal train state (flax TrainState without the apply coupling)."""
    step: Any
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def build_train_step(loss_fn: Callable, optimizer, mesh,
                     param_specs=None,
                     donate: bool = True,
                     remat: bool = False,
                     accum_steps: int = 1,
                     goodput=None,
                     telemetry_registry=None):
    """Build (init_fn, step_fn).

    - loss_fn(params, batch) -> scalar loss (called under jit/mesh).
    - optimizer: an optax GradientTransformation.
    - param_specs: pytree of PartitionSpec for params (None = replicated).
    - remat: wrap loss in jax.checkpoint to trade FLOPs for HBM.
    - accum_steps: >1 runs the batch as that many gradient-accumulation
      microbatches under one optimizer update (lax.scan, f32 gradient
      accumulator) — activation memory drops ~accum_steps x for the
      same effective batch.  The microbatch split is strided (row r ->
      microbatch r % accum_steps), so each microbatch keeps the full
      batch's (dp, fsdp) sharding instead of collapsing onto a fraction
      of the mesh — which requires the batch dim to divide by
      accum_steps x (dp*fsdp), enforced at trace time.  Gradients equal
      the full-batch step's exactly (for the usual mean-reduction
      losses) up to f32 reassociation.

    - goodput / telemetry_registry: when either is set, the returned
      step_fn is wrapped by telemetry.goodput.instrument_step — each
      call blocks on its outputs and its wall time is attributed to the
      compile bucket (first call) or the productive bucket + the
      train_step_seconds histogram (subsequent calls).

    step_fn(state, batch) -> (state, metrics) with donated state buffers.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    _batch_shards = 1
    for axis in ("dp", "fsdp"):
        _batch_shards *= mesh.shape.get(axis, 1)
    if remat:
        loss_fn = jax.checkpoint(loss_fn)

    def init_fn(params):
        from jax.sharding import NamedSharding, PartitionSpec as P
        if param_specs is not None:
            params = shard_params(params, param_specs, mesh)
        opt_state = optimizer.init(params)
        # Every leaf must carry a mesh sharding (param-shaped moments
        # inherit it from zeros_like; scalars like adam's count do not):
        # checkpoint-restore commits arrays to their saved shardings, and a
        # single-device-committed scalar would then conflict with mesh-wide
        # params under jit.
        replicated = NamedSharding(mesh, P())

        def _pin(x):
            if hasattr(x, "sharding") and isinstance(x.sharding,
                                                     NamedSharding):
                return x
            return jax.device_put(x, replicated)

        opt_state = jax.tree_util.tree_map(_pin, opt_state)
        step = jax.device_put(jnp.zeros((), jnp.int32), replicated)
        return TrainState(step=step, params=params, opt_state=opt_state)

    def _accumulate(params, batch):
        """Mean loss/grads over accum_steps strided microbatches."""
        def split(x):
            b = x.shape[0]
            if b % (accum_steps * _batch_shards):
                # Divisibility by accum_steps alone would trace, but the
                # strided microbatches could no longer keep every
                # (dp, fsdp) shard populated — XLA would insert a batch
                # reshuffle per microbatch, silently defeating the point
                # of the strided split.
                raise ValueError(
                    f"batch dim {b} not divisible by accum_steps"
                    f" {accum_steps} x batch shards {_batch_shards}"
                    f" (dp*fsdp)")
            # [B, ...] -> [A, B/A, ...] with row r in microbatch
            # r % A: dim 0 of the original stays the contiguous-major
            # axis of the reshape, so the microbatch rows remain spread
            # over every (dp, fsdp) shard.
            return jnp.moveaxis(
                x.reshape((b // accum_steps, accum_steps) + x.shape[1:]),
                1, 0)

        micro = jax.tree_util.tree_map(split, batch)
        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)

        def body(carry, mb):
            loss_acc, g_acc = carry
            loss, g = jax.value_and_grad(loss_fn)(params, mb)
            g_acc = jax.tree_util.tree_map(
                lambda a, x: a + x.astype(jnp.float32), g_acc, g)
            return (loss_acc + loss.astype(jnp.float32), g_acc), None

        (loss_sum, g_sum), _ = jax.lax.scan(
            body, (jnp.float32(0.0), g0), micro)
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / accum_steps).astype(p.dtype), g_sum, params)
        return loss_sum / accum_steps, grads

    def _step(state: TrainState, batch):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        else:
            loss, grads = _accumulate(state.params, batch)
        updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                                  state.params)
        new_params = jax.tree_util.tree_map(
            lambda p, u: (p + u).astype(p.dtype), state.params, updates)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt_state)
        metrics = {"loss": loss.astype(jnp.float32),
                   "grad_norm": optax_global_norm(grads)}
        return new_state, metrics

    # Params arrive sharded via init_fn; jit propagates those shardings to
    # the outputs (and the optimizer state inherits them), so no explicit
    # out_shardings are needed — donation keeps buffers in place.
    step_fn = jax.jit(_step, donate_argnums=(0,) if donate else ())
    if goodput is not None or telemetry_registry is not None:
        from ..telemetry.goodput import instrument_step
        step_fn = instrument_step(step_fn, goodput=goodput,
                                  registry=telemetry_registry)
    return init_fn, step_fn


def optax_global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in leaves))


# ---------------------------------------------------------------------------
# Preemption-aware training loop
# ---------------------------------------------------------------------------

# The kubelet (runtime/kubelet.py) exports the pod's notice-file path in
# this env var; a cloud deployment points it at whatever surface the
# provider's preemption notice lands on.  Existence of the file IS the
# notice.
PREEMPTION_NOTICE_ENV = "K_PREEMPTION_NOTICE_FILE"

# Retryable by RestartPolicy=ExitCode (128-255): a preemption exit must
# trigger gang repair + resume-from-checkpoint, never a permanent
# MPIJob failure.  143 = 128 + SIGTERM, the code an un-aware workload
# would die with anyway when the grace window closes.
PREEMPTION_EXIT_CODE = 143


def preemption_notice_path() -> Optional[str]:
    """Where this process's preemption notice appears (None when no
    channel is configured — bare-metal runs outside the runtime)."""
    path = os.environ.get(PREEMPTION_NOTICE_ENV)
    if path:
        return path
    sandbox = os.environ.get("K_SANDBOX_DIR")
    if sandbox:
        return os.path.join(sandbox, "preemption.notice")
    return None


def preemption_requested(path: Optional[str] = None) -> bool:
    path = path or preemption_notice_path()
    return bool(path) and os.path.exists(path)


def run_train_loop(state, step_fn, batches, checkpoint_manager=None,
                   max_steps: Optional[int] = None, start_step: int = 0,
                   preemption_file: Optional[str] = None,
                   exit_on_preemption: bool = True,
                   on_metrics: Optional[Callable] = None):
    """Drive ``step_fn`` over ``batches`` with checkpointing and
    preemption-aware checkpoint-then-exit.

    Each step: run, bump the step counter, let the checkpoint manager
    save on its schedule, then poll the preemption notice (the
    kubelet's K_PREEMPTION_NOTICE_FILE channel).  On a notice the loop
    checkpoints IMMEDIATELY (off-schedule, inside the grace window) and
    exits with the retryable code 143 so RestartPolicy=ExitCode
    restarts the gang and the job resumes from this exact step — the
    alternative is dying at SIGTERM with up to ``every - 1`` steps of
    lost work.  ``exit_on_preemption=False`` returns instead of raising
    SystemExit (embedders that manage their own exit).

    Returns ``(state, step)`` when batches are exhausted, ``max_steps``
    is reached, or a preemption was handled without exiting.
    """
    step = start_step
    notice = preemption_file or preemption_notice_path()

    def handle_preemption(saved_this_step: bool):
        if checkpoint_manager is not None and not saved_this_step:
            checkpoint_manager.save(state, step)
        # Black-box the exit: record the preemption on the flight ring,
        # export it as a sidecar (so the controller's bundle gets a
        # train lane), and dump this process's own bundle — SystemExit
        # never reaches sys.excepthook, so this is the only shot.
        from ..telemetry import flight
        flight.record("train", "preemption", step=step,
                      checkpointed=checkpoint_manager is not None,
                      exit_code=PREEMPTION_EXIT_CODE)
        flight.export_sidecar()
        flight.dump_bundle("train-preemption")
        if exit_on_preemption:
            raise SystemExit(PREEMPTION_EXIT_CODE)

    for batch in batches:
        if max_steps is not None and step >= max_steps:
            break
        # Pre-step check: a notice that landed while blocked fetching
        # the batch must not burn a whole step of the grace window.
        if preemption_requested(notice):
            handle_preemption(saved_this_step=False)
            return state, step
        state, metrics = step_fn(state, batch)
        step += 1
        if on_metrics is not None:
            on_metrics(step, metrics)
        saved = False
        if checkpoint_manager is not None:
            saved = checkpoint_manager.maybe_save(state, step)
        if preemption_requested(notice):
            # A scheduled save this step already captured this state;
            # don't spend the grace window writing it twice.
            handle_preemption(saved_this_step=saved)
            return state, step
    return state, step
