"""Pipeline parallelism over a 'pp' mesh axis.

GPipe-style SPMD pipelining, TPU-first: per-stage parameters are stacked
on a leading axis sharded over 'pp' (each device holds its stage), and
microbatches stream through the ring with ``jax.lax.ppermute`` under a
``lax.scan`` — nearest-neighbor ICI traffic, static shapes, fully
differentiable (reverse-mode flows back through the scan/ppermute).

The schedule is the classic M+P-1 step fill-drain pipeline: stage 0
injects microbatch t at step t, stage P-1 emits microbatch t at step
t+P-1, and a masked psum broadcasts the finished outputs to every
device.  Bubble fraction is (P-1)/(M+P-1) — pick M >> P.

No reference counterpart (the reference scales processes, not models —
SURVEY.md §2.3); this is workload-stack surface for models too large for
tensor parallelism alone.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp


def stack_stage_params(per_stage_params: list):
    """Stack a list of per-stage param pytrees into leading-axis arrays
    ([P, ...]) ready to shard over 'pp'."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def stage_param_specs(stacked_params):
    """PartitionSpec tree: leading axis 'pp', other dims replicated."""
    from jax.sharding import PartitionSpec as P

    def spec(leaf):
        extra = [None] * (leaf.ndim - 1)
        return P("pp", *extra)

    return jax.tree_util.tree_map(spec, stacked_params)


def pipeline_apply(stage_fn: Callable, stacked_params, microbatches,
                   mesh, axis_name: str = "pp",
                   batch_axes=("dp", "fsdp")):
    """Run x through P pipelined stages.

    - stage_fn(params, x) -> y with y.shape == x.shape (homogeneous
      stages, transformer-block style).
    - stacked_params: pytree with leading dim P (stack_stage_params).
    - microbatches: [M, mb, ...] — M microbatches streamed through.

    Returns [M, mb, ...] outputs (replicated over 'pp', batch dims
    sharded over ``batch_axes``).
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            # One stage per pipeline rank — a mismatch would silently run
            # only every (shape[0]/n_stages)-th stage.
            raise ValueError(
                f"stacked stage dim {leaf.shape[0]} != mesh"
                f" {axis_name}={n_stages}")

    def body(stacked_local, xs):
        p = jax.lax.axis_index(axis_name)
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        m = xs.shape[0]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        state0 = jnp.zeros_like(xs[0])
        outputs0 = jnp.zeros_like(xs)

        def step(carry, t):
            state, outputs = carry
            # Stage 0 injects microbatch t (zeros during drain).
            inject = jnp.where(t < m, xs[jnp.minimum(t, m - 1)],
                               jnp.zeros_like(state))
            x_in = jnp.where(p == 0, inject, state)
            y = stage_fn(params, x_in)
            state_next = jax.lax.ppermute(y, axis_name, perm)
            # Last stage finishes microbatch t-(P-1) at step t.
            out_t = t - (n_stages - 1)
            emit = (p == n_stages - 1) & (out_t >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(emit, y, jax.lax.dynamic_index_in_dim(
                    outputs, jnp.maximum(out_t, 0), 0, keepdims=False)),
                jnp.maximum(out_t, 0), 0)
            return (state_next, updated), None

        (_, outputs), _ = jax.lax.scan(
            step, (state0, outputs0), jnp.arange(m + n_stages - 1))
        # Broadcast the last stage's outputs to every pipeline rank.
        return jax.lax.psum(
            jnp.where(p == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)

    extra = [None] * (microbatches.ndim - 2)
    x_spec = P(None, batch_axes, *extra)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(stage_param_specs(stacked_params), x_spec),
        out_specs=x_spec, check_vma=False)
    return fn(stacked_params, microbatches)


def split_microbatches(x, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...]."""
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def merge_microbatches(x):
    """[M, mb, ...] -> [B, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
