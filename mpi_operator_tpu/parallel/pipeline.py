"""Pipeline parallelism over a 'pp' mesh axis.

GPipe-style SPMD pipelining, TPU-first: per-stage parameters are stacked
on a leading axis sharded over 'pp' (each device holds its stage), and
microbatches stream through the ring with ``jax.lax.ppermute`` under a
``lax.scan`` — nearest-neighbor ICI traffic, static shapes, fully
differentiable (reverse-mode flows back through the scan/ppermute).

The schedule is the classic M+P-1 step fill-drain pipeline: stage 0
injects microbatch t at step t, stage P-1 emits microbatch t at step
t+P-1, and a masked psum broadcasts the finished outputs to every
device.  Bubble fraction is (P-1)/(M+P-1) — pick M >> P.

No reference counterpart (the reference scales processes, not models —
SURVEY.md §2.3); this is workload-stack surface for models too large for
tensor parallelism alone.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from ..compat import shard_map


def stack_stage_params(per_stage_params: list):
    """Stack a list of per-stage param pytrees into leading-axis arrays
    ([P, ...]) ready to shard over 'pp'."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def stage_param_specs(stacked_params, fsdp_dims=None):
    """PartitionSpec tree: leading axis 'pp'; with ``fsdp_dims`` (see
    stage_param_fsdp_dims), each leaf whose entry is >= 1 additionally
    shards that dim over 'fsdp' (PP x FSDP composition)."""
    from jax.sharding import PartitionSpec as P

    def spec(leaf, d=-1):
        parts = ["pp"] + [None] * (leaf.ndim - 1)
        if d >= 1:
            parts[d] = "fsdp"
        return P(*parts)

    if fsdp_dims is None:
        return jax.tree_util.tree_map(spec, stacked_params)
    return jax.tree_util.tree_map(spec, stacked_params, fsdp_dims)


def stage_param_fsdp_dims(stacked_params, mesh):
    """Per-leaf dim index (into the stacked [P, ...] layout) to shard
    over 'fsdp', or -1.  Picks the first non-stage dim divisible by the
    axis — for llama stacks [P, layers/stage, d_in, d_out] with one
    layer per stage that is d_in, for generic [P, d0, ...] stacks d0.
    Undivisible leaves (scalars/vectors/ragged) stay replicated — ZeRO
    keeps them cheap anyway."""
    n = mesh.shape.get("fsdp", 1)

    def dim(leaf):
        # Shard genuine matrices only: a per-stage leaf with < 2
        # non-trivial dims (biases [P, h], per-layer norm scales
        # [S, 1, d], ...) is a few KB per stage, and a dedicated
        # latency-bound all_gather + psum_scatter per such leaf is a
        # net loss.
        if n <= 1:
            return -1
        non_trivial = [i for i in range(1, leaf.ndim) if leaf.shape[i] > 1]
        if len(non_trivial) < 2:
            return -1
        for d in range(1, leaf.ndim):
            if leaf.shape[d] >= n and leaf.shape[d] % n == 0:
                return d
        return -1

    return jax.tree_util.tree_map(dim, stacked_params)


def _gather_fsdp_params(params, fsdp_dims):
    """Inside shard_map, AFTER the stage dim was indexed away:
    reassemble full per-stage params from their fsdp shards.  Called
    once per body invocation, so the full stage copy lives for the
    whole pipelined pass — what PP x FSDP buys is sharded PERSISTENT
    state (params at rest + optimizer moments), not lower compute-time
    residency."""
    return jax.tree_util.tree_map(
        lambda leaf, d: jax.lax.all_gather(leaf, "fsdp", axis=d - 1,
                                           tiled=True) if d >= 1 else leaf,
        params, fsdp_dims)


def pipeline_apply(stage_fn: Callable, stacked_params, microbatches,
                   mesh, axis_name: str = "pp",
                   batch_axes=("dp", "fsdp"), fsdp_shard: bool = False):
    """Run x through P pipelined stages.

    - stage_fn(params, x) -> y with y.shape == x.shape (homogeneous
      stages, transformer-block style).
    - stacked_params: pytree with leading dim P (stack_stage_params).
    - microbatches: [M, mb, ...] — M microbatches streamed through.
    - fsdp_shard: PP x FSDP — eligible stage weights live sharded over
      'fsdp' (persistent storage + optimizer state: ZeRO) and are
      all-gathered ONCE per body invocation, so the full per-stage
      copy is resident for the whole pipelined forward/backward; grads
      reduce-scatter back through the shard_map transpose
      automatically.

    Returns [M, mb, ...] outputs (replicated over 'pp', batch dims
    sharded over ``batch_axes``).
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            # One stage per pipeline rank — a mismatch would silently run
            # only every (shape[0]/n_stages)-th stage.
            raise ValueError(
                f"stacked stage dim {leaf.shape[0]} != mesh"
                f" {axis_name}={n_stages}")
    fsdp_dims = (stage_param_fsdp_dims(stacked_params, mesh)
                  if fsdp_shard else None)

    def body(stacked_local, xs):
        p = jax.lax.axis_index(axis_name)
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        if fsdp_dims is not None:
            params = _gather_fsdp_params(params, fsdp_dims)
        m = xs.shape[0]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        state0 = jnp.zeros_like(xs[0])
        outputs0 = jnp.zeros_like(xs)

        def step(carry, t):
            state, outputs = carry
            # Stage 0 injects microbatch t (zeros during drain).
            inject = jnp.where(t < m, xs[jnp.minimum(t, m - 1)],
                               jnp.zeros_like(state))
            x_in = jnp.where(p == 0, inject, state)
            y = stage_fn(params, x_in)
            state_next = jax.lax.ppermute(y, axis_name, perm)
            # Last stage finishes microbatch t-(P-1) at step t.
            out_t = t - (n_stages - 1)
            emit = (p == n_stages - 1) & (out_t >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(emit, y, jax.lax.dynamic_index_in_dim(
                    outputs, jnp.maximum(out_t, 0), 0, keepdims=False)),
                jnp.maximum(out_t, 0), 0)
            return (state_next, updated), None

        (_, outputs), _ = jax.lax.scan(
            step, (state0, outputs0), jnp.arange(m + n_stages - 1))
        # Broadcast the last stage's outputs to every pipeline rank.
        return jax.lax.psum(
            jnp.where(p == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)

    extra = [None] * (microbatches.ndim - 2)
    x_spec = P(None, batch_axes, *extra)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(stage_param_specs(stacked_params, fsdp_dims), x_spec),
        out_specs=x_spec, check_vma=False)
    return fn(stacked_params, microbatches)


def split_microbatches(x, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...]."""
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def merge_microbatches(x):
    """[M, mb, ...] -> [B, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


# ---------------------------------------------------------------------------
# 1F1B (fused forward+backward) schedule
# ---------------------------------------------------------------------------

def _simulate_1f1b(n_stages: int, n_micro: int):
    """Event-driven static schedule: per (stage, tick) which microbatch to
    Forward and which to Backward (-1 = idle slot).  Each tick has one F
    slot and one B slot per stage (the standard SPMD 1F1B step); at most
    P - p microbatches are in flight at stage p, which is the 1F1B
    activation-memory bound this schedule exists for."""
    import numpy as np

    P, M = n_stages, n_micro
    t_max = 2 * (M + P) + 4
    fwd = -np.ones((P, t_max), np.int32)
    bwd = -np.ones((P, t_max), np.int32)
    fwd_done = np.full((P, M), t_max + 1)
    bwd_done = np.full((P, M), t_max + 1)
    nf = [0] * P
    nb = [0] * P

    end = 0
    for t in range(t_max):
        if all(nb[p] == M for p in range(P)):
            end = t
            break
        for p in range(P):
            # F slot: activation from the left arrived on an EARLIER tick
            # (stage 0 always has its input), bounded in-flight window.
            if nf[p] < M:
                m = nf[p]
                avail = (p == 0) or (fwd_done[p - 1][m] < t)
                if avail and (nf[p] - nb[p]) < (P - p):
                    fwd[p][t] = m
                    fwd_done[p][m] = t
                    nf[p] += 1
            # B slot: dy from the right arrived earlier; the last stage
            # builds dy from its own F of the same tick (F runs first in
            # the step body).
            if nb[p] < M:
                m = nb[p]
                ready = (fwd_done[P - 1][m] <= t) if p == P - 1 \
                    else (bwd_done[p + 1][m] < t)
                if ready:
                    bwd[p][t] = m
                    bwd_done[p][m] = t
                    nb[p] += 1
    else:
        raise RuntimeError("1F1B schedule did not converge")
    return fwd[:, :end], bwd[:, :end], end


def _phase_bounds(fwd_np, bwd_np, n_ticks: int, head_slots=None):
    """(first tick with any B scheduled, one past the last tick with any
    F scheduled) — the static warmup/steady/drain split.

    Under shard_map every rank executes the same traced tick body, so a
    tick costs F + head + B wall-clock even on ranks whose slot is idle
    (-1): the "pipeline bubble" in lockstep SPMD is masked compute, not
    idle time.  No B is scheduled anywhere before the first B tick and
    no F after the last F tick, so those segments can run cheaper bodies
    (F-only / B-only) with the same carry — cutting ~(P-1) ticks' worth
    of dead backward compute in warmup and dead forward+head compute in
    drain.  This is the part of zero-bubble (ZB-H1) scheduling that
    actually pays under lockstep SPMD; the dX/dW backward split itself
    does not, because every rank's tick body would still contain one F,
    one dX and one dW computation regardless of which microbatch (if
    any) fills each slot, leaving total ticks bounded by the same
    one-F-slot-per-tick constraint.
    """
    import numpy as np

    b_ticks = np.nonzero((bwd_np >= 0).any(axis=0))[0]
    f_ticks = np.nonzero((fwd_np >= 0).any(axis=0))[0]
    t_warm = int(b_ticks[0]) if b_ticks.size else n_ticks
    t_fend = int(f_ticks[-1]) + 1 if f_ticks.size else 0
    if head_slots is not None:
        # Gradient-correctness invariant of the split: the head (loss +
        # dy queueing) only exists in the combined body, so every
        # head-bearing F slot must land in [t_warm, t_fend).  Holds by
        # construction today (the simulators schedule the last global
        # stage's B on the same tick as its F); a simulator change that
        # delayed the first B past that F would otherwise silently zero
        # the loss and every gradient.
        h_ticks = np.nonzero(head_slots)[0]
        if h_ticks.size and (h_ticks[0] < t_warm or h_ticks[-1] >= t_fend):
            raise RuntimeError(
                f"head-bearing F slots at ticks [{h_ticks[0]}, "
                f"{h_ticks[-1]}] escape the combined segment "
                f"[{t_warm}, {t_fend})")
    return t_warm, t_fend


def pipeline_1f1b(stage_fn: Callable, head_fn: Callable, stacked_params,
                  head_params, microbatches, mesh, axis_name: str = "pp",
                  batch_axes=("dp", "fsdp"), aux=None,
                  fsdp_shard: bool = False):
    """Fused forward+backward pipeline with the 1F1B schedule.

    GPipe (`pipeline_apply` + autodiff) keeps one activation per
    microbatch alive across the whole forward — O(M) memory.  1F1B
    interleaves each stage's backwards between forwards so at most
    P - p microbatch inputs are resident per stage (ring buffers of
    size P), recomputing the stage forward inside the backward (remat).

    - stage_fn(params, x) -> y, homogeneous stages (y.shape == x.shape).
    - head_fn(head_params, y, m) -> scalar loss for microbatch m
      (applied at the LAST stage; total loss is the mean over M).  With
      ``aux`` ([M, mb, ...], sharded like microbatches — e.g. target
      token ids) the signature becomes head_fn(head_params, y, aux_m, m)
      and aux is treated as non-differentiable.
    - stacked_params: pytree with leading dim P; head_params: any pytree.
    - microbatches: [M, mb, ...].

    Returns (loss, stage_grads, head_grads, dx) where stage_grads has
    the same stacked [P, ...] layout, and dx [M, mb, ...] is the loss
    gradient w.r.t. microbatches (feed it to the embedding backward).
    Gradients are exact (tested against jax.grad of the sequential
    model).
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis_name]
    m_count = microbatches.shape[0]
    if m_count < n_stages:
        raise ValueError(
            f"1F1B needs microbatches >= stages ({m_count} < {n_stages})")
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked stage dim {leaf.shape[0]} != mesh"
                f" {axis_name}={n_stages}")

    fwd_np, bwd_np, n_ticks = _simulate_1f1b(n_stages, m_count)
    fwd_table = jnp.asarray(fwd_np)
    bwd_table = jnp.asarray(bwd_np)
    t_warm, t_fend = _phase_bounds(fwd_np, bwd_np, n_ticks,
                                   head_slots=fwd_np[-1] >= 0)
    fsdp_dims = (stage_param_fsdp_dims(stacked_params, mesh)
                  if fsdp_shard else None)

    def body(stacked_local, head_local, xs, xs_aux):
        p = jax.lax.axis_index(axis_name)
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        if fsdp_dims is not None:
            # PP x FSDP: reassemble the full stage weights from their
            # fsdp shards (transient); grad accumulation below runs
            # full-size and is reduce-scattered back in the collect.
            params = _gather_fsdp_params(params, fsdp_dims)
        mb_shape = xs.shape[1:]
        last = n_stages - 1
        right_perm = [(i, i + 1) for i in range(n_stages - 1)]
        left_perm = [(i + 1, i) for i in range(n_stages - 1)]

        def take_row(table, row):
            safe = jnp.clip(row, 0, n_stages - 1)
            return jnp.where((row >= 0) & (row < n_stages),
                             table[safe], -1)

        zeros_mb = jnp.zeros(mb_shape, xs.dtype)
        carry0 = {
            "fwd_buf": jnp.zeros((n_stages,) + mb_shape, xs.dtype),
            "bwd_buf": jnp.zeros((n_stages,) + mb_shape, jnp.float32),
            "x_buf": jnp.zeros((n_stages,) + mb_shape, xs.dtype),
            "grads": jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), params),
            "head_grads": jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), head_local),
            "dx": jnp.zeros((m_count,) + mb_shape, jnp.float32),
            "loss": jnp.float32(0.0),
        }

        def make_step(with_f: bool, with_b: bool):
            # with_f/with_b are trace-time flags: the warmup segment (no
            # B scheduled on any rank) omits the head + backward compute
            # from its scan body, the drain segment (no F left) omits the
            # forward + head — see _phase_bounds.
            def step(carry, t):
                x_buf = carry["x_buf"]
                bwd_buf = carry["bwd_buf"]
                fwd_buf = carry["fwd_buf"]
                grads = carry["grads"]
                head_grads = carry["head_grads"]
                dx = carry["dx"]
                loss = carry["loss"]

                if with_f:
                    # ---- F slot ---------------------------------------
                    my_f = take_row(fwd_table, p)[t]
                    f_m = jnp.maximum(my_f, 0)
                    x_in = jnp.where(
                        p == 0, xs[f_m],
                        fwd_buf[f_m % n_stages])
                    y = stage_fn(params, x_in)
                    do_f = my_f >= 0
                    x_buf = jnp.where(
                        do_f,
                        x_buf.at[f_m % n_stages].set(x_in),
                        x_buf)

                if with_f and with_b:
                    # Last stage: head loss + dy for this microbatch,
                    # queued for the B slot (possibly this same tick).
                    # Last-stage F slots only exist once B slots do, so
                    # the F-only warmup body never needs the head.
                    def head_loss(hp, yy):
                        if xs_aux is None:
                            return head_fn(hp, yy, f_m)
                        return head_fn(hp, yy, xs_aux[f_m], f_m)
                    (loss_m, (dhead_m, dy_m)) = _head_value_and_grads(
                        head_loss, head_local, y)
                    f_here = do_f & (p == last)
                    loss = loss + jnp.where(f_here, loss_m / m_count, 0.0)
                    head_grads = jax.tree_util.tree_map(
                        lambda acc, g: acc + jnp.where(f_here,
                                                       g / m_count, 0.0),
                        head_grads, dhead_m)
                    bwd_buf = jnp.where(
                        f_here,
                        bwd_buf.at[f_m % n_stages].set(
                            dy_m.astype(jnp.float32) / m_count),
                        bwd_buf)

                if with_b:
                    # ---- B slot (remat: recompute the stage forward) --
                    my_b = take_row(bwd_table, p)[t]
                    b_m = jnp.maximum(my_b, 0)
                    x_saved = x_buf[b_m % n_stages]
                    dy = bwd_buf[b_m % n_stages].astype(xs.dtype)
                    _, vjp_fn = jax.vjp(lambda pr, xx: stage_fn(pr, xx),
                                        params, x_saved)
                    dparams, dx_m = vjp_fn(dy)
                    do_b = my_b >= 0
                    grads = jax.tree_util.tree_map(
                        lambda acc, g: acc + jnp.where(
                            do_b, g.astype(jnp.float32), 0.0),
                        grads, dparams)
                    dx = jnp.where(
                        do_b & (p == 0),
                        dx.at[b_m].set(dx_m.astype(jnp.float32)),
                        dx)

                # ---- communication --------------------------------------
                if with_f:
                    # forward activation to the right
                    f_msg = jnp.where(do_f & (p < last), y, zeros_mb)
                    f_in = jax.lax.ppermute(f_msg, axis_name, right_perm)
                    left_f = take_row(fwd_table, p - 1)[t]
                    fwd_buf = jnp.where(
                        (p > 0) & (left_f >= 0),
                        fwd_buf.at[jnp.maximum(left_f, 0)
                                   % n_stages].set(f_in),
                        fwd_buf)
                if with_b:
                    # backward gradient to the left
                    b_msg = jnp.where(do_b & (p > 0),
                                      dx_m.astype(jnp.float32),
                                      jnp.zeros(mb_shape, jnp.float32))
                    b_in = jax.lax.ppermute(b_msg, axis_name, left_perm)
                    right_b = take_row(bwd_table, p + 1)[t]
                    bwd_buf = jnp.where(
                        (p < last) & (right_b >= 0),
                        bwd_buf.at[jnp.maximum(right_b, 0)
                                   % n_stages].set(b_in),
                        bwd_buf)

                return {"fwd_buf": fwd_buf, "bwd_buf": bwd_buf,
                        "x_buf": x_buf, "grads": grads,
                        "head_grads": head_grads, "dx": dx,
                        "loss": loss}, None
            return step

        carry = carry0
        for lo, hi, stp in ((0, t_warm, make_step(True, False)),
                            (t_warm, t_fend, make_step(True, True)),
                            (t_fend, n_ticks, make_step(False, True))):
            if hi > lo:
                carry, _ = jax.lax.scan(stp, carry, jnp.arange(lo, hi))

        return _collect_1f1b(carry, mesh, axis_name, batch_axes, p, last,
                             lambda g: g[None], fsdp_dims=fsdp_dims)

    extra = [None] * (microbatches.ndim - 2)
    x_spec = P(None, batch_axes, *extra)
    rep = P()
    aux_spec = None
    if aux is not None:
        aux_spec = P(None, batch_axes, *([None] * (aux.ndim - 2)))
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(stage_param_specs(stacked_params, fsdp_dims),
                  jax.tree_util.tree_map(lambda _: rep, head_params),
                  x_spec, aux_spec),
        out_specs=(rep,
                   stage_param_specs(stacked_params, fsdp_dims),
                   jax.tree_util.tree_map(lambda _: rep, head_params),
                   P(None, batch_axes, *extra)),
        check_vma=False)
    return fn(stacked_params, head_params, microbatches, aux)


def _head_value_and_grads(head_loss, head_params, y):
    """(loss, (d head_params, d y)) for the last-stage loss head."""
    loss, vjp_fn = jax.vjp(head_loss, head_params, y)
    dhead, dy = vjp_fn(jnp.float32(1.0))
    return loss, (dhead, dy)


def _collect_1f1b(carry, mesh, axis_name, batch_axes, p, last, expand,
                  fsdp_dims=None):
    """Shared 1F1B collect epilogue (plain and interleaved schedules):
    loss/head grads live on the last stage, dx on stage 0, stage grads
    stay per-rank (``expand`` restores the 'pp'-sharded leading axis —
    [None] for [P,...] stacks, [:, None] for [V, P, ...]).  Each
    batch-axis member saw only its local shard, so loss and param grads
    get the data-parallel mean autodiff would have inserted; dx is
    d(LOCAL shard mean)/dx_local and the global loss is the mean over
    shards, so each shard's input gradient carries 1/n_dp.

    With ``fsdp_dims`` (PP x FSDP), flagged stage grads were
    accumulated FULL-size per rank from that rank's batch shard; they
    leave as the fsdp-reduce-scattered mean shard (dp-mean over the
    remaining axes), matching the sharded parameter layout."""
    on = lambda cond, x: jnp.where(cond, x, jnp.zeros_like(x))  # noqa
    dp_axes = tuple(a for a in batch_axes if a in mesh.shape)
    dp_mean = (lambda v: jax.lax.pmean(v, dp_axes)) if dp_axes \
        else (lambda v: v)
    loss = dp_mean(jax.lax.psum(on(p == last, carry["loss"]), axis_name))
    head_grads = jax.tree_util.tree_map(
        lambda g: dp_mean(jax.lax.psum(on(p == last, g), axis_name)),
        carry["head_grads"])
    n_dp = 1
    for a in dp_axes:
        n_dp *= mesh.shape[a]
    dx = jax.lax.psum(on(p == 0, carry["dx"]), axis_name) / n_dp
    if fsdp_dims is None:
        stage_grads = jax.tree_util.tree_map(
            lambda g: expand(dp_mean(g)), carry["grads"])
    else:
        n_fsdp = mesh.shape.get("fsdp", 1)
        other = tuple(a for a in dp_axes if a != "fsdp")
        other_mean = (lambda v: jax.lax.pmean(v, other)) if other \
            else (lambda v: v)

        def collect(g, d):
            if d >= 1:
                # Scatter FIRST: the fsdp reduce-scatter shrinks the
                # tensor n_fsdp-fold before the dp pmean moves it (the
                # collectives act on disjoint axes and are linear, so
                # the order only changes bytes on the wire); / n_fsdp
                # turns the fsdp sum into the batch mean.  d indexes the
                # STACKED layout; the stage dim is gone here.
                g = jax.lax.psum_scatter(g, "fsdp",
                                         scatter_dimension=d - 1,
                                         tiled=True) / n_fsdp
                return expand(other_mean(g))
            return expand(dp_mean(g))

        stage_grads = jax.tree_util.tree_map(collect, carry["grads"],
                                             fsdp_dims)
    return loss, stage_grads, head_grads, dx


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) 1F1B schedule
# ---------------------------------------------------------------------------

def _simulate_interleaved(n_stages: int, n_virtual: int, n_micro: int):
    """Static schedule for Megatron-style interleaved 1F1B: each
    pipeline rank owns ``n_virtual`` chunks (rank p holds global stages
    v*P + p), microbatches cycle through chunks in groups of P, and the
    warmup depth grows by (V-1)*P forwards — the bubble shrinks ~1/V at
    the cost of V x the chunk-boundary communication (all of it
    nearest-neighbor ppermute traffic on the ring, incl. the P-1 -> 0
    wrap between chunks).

    Returns (fwd_table, bwd_table, n_ticks, ring sizes): tables are
    [P, T] int32 with entries v*M + m (or -1 idle); ring sizes are the
    maximum simulated occupancies of the forward-input, backward-input
    and saved-activation buffers, so the SPMD body can size its ring
    buffers exactly.
    """
    import numpy as np

    P, V, M = n_stages, n_virtual, n_micro
    if M % P != 0:
        raise ValueError(
            f"interleaved 1F1B needs microbatches divisible by stages "
            f"({M} % {P})")
    S = P * V

    def f_op(p, k):
        g, j = divmod(k, P * V)
        return (j // P, g * P + j % P)        # (chunk, microbatch)

    def b_op(p, k):
        g, j = divmod(k, P * V)
        return (V - 1 - j // P, g * P + j % P)

    t_max = 4 * (M * V + P) + 8
    fwd = -np.ones((P, t_max), np.int64)
    bwd = -np.ones((P, t_max), np.int64)
    fwd_done = np.full((S, M), t_max + 1)
    bwd_done = np.full((S, M), t_max + 1)
    nf = [0] * P
    nb = [0] * P
    caps = [min(M * V, (V - 1) * P + 2 * (P - p - 1) + 1)
            for p in range(P)]

    end = 0
    for t in range(t_max):
        if all(nb[p] == M * V for p in range(P)):
            end = t
            break
        for p in range(P):
            if nf[p] < M * V and (nf[p] - nb[p]) < caps[p]:
                v, m = f_op(p, nf[p])
                s = v * P + p
                if s == 0 or fwd_done[s - 1][m] < t:
                    fwd[p][t] = v * M + m
                    fwd_done[s][m] = t
                    nf[p] += 1
            if nb[p] < M * V:
                v, m = b_op(p, nb[p])
                s = v * P + p
                ready = (fwd_done[s][m] <= t) if s == S - 1 \
                    else (bwd_done[s + 1][m] < t)
                if ready:
                    bwd[p][t] = v * M + m
                    bwd_done[s][m] = t
                    nb[p] += 1
    else:
        raise RuntimeError("interleaved 1F1B schedule did not converge")

    # Exact ring-buffer sizes from the simulated arrival/consume ticks.
    def max_occupancy(arrivals, consumes):
        """arrivals/consumes: lists of (tick, key); occupancy counts
        arrived-not-yet-consumed at each tick."""
        events = [(t, 1) for t, _ in arrivals] + \
                 [(t + 1, -1) for t, _ in consumes]
        occ = best = 0
        for _, d in sorted(events):
            occ += d
            best = max(best, occ)
        return max(best, 1)

    kf = kb = kx = 1
    for p in range(P):
        for v in range(V):
            s = v * P + p
            f_arr = [(fwd_done[s - 1][m], m) for m in range(M) if s > 0]
            f_con = [(fwd_done[s][m], m) for m in range(M) if s > 0]
            kf = max(kf, max_occupancy(f_arr, f_con))
            b_arr = [(bwd_done[s + 1][m] if s < S - 1
                      else fwd_done[s][m], m) for m in range(M)]
            b_con = [(bwd_done[s][m], m) for m in range(M)]
            kb = max(kb, max_occupancy(b_arr, b_con))
            x_arr = [(fwd_done[s][m], m) for m in range(M)]
            x_con = [(bwd_done[s][m], m) for m in range(M)]
            kx = max(kx, max_occupancy(x_arr, x_con))
    return (fwd[:, :end].astype(np.int32), bwd[:, :end].astype(np.int32),
            end, kf, kb, kx)


def pipeline_interleaved_1f1b(stage_fn: Callable, head_fn: Callable,
                              stacked_params, head_params, microbatches,
                              mesh, virtual_stages: int,
                              axis_name: str = "pp",
                              batch_axes=("dp", "fsdp"), aux=None,
                              fsdp_shard: bool = False):
    """Interleaved (virtual-stage) 1F1B: rank p holds ``virtual_stages``
    chunks (global stage v*P + p), shrinking the pipeline bubble ~1/V
    vs `pipeline_1f1b` at the cost of V x the chunk-boundary ppermute
    traffic (still all nearest-neighbor, incl. the P-1 -> 0 ring wrap).

    - stacked_params: pytree with leading dim S = P * virtual_stages
      (global stage s = v*P + p at index s, i.e. `stack_stage_params`
      order); grads come back in the same layout.
    - stage_fn(params, x) -> y operates on ONE chunk's params.
    - head_fn / aux / return signature match `pipeline_1f1b`.

    Microbatch count must divide by P (the canonical interleaved
    grouping).  Gradients are exact (tested against jax.grad of the
    sequential model).
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis_name]
    n_virtual = virtual_stages
    total = n_stages * n_virtual
    m_count = microbatches.shape[0]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != total:
            raise ValueError(
                f"stacked stage dim {leaf.shape[0]} != "
                f"pp*virtual = {total}")
    if n_virtual == 1:
        return pipeline_1f1b(stage_fn, head_fn, stacked_params,
                             head_params, microbatches, mesh,
                             axis_name=axis_name, batch_axes=batch_axes,
                             aux=aux, fsdp_shard=fsdp_shard)

    fwd_np, bwd_np, n_ticks, kf, kb, kx = _simulate_interleaved(
        n_stages, n_virtual, m_count)
    fwd_table = jnp.asarray(fwd_np)
    bwd_table = jnp.asarray(bwd_np)
    # Head-bearing F slots: chunk V-1 on the last rank (entry v*M + m).
    t_warm, t_fend = _phase_bounds(
        fwd_np, bwd_np, n_ticks,
        head_slots=fwd_np[-1] >= (n_virtual - 1) * m_count)

    # [S, ...] -> [V, P, ...]: s = v*P + p, so a plain reshape lands
    # chunk v of rank p at [v, p].
    def to_vp(leaf):
        return leaf.reshape((n_virtual, n_stages) + leaf.shape[1:])

    def from_vp(leaf):
        return leaf.reshape((total,) + leaf.shape[2:])

    stacked_vp = jax.tree_util.tree_map(to_vp, stacked_params)
    # fsdp dims computed on the [S, d0, ...] layout: entry d refers to
    # physical dim d+1 in the [V, P, d0, ...] layout, dim d in the
    # per-rank [V, d0, ...] chunks, and dim d-1 in one chunk's params.
    fsdp_dims = (stage_param_fsdp_dims(stacked_params, mesh)
                 if fsdp_shard else None)
    n_fsdp = mesh.shape.get("fsdp", 1)

    def vp_specs(tree):
        def spec(leaf, d=-1):
            parts = [None, axis_name] + [None] * (leaf.ndim - 2)
            if d >= 1:
                parts[d + 1] = "fsdp"
            return P(*parts)
        if fsdp_dims is None:
            return jax.tree_util.tree_map(spec, tree)
        return jax.tree_util.tree_map(spec, tree, fsdp_dims)

    def body(stacked_local, head_local, xs, xs_aux):
        p = jax.lax.axis_index(axis_name)
        # [V, 1, ...] -> [V, ...]
        chunks = jax.tree_util.tree_map(lambda a: a[:, 0], stacked_local)
        mb_shape = xs.shape[1:]
        last = n_stages - 1
        ring_r = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        ring_l = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        def chunk_params(v):
            one = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, v, 0, keepdims=False), chunks)
            if fsdp_dims is not None:
                one = _gather_fsdp_params(one, fsdp_dims)
            return one

        zeros_mb = jnp.zeros(mb_shape, xs.dtype)
        carry0 = {
            "fwd_buf": jnp.zeros((n_virtual, kf) + mb_shape, xs.dtype),
            "bwd_buf": jnp.zeros((n_virtual, kb) + mb_shape, jnp.float32),
            "x_buf": jnp.zeros((n_virtual, kx) + mb_shape, xs.dtype),
            # Grad accumulation runs FULL-size per chunk (vjp of the
            # gathered params); the collect reduce-scatters it back.
            "grads": jax.tree_util.tree_map(
                lambda a, d=None: jnp.zeros(
                    tuple(x * n_fsdp if d is not None and d >= 1
                          and i == d else x
                          for i, x in enumerate(a.shape)), jnp.float32),
                chunks, *((fsdp_dims,) if fsdp_dims is not None else ())),
            "head_grads": jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), head_local),
            "dx": jnp.zeros((m_count,) + mb_shape, jnp.float32),
            "loss": jnp.float32(0.0),
        }

        def decode(e):
            return e // m_count, e % m_count   # (chunk, microbatch)

        def make_step(with_f: bool, with_b: bool):
            # Same warmup/steady/drain specialization as pipeline_1f1b
            # (_phase_bounds): the last global stage's first F coincides
            # with the first B tick, so the F-only warmup body never
            # needs the head, and the B-only drain body never runs F.
            def step(carry, t):
                x_buf = carry["x_buf"]
                bwd_buf = carry["bwd_buf"]
                fwd_buf = carry["fwd_buf"]
                grads = carry["grads"]
                head_grads = carry["head_grads"]
                dx = carry["dx"]
                loss = carry["loss"]

                if with_f:
                    # ---- F slot ---------------------------------------
                    my_f = fwd_table[p][t]
                    do_f = my_f >= 0
                    v_f, m_f = decode(jnp.maximum(my_f, 0))
                    x_in = jnp.where((v_f == 0) & (p == 0), xs[m_f],
                                     fwd_buf[v_f, m_f % kf])
                    params_f = chunk_params(v_f)
                    y = stage_fn(params_f, x_in)
                    x_buf = jnp.where(
                        do_f, x_buf.at[v_f, m_f % kx].set(x_in),
                        x_buf)

                if with_f and with_b:
                    # Last global stage (chunk V-1 on rank P-1): head
                    # loss + dy, queued for the B slot (possibly this
                    # same tick).
                    def head_loss(hp, yy):
                        if xs_aux is None:
                            return head_fn(hp, yy, m_f)
                        return head_fn(hp, yy, xs_aux[m_f], m_f)
                    loss_m, (dhead_m, dy_m) = _head_value_and_grads(
                        head_loss, head_local, y)
                    f_here = do_f & (p == last) & (v_f == n_virtual - 1)
                    loss = loss + jnp.where(f_here, loss_m / m_count, 0.0)
                    head_grads = jax.tree_util.tree_map(
                        lambda acc, g: acc + jnp.where(f_here,
                                                       g / m_count, 0.0),
                        head_grads, dhead_m)
                    bwd_buf = jnp.where(
                        f_here,
                        bwd_buf.at[v_f, m_f % kb].set(
                            dy_m.astype(jnp.float32) / m_count),
                        bwd_buf)

                if with_b:
                    # ---- B slot (remat: recompute the chunk forward) --
                    my_b = bwd_table[p][t]
                    do_b = my_b >= 0
                    v_b, m_b = decode(jnp.maximum(my_b, 0))
                    x_saved = x_buf[v_b, m_b % kx]
                    dy = bwd_buf[v_b, m_b % kb].astype(xs.dtype)
                    params_b = chunk_params(v_b)
                    _, vjp_fn = jax.vjp(lambda pr, xx: stage_fn(pr, xx),
                                        params_b, x_saved)
                    dparams, dx_m = vjp_fn(dy)
                    grads = jax.tree_util.tree_map(
                        lambda acc, g: acc.at[v_b].add(
                            jnp.where(do_b, g.astype(jnp.float32), 0.0)),
                        grads, dparams)
                    dx = jnp.where(
                        do_b & (p == 0) & (v_b == 0),
                        dx.at[m_b].set(dx_m.astype(jnp.float32)),
                        dx)

                # ---- communication --------------------------------------
                if with_f:
                    # Forward activation to the right neighbor (ring wrap
                    # P-1->0 crosses a chunk boundary: the receiver files
                    # it under chunk v+1).  The last global stage sends
                    # nothing.
                    send_f = do_f & ~((p == last) & (v_f == n_virtual - 1))
                    f_in = jax.lax.ppermute(
                        jnp.where(send_f, y, zeros_mb), axis_name, ring_r)
                    left = (p - 1) % n_stages
                    e_l = fwd_table[left][t]
                    v_l, m_l = decode(jnp.maximum(e_l, 0))
                    recv_f = (e_l >= 0) & ~((left == last) &
                                            (v_l == n_virtual - 1))
                    v_fs = jnp.where(p == 0, v_l + 1, v_l)
                    fwd_buf = jnp.where(
                        recv_f,
                        fwd_buf.at[jnp.clip(v_fs, 0, n_virtual - 1),
                                   m_l % kf].set(f_in),
                        fwd_buf)

                if with_b:
                    # Backward gradient to the left neighbor (ring wrap
                    # 0->P-1 crosses the chunk boundary downward).
                    # Global stage 0 sends nothing (its dx is the
                    # embedding gradient).
                    send_b = do_b & ~((p == 0) & (v_b == 0))
                    b_in = jax.lax.ppermute(
                        jnp.where(send_b, dx_m.astype(jnp.float32),
                                  jnp.zeros(mb_shape, jnp.float32)),
                        axis_name, ring_l)
                    right = (p + 1) % n_stages
                    e_r = bwd_table[right][t]
                    v_r, m_r = decode(jnp.maximum(e_r, 0))
                    recv_b = (e_r >= 0) & ~((right == 0) & (v_r == 0))
                    v_bs = jnp.where(p == last, v_r - 1, v_r)
                    bwd_buf = jnp.where(
                        recv_b,
                        bwd_buf.at[jnp.clip(v_bs, 0, n_virtual - 1),
                                   m_r % kb].set(b_in),
                        bwd_buf)

                return {"fwd_buf": fwd_buf, "bwd_buf": bwd_buf,
                        "x_buf": x_buf, "grads": grads,
                        "head_grads": head_grads, "dx": dx,
                        "loss": loss}, None
            return step

        carry = carry0
        for lo, hi, stp in ((0, t_warm, make_step(True, False)),
                            (t_warm, t_fend, make_step(True, True)),
                            (t_fend, n_ticks, make_step(False, True))):
            if hi > lo:
                carry, _ = jax.lax.scan(stp, carry, jnp.arange(lo, hi))

        # Carry grads have a leading V dim, so the flagged scatter
        # dim sits one deeper than in the plain schedule: shift the dim
        # entries by one (collect scatters at entry-1).
        collect_dims = None
        if fsdp_dims is not None:
            collect_dims = jax.tree_util.tree_map(
                lambda d: d + 1 if d >= 1 else d, fsdp_dims)
        return _collect_1f1b(carry, mesh, axis_name, batch_axes, p, last,
                             lambda g: g[:, None],
                             fsdp_dims=collect_dims)

    extra = [None] * (microbatches.ndim - 2)
    x_spec = P(None, batch_axes, *extra)
    rep = P()
    aux_spec = None
    if aux is not None:
        aux_spec = P(None, batch_axes, *([None] * (aux.ndim - 2)))
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(vp_specs(stacked_vp),
                  jax.tree_util.tree_map(lambda _: rep, head_params),
                  x_spec, aux_spec),
        out_specs=(rep, vp_specs(stacked_vp),
                   jax.tree_util.tree_map(lambda _: rep, head_params),
                   P(None, batch_axes, *extra)),
        check_vma=False)
    loss, grads_vp, head_grads, dx = fn(stacked_vp, head_params,
                                        microbatches, aux)
    return loss, jax.tree_util.tree_map(from_vp, grads_vp), head_grads, dx
