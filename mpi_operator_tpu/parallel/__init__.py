"""SPMD parallelism over jax.sharding.Mesh.

The reference delegates all parallelism to the workload (SURVEY.md §2.3);
its examples use Horovod DP allreduce.  The TPU-native workload stack
instead scales through one device mesh: data parallelism ('dp'), ZeRO-3
style parameter sharding ('fsdp'), Megatron tensor parallelism ('tp') and
ring-attention sequence/context parallelism ('sp') — XLA inserts the
collectives (psum / all_gather / reduce_scatter / ppermute) over ICI/DCN
from sharding annotations, replacing NCCL/MPI calls entirely.
"""

from .mesh import MeshConfig, create_mesh, batch_sharding  # noqa: F401
from .train import (TrainState, build_train_step,  # noqa: F401
                    run_train_loop)
