"""Python SDK for MPIJob.

Parity with /root/reference/sdk/python/v2beta1 (openapi-generated
V2beta1MPIJob* models + CustomObjectsApi submission, see
sdk/python/v2beta1/tensorflow-mnist.py:17-19).  Here the typed models ARE
the framework's API dataclasses — no generation step — and the client
wraps any Clientset (in-memory LocalCluster or a future HTTP shim), plus
YAML/dict round-trip and job builder helpers.
"""

from .client import MPIJobClient  # noqa: F401
from .builders import new_jax_job, job_from_yaml, job_to_yaml  # noqa: F401
