"""MPIJobClient — user-facing job lifecycle API.

The analogue of the reference SDK's CustomObjectsApi usage
(sdk/python/v2beta1/tensorflow-mnist.py): create/get/list/delete plus
wait helpers and condition inspection.
"""

from __future__ import annotations

import time
from typing import Optional

from ..api import constants
from ..api.types import MPIJob
from ..k8s.apiserver import Clientset


class MPIJobClient:
    def __init__(self, clientset: Clientset, namespace: str = "default"):
        self._cs = clientset
        self.namespace = namespace

    def _jobs(self, namespace: Optional[str] = None):
        return self._cs.mpi_jobs(namespace or self.namespace)

    # -- CRUD -------------------------------------------------------------
    def create(self, job: MPIJob) -> MPIJob:
        return self._jobs(job.metadata.namespace or None).create(job)

    def get(self, name: str, namespace: Optional[str] = None) -> MPIJob:
        return self._jobs(namespace).get(name)

    def list(self, namespace: Optional[str] = None) -> list:
        return self._jobs(namespace).list()

    def delete(self, name: str, namespace: Optional[str] = None) -> None:
        self._jobs(namespace).delete(name)

    def update(self, job: MPIJob) -> MPIJob:
        return self._jobs(job.metadata.namespace or None).update(job)

    # -- lifecycle helpers -------------------------------------------------
    def suspend(self, name: str, namespace: Optional[str] = None) -> MPIJob:
        job = self.get(name, namespace)
        job.spec.run_policy.suspend = True
        return self.update(job)

    def resume(self, name: str, namespace: Optional[str] = None) -> MPIJob:
        job = self.get(name, namespace)
        job.spec.run_policy.suspend = False
        return self.update(job)

    @staticmethod
    def condition_status(job: MPIJob, cond_type: str) -> Optional[str]:
        for c in job.status.conditions:
            if c.type == cond_type:
                return c.status
        return None

    def is_succeeded(self, name: str, namespace: Optional[str] = None) -> bool:
        return self.condition_status(self.get(name, namespace),
                                     constants.JOB_SUCCEEDED) == "True"

    def wait_for_condition(self, name: str, cond_type: str,
                           namespace: Optional[str] = None,
                           timeout: float = 300.0,
                           poll: float = 0.2) -> MPIJob:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.get(name, namespace)
            if self.condition_status(job, cond_type) == "True":
                return job
            if cond_type != constants.JOB_FAILED and \
                    self.condition_status(job, constants.JOB_FAILED) == "True":
                conds = [(c.type, c.status, c.reason, c.message)
                         for c in job.status.conditions]
                raise RuntimeError(f"MPIJob {name} failed: {conds}")
            time.sleep(poll)
        raise TimeoutError(
            f"MPIJob {name} did not reach {cond_type} in {timeout}s")

    def wait_for_completion(self, name: str,
                            namespace: Optional[str] = None,
                            timeout: float = 300.0) -> MPIJob:
        return self.wait_for_condition(name, constants.JOB_SUCCEEDED,
                                       namespace, timeout)
