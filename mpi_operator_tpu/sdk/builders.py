"""Job construction helpers + YAML round-trip."""

from __future__ import annotations

from typing import Optional

from ..api import constants
from ..api.types import MPIJob, MPIJobSpec, ReplicaSpec, RunPolicy
from ..k8s.core import Container, PodSpec, PodTemplateSpec
from ..k8s.meta import ObjectMeta, from_dict, to_dict


def new_jax_job(name: str,
                image: str,
                command: list,
                workers: int,
                namespace: str = "default",
                slots_per_worker: int = 1,
                run_launcher_as_worker: bool = True,
                launcher_command: Optional[list] = None,
                tpu_chips: int = 0,
                tpu_topology: str = "",
                tpu_accelerator: str = "",
                run_policy: Optional[RunPolicy] = None) -> MPIJob:
    """Build a TPU-native MPIJob: workers request google.com/tpu chips and
    GKE topology nodeSelectors; bootstrap rides the JAX coordinator env.

    The analogue of the reference's example YAMLs
    (examples/v2beta1/pi/pi.yaml) with the JAX implementation.
    """
    def pod(cmd, with_tpu: bool) -> PodTemplateSpec:
        container = Container(name="main", image=image, command=list(cmd))
        spec = PodSpec(containers=[container])
        if with_tpu and tpu_chips:
            container.resources.limits[constants.TPU_RESOURCE] = str(tpu_chips)
            if tpu_topology:
                spec.node_selector[
                    constants.GKE_TPU_TOPOLOGY_NODE_SELECTOR] = tpu_topology
            if tpu_accelerator:
                spec.node_selector[
                    constants.GKE_TPU_ACCELERATOR_NODE_SELECTOR] = \
                    tpu_accelerator
        return PodTemplateSpec(spec=spec)

    return MPIJob(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=MPIJobSpec(
            mpi_implementation=constants.IMPL_JAX,
            slots_per_worker=slots_per_worker,
            run_launcher_as_worker=run_launcher_as_worker,
            run_policy=run_policy or RunPolicy(),
            mpi_replica_specs={
                constants.REPLICA_TYPE_LAUNCHER: ReplicaSpec(
                    template=pod(launcher_command or command,
                                 run_launcher_as_worker)),
                constants.REPLICA_TYPE_WORKER: ReplicaSpec(
                    replicas=workers,
                    template=pod(command, True)),
            }))


def job_to_yaml(job: MPIJob) -> str:
    import yaml
    return yaml.safe_dump(to_dict(job), sort_keys=False)


def job_from_yaml(text: str) -> MPIJob:
    import yaml
    return from_dict(MPIJob, yaml.safe_load(text))
