"""Serving fleet in one process: the data-plane side of a ServeJob.

`ServeReplicaRunner` is the serving kubelet analogue: it watches the
ServeJob controller's replica pods and, for each, runs a REAL
`InferenceServer` in-process — flipping the pod Ready only once the
HTTP endpoint is bound (readiness gating is real, not declared) and
publishing the live URL on the pod's ``serving.kubeflow.org/url``
annotation, which is how the router discovers endpoints.

`LocalServeFleet` wires the whole loop — apiserver + ServeJobController
+ replica runner + fleet router (+ autoscaler when the ServeJob has an
autoscale block) — the serving counterpart of server/cluster.py's
LocalCluster, used by `make serve-fleet-smoke`, bench_serve_fleet.py
and the chaos `replica_kill` scenarios.  It is LocalCluster-shaped
(``.client``/``.controller``/``.kubelet``) so the chaos engine and the
default invariants run against it unchanged.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..api import constants
from ..api.types import ServeJob
from ..controller.servejob import ServeJobController, serve_selector
from ..k8s import core
from ..k8s.apiserver import Clientset, is_conflict, is_not_found
from ..k8s.selectors import match_labels
from ..telemetry import flight
from .autoscaler import ServeAutoscaler
from .router import FleetRouter


class ServeReplicaRunner:
    """Runs one InferenceServer per serving replica pod (see module
    docstring).  ``server_factory(pod) -> InferenceServer`` builds an
    UNstarted server for a pod; the runner starts it, reflects pod
    status, and keeps the router's membership in sync."""

    def __init__(self, clientset: Clientset,
                 server_factory: Callable,
                 namespace: str = "default",
                 router: Optional[FleetRouter] = None,
                 poll_interval: float = 0.05,
                 job_name: Optional[str] = None):
        self.client = clientset
        self.server_factory = server_factory
        self.namespace = namespace
        # Scope to ONE ServeJob's replicas when given: two fleets
        # sharing a namespace must not adopt (and route to) each
        # other's pods.
        self.job_name = job_name
        self.router = router
        self.poll_interval = float(poll_interval)
        # (ns, name) -> (pod uid, InferenceServer).  The uid matters:
        # a rolling replacement deletes and recreates the pod under the
        # SAME name (often within one controller sync), so name alone
        # would leave the old-template server running forever while the
        # recreated pod waits Pending.
        self._servers: Dict[tuple, tuple] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- pod reflection ----------------------------------------------------
    def _serve_pods(self) -> dict:
        pods = {}
        for p in self.client.server.list("v1", "Pod", self.namespace):
            if p.metadata.labels.get(constants.REPLICA_TYPE_LABEL) \
                    != constants.REPLICA_TYPE_SERVE.lower():
                continue
            if self.job_name is not None and p.metadata.labels.get(
                    constants.JOB_NAME_LABEL) != self.job_name:
                continue
            pods[(p.metadata.namespace, p.metadata.name)] = p
        return pods

    def _reflect(self, namespace: str, name: str, phase: str,
                 ready: bool, url: str = "", reason: str = "") -> None:
        """Annotate the URL (metadata update) then reflect phase/Ready
        (status update), both conflict-retried."""
        for _ in range(20):
            try:
                pod = self.client.pods(namespace).get(name)
            except Exception as exc:
                if is_not_found(exc):
                    return
                time.sleep(0.05)
                continue
            if url and pod.metadata.annotations.get(
                    constants.SERVE_URL_ANNOTATION) != url:
                try:
                    pod.metadata.annotations[
                        constants.SERVE_URL_ANNOTATION] = url
                    pod = self.client.pods(namespace).update(pod)
                except Exception as exc:
                    if is_conflict(exc):
                        continue
                    time.sleep(0.05)
                    continue
            pod.status.phase = phase
            pod.status.reason = reason
            pod.status.conditions = [c for c in pod.status.conditions
                                     if c.type != "Ready"]
            pod.status.conditions.append(core.PodCondition(
                type="Ready",
                status=core.CONDITION_TRUE if ready
                else core.CONDITION_FALSE))
            try:
                self.client.pods(namespace).update_status(pod)
                return
            except Exception as exc:
                if is_conflict(exc) or not is_not_found(exc):
                    time.sleep(0.05)
                    continue
                return

    def _start_replica(self, pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        try:
            srv = self.server_factory(pod)
            srv.start()
        except Exception as exc:
            flight.record("serving", "replica_start_failed",
                          pod=f"{key[0]}/{key[1]}", error=str(exc))
            self._reflect(*key, phase=core.POD_FAILED, ready=False,
                          reason="StartError")
            return
        with self._lock:
            self._servers[key] = (pod.metadata.uid, srv)
        self._reflect(*key, phase=core.POD_RUNNING, ready=True,
                      url=srv.url)
        flight.record("serving", "replica_up", pod=f"{key[0]}/{key[1]}",
                      url=srv.url)
        if self.router is not None:
            self.router.add_replica(key[1], srv.url)

    def _stop_replica(self, key: tuple, graceful: bool = True) -> None:
        with self._lock:
            entry = self._servers.pop(key, None)
        if entry is None:
            return
        _, srv = entry
        if self.router is not None:
            self.router.remove_replica(key[1])
        try:
            srv.stop()
        except Exception as exc:
            # A wedged server must not block teardown of the rest of
            # the fleet; the flight ring keeps the evidence.
            flight.record("serving", "replica_stop_error",
                          pod=f"{key[0]}/{key[1]}", error=repr(exc))
        flight.record("serving", "replica_down",
                      pod=f"{key[0]}/{key[1]}", graceful=graceful)

    def kill(self, namespace: str, name: str) -> bool:
        """Abrupt replica death (chaos `replica_kill`): poison the
        batcher FIRST so in-flight requests fail loudly and /healthz
        flips 503 (what tells the router to retry them elsewhere), then
        mark the pod Failed so the controller replaces it."""
        key = (namespace, name)
        with self._lock:
            entry = self._servers.get(key)
        if entry is None:
            return False
        srv = entry[1]
        batcher = getattr(srv, "_batcher", None)
        if batcher is not None:
            # The batcher's own fatal path: sets fatal_error/_stop so
            # /healthz flips 503 and queued requests fail loudly, and
            # cuts the batcher-fatal black-box bundle (phase names the
            # chaos kill) — same semantics as any other fatal tick.
            batcher._tick_fatal(RuntimeError("replica killed (chaos)"),
                                "replica-kill")
        if self.router is not None:
            self.router.mark_dead(name)
        self._reflect(namespace, name, phase=core.POD_FAILED,
                      ready=False, reason="Killed")
        self._stop_replica(key, graceful=False)
        flight.record("serving", "replica_killed", pod=f"{namespace}/{name}")
        return True

    # -- control loop ------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                pods = self._serve_pods()
            except Exception:
                # API weather (chaos bursts): hold membership, retry.
                self._stop.wait(self.poll_interval)
                continue
            with self._lock:
                running = dict(self._servers)
            for key, (uid, _) in running.items():
                pod = pods.get(key)
                if pod is None:
                    self._stop_replica(key)  # pod deleted: wind down
                elif pod.metadata.uid != uid:
                    # Same name, new pod object (rolling replacement
                    # recreates in place): the server belongs to the
                    # DEAD pod — stop it so the fresh pod starts below.
                    self._stop_replica(key)
            with self._lock:
                running_keys = set(self._servers)
            for key, pod in pods.items():
                if key not in running_keys and pod.status.phase in (
                        "", core.POD_PENDING):
                    self._start_replica(pod)
            self._stop.wait(self.poll_interval)

    def start(self) -> "ServeReplicaRunner":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-replica-runner")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            keys = list(self._servers)
        for key in keys:
            self._stop_replica(key)


class LocalServeFleet:
    """ServeJob end-to-end in one process (see module docstring)."""

    def __init__(self, job: ServeJob, server_factory: Callable,
                 client: Optional[Clientset] = None,
                 policy: str = "prefix",
                 router_refresh: float = 0.1,
                 autoscaler_poll: float = 0.5,
                 mpi_controller=None,
                 router_seed: int = 0):
        self.client = client or Clientset()
        self.job = job
        self.namespace = job.metadata.namespace or "default"
        job.metadata.namespace = self.namespace
        self.controller = ServeJobController(
            self.client, mpi_controller=mpi_controller)
        self.router = FleetRouter(policy=policy,
                                  refresh_interval=router_refresh,
                                  seed=router_seed)
        self.runner = ServeReplicaRunner(self.client, server_factory,
                                         namespace=self.namespace,
                                         router=self.router,
                                         job_name=job.metadata.name)
        self.autoscaler = None
        if job.spec.autoscale is not None:
            self.autoscaler = ServeAutoscaler(
                self.client, self.namespace, job.metadata.name,
                self.router, poll_interval=autoscaler_poll,
                model=job.metadata.name)
        # LocalCluster-shape for the chaos engine + default invariants.
        self.kubelet = None
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "LocalServeFleet":
        self.controller.run()
        self.router.start()
        self.runner.start()
        self.client.serve_jobs(self.namespace).create(self.job)
        if self.autoscaler is not None:
            self.autoscaler.start()
        self._started = True
        return self

    def stop(self) -> None:
        if not self._started:
            return
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.runner.stop()
        self.router.stop()
        self.controller.stop()
        self._started = False

    def __enter__(self) -> "LocalServeFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- conveniences ------------------------------------------------------
    def wait_ready(self, replicas: Optional[int] = None,
                   timeout: float = 60.0) -> None:
        """Block until `replicas` (default: the spec count) replicas are
        healthy in the router's routing set."""
        want = replicas if replicas is not None \
            else (self.job.spec.replicas or 1)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.router.healthy_replicas()) >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"fleet never reached {want} healthy replicas "
            f"({len(self.router.healthy_replicas())} up)")

    def kill_replica(self, namespace: str, name: str) -> bool:
        return self.runner.kill(namespace, name)

    def serve_pods(self) -> list:
        selector = serve_selector(self.job.metadata.name)
        return [p for p in self.client.server.list("v1", "Pod",
                                                   self.namespace)
                if match_labels(selector, p.metadata.labels)]

    def fleet_prefix_stats(self) -> dict:
        """Aggregate prefix-cache counters across live replicas (the
        fleet-wide hit-rate number the bench publishes)."""
        agg = {"lookups": 0, "hit_blocks": 0, "hit_tokens": 0,
               "evicted": 0}
        with self.runner._lock:
            servers = [srv for _, srv in self.runner._servers.values()]
        for srv in servers:
            batcher = getattr(srv, "_batcher", None)
            stats = getattr(batcher, "prefix_stats", None)
            if stats:
                for k in agg:
                    agg[k] += stats[k]
        return agg
