"""Continuous batching for the KV-cache decode path.

Requests arrive at arbitrary times; instead of serializing whole
generations (single-flight) the batcher keeps B persistent cache slots
and runs ONE decode step per tick across every active slot — new
requests are prefilled into free slots between ticks and finished slots
are freed immediately (vLLM-style iteration-level scheduling;
per-slot greedy or nucleus sampling).  Built on the per-row cache index (models/llama.py): each
slot decodes at its own position, so mixed-length, mixed-arrival
sequences coexist in one batch.

The decode step is jitted once for the fixed slot count; prefill is
jitted per padded prompt-width bucket (powers of two) to bound
recompilation.

With ``page_size > 0`` the KV cache is paged (vLLM-style, static
shapes): K/V live in a shared pool of fixed-size blocks and each slot
holds a block table instead of a dense max_seq_len row
(models/llama.py paged decode branch).  A slot's block budget
(prompt + max_new_tokens) is reserved at admission and returned at
retirement, so with ``cache_blocks`` below the worst case the pool
oversubscribes: many short requests share the memory one worst-case
slot would pin, and admission simply waits for blocks when the pool
runs dry.

The steady-state tick is **pipelined** (``pipelined=True``, the
default): step k+1 is dispatched from step k's still-on-device token
array before any of step k's tokens are fetched, so the device computes
step k+1 while the host runs stop-checks, emission, retirement and
admission for step k (JAX async dispatch).  A slot that retires or is
replaced between dispatch and fetch simply has its overrun token
discarded at fetch time, so emitted streams are byte-identical to the
serialized loop's — greedy and sampled (regression-tested; see
tools/serve_bench_smoke.py).  Each tick fetches the whole
``[max_slots]`` token array in ONE device→host transfer instead of one
blocking transfer per slot; the transfer/dispatch budget is counted in
telemetry (``serving_d2h_transfers_total`` et al.) so the invariant is
asserted, not assumed.  Speculative batchers keep the serialized loop:
acceptance needs the committed host-side streams before each round, and
a verify round already amortizes its round-trip over k+1 tokens.

Paged mode also prefix-caches (``prefix_cache=True``): full prompt
blocks are content-addressed by their token prefix, so a request whose
prompt begins with a previously-seen prefix points its block table at
the existing pool blocks (refcounted; evicted LRU only at refcount 0
under pool pressure) and prefills ONLY the suffix — the suffix runs
through the paged multi-token decode branch as a batch-1 apply whose
block table already maps the shared prefix, writing exclusively into
the slot's private blocks.  K/V of a position depends only on its token
prefix (causal attention, absolute RoPE), so reuse is exact; shared
blocks are never written again because every later write lands at
positions at or past the owning slot's prompt suffix.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

from ..analysis.lockcheck import name_lock
from ..models.llama import select_rows as _select_rows
from ..telemetry.metrics import Registry, new_serving_metrics

PIPELINE_ENV = "MPI_OPERATOR_SERVE_PIPELINE"
# Injected data-plane latency (simulation/bench knobs): a per-tick decode
# sleep and a per-prefilled-token sleep, both held under the device lock
# so they model accelerator occupancy.  On the single-core bench host
# these make routing/cache effects measurable where real tiny-model
# compute would be GIL-serialized noise (bench_serve_fleet.py).  Never
# set in production.
DECODE_LATENCY_ENV = "MPI_OPERATOR_SERVE_DECODE_LATENCY"
PREFILL_TOKEN_LATENCY_ENV = "MPI_OPERATOR_SERVE_PREFILL_TOKEN_LATENCY"

# KV export/import waves are padded to a FIXED width so the gather /
# `.at[blks].set` programs compile exactly ONCE per pool leaf, ever
# (variable widths would compile a fresh XLA program per distinct
# page count — a compile storm under the device lock).  The widths
# differ on purpose: exports run on prefill replicas where nothing
# competes for the device lock, so one wide wave per transfer batch
# (matching MAX_PAGES_PER_PUSH in serving/kv_transfer.py) is
# cheapest; imports land on DECODE replicas with live token streams,
# so waves are kept narrow — the lock is released between waves and
# decode steps interleave, bounding the per-import decode stall to
# one narrow scatter instead of one full transfer batch.
_EXPORT_WAVE_WIDTH = 64
_IMPORT_WAVE_WIDTH = 8


def _page_digest(parent_hex: str, page) -> str:
    """Content digest of one prompt page CHAINED through its parent's
    digest, so a digest identifies the whole token prefix up to and
    including this page — position-independent, unlike the in-batcher
    registry key (which chains through pool block ids)."""
    import hashlib
    h = hashlib.blake2b(digest_size=8)
    h.update(parent_hex.encode())
    h.update(",".join(str(int(t)) for t in page).encode())
    return h.hexdigest()


def prefix_page_digests(tokens, page_size: int) -> List[str]:
    """Chain digests of a prompt's full pages eligible for prefix-cache
    reuse (at least one token is always left to prefill — the same cap
    as ContinuousBatcher._match_prefix).  Digest j covers tokens
    [0, (j+1)*page_size); the fleet router computes these for an
    incoming prompt and matches them against each replica's advertised
    ``prefix_digest()`` to find the longest cached run."""
    if page_size <= 0:
        # Disaggregated transfer and router prefix matching are both
        # meaningless without a paged cache; surface the misconfig at
        # the digest layer too so no caller can half-work (the disagg
        # fleet rejects page_size == 0 at construction — see
        # serving/disagg.py DisaggConfigError).
        raise ValueError(
            f"prefix_page_digests requires a paged KV cache "
            f"(page_size > 0), got page_size={page_size}")
    out: List[str] = []
    parent = ""
    for j in range((len(tokens) - 1) // page_size):
        parent = _page_digest(parent,
                              tokens[j * page_size:(j + 1) * page_size])
        out.append(parent)
    return out


class _WaitQueue:
    """FIFO of requests with a *non-dequeuing* idle wait.

    ``queue.Queue.get(timeout) + put(...)`` — the old idle-wait idiom —
    re-enqueues the peeked request BEHIND anything submitted in
    between, breaking admission FIFO exactly when the batcher is busy
    waking up.  This queue exposes :meth:`wait_nonempty` instead: the
    scheduler blocks on the condition without ever taking the head, so
    submission order is admission order unconditionally.
    """

    def __init__(self):
        self._items: deque = deque()
        self._cond = threading.Condition()

    def put(self, item) -> None:
        with self._cond:
            self._items.append(item)
            self._cond.notify_all()

    def get_nowait(self):
        with self._cond:
            if not self._items:
                raise queue.Empty
            return self._items.popleft()

    def qsize(self) -> int:
        with self._cond:
            return len(self._items)

    def wait_nonempty(self, timeout: float) -> bool:
        """Block until an item is present (without removing it) or the
        timeout elapses; returns whether the queue is non-empty."""
        with self._cond:
            if not self._items:
                self._cond.wait(timeout)
            return bool(self._items)

    def poke(self) -> None:
        """Wake an idle ``wait_nonempty`` without enqueuing anything —
        used by out-of-band scheduler work (KV-page imports) so an idle
        batcher services it immediately instead of at the next 50ms
        idle-poll tick."""
        with self._cond:
            self._cond.notify_all()


@dataclass
class _Request:
    tokens: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    seed: int = 0
    stop_tokens: frozenset = frozenset()
    done: threading.Event = field(default_factory=threading.Event)
    output: List[int] = field(default_factory=list)
    error: Optional[Exception] = None
    on_token: Optional[object] = None  # callable(int), streaming hook
    cancelled: threading.Event = field(default_factory=threading.Event)
    # Telemetry: set at enqueue; emit() attributes TTFT (first token
    # after submission) and inter-token latency to the serving
    # histograms.
    metrics: Optional[dict] = None
    submitted_at: float = 0.0
    _last_emit: float = 0.0
    # Set when the request sat out a pool-exhaustion deferral, so its
    # admission wait lands in the path="deferred" histogram variant.
    was_deferred: bool = False
    # Causal tracing (router-carried context): wall-clock submit time
    # and the perf_counter admission mark, so the first token emits the
    # replica-side queue-wait and prefill spans retroactively.
    trace_ctx: Optional[object] = None
    submitted_wall: float = 0.0
    admitted_at: float = 0.0

    def emit(self, token: int) -> None:
        if self.metrics is not None:
            now = time.perf_counter()
            if not self.output and self.submitted_at:
                self.metrics["ttft_seconds"].observe(
                    now - self.submitted_at)
                if self.trace_ctx is not None:
                    self._trace_first_token(now)
            elif self.output and self._last_emit:
                self.metrics["token_latency_seconds"].observe(
                    now - self._last_emit)
            self._last_emit = now
        self.output.append(token)
        if self.on_token is not None:
            self.on_token(token)

    def _trace_first_token(self, now: float) -> None:
        """Replica-side spans of the request's causal trace, emitted
        once at first token: submit → admission (``serve_queue_wait``)
        and admission → first token (``prefill`` — prefill dominates
        it), both parented to the router's request span."""
        from ..telemetry.trace import default_tracer
        admitted = self.admitted_at or self.submitted_at
        queue_wait = max(0.0, admitted - self.submitted_at)
        tracer = default_tracer()
        tracer.emit("serve_queue_wait", ts=self.submitted_wall,
                    dur=queue_wait, ctx=self.trace_ctx,
                    deferred=self.was_deferred)
        tracer.emit("prefill", ts=self.submitted_wall + queue_wait,
                    dur=max(0.0, now - admitted), ctx=self.trace_ctx,
                    prompt_tokens=len(self.tokens))

    @property
    def finished(self) -> bool:
        """Budget exhausted or a stop/EOS token emitted (the stop token
        itself is included in the output, the standard convention)."""
        return (len(self.output) >= self.max_new_tokens
                or (bool(self.stop_tokens)
                    and self.output
                    and self.output[-1] in self.stop_tokens))


def _bucket(n: int, cap: int) -> int:
    width = 8
    while width < n:
        width *= 2
    return min(width, cap)  # never pad past the cache length


class ContinuousBatcher:
    """Continuous-batching scheduler over `model`'s decode path; each
    slot carries its own (temperature, top_p, rng) so greedy and
    sampling requests share decode ticks."""

    def __init__(self, model, variables, max_slots: int = 4,
                 device_lock: Optional[threading.Lock] = None,
                 page_size: int = 0, cache_blocks: int = 0,
                 prefix_cache: bool = True,
                 draft_model=None, draft_variables=None,
                 draft_len: int = 4, kv_cache_dtype: str = "auto",
                 draft_strategy: Optional[str] = None,
                 prompt_lookup_ngram: int = 3,
                 prefill_chunk: int = 0,
                 pipelined: Optional[bool] = None,
                 telemetry_registry: Optional[Registry] = None,
                 decode_latency: Optional[float] = None,
                 prefill_token_latency: Optional[float] = None):
        import dataclasses

        import jax
        import jax.numpy as jnp

        self.model = model
        self.variables = variables
        self.max_slots = max_slots
        self.telemetry = new_serving_metrics(telemetry_registry
                                             or Registry())
        # Pipelined steady-state ticks (see module docstring): default
        # on, overridable per-batcher or fleet-wide via the env knob;
        # forced off below when a draft is configured (speculation needs
        # the committed host-side streams before every round).
        if pipelined is None:
            pipelined = os.environ.get(
                PIPELINE_ENV, "1").lower() not in ("0", "false", "no")
        self.pipelined = bool(pipelined)
        # Injected accelerator-occupancy latency (see module constants):
        # slept under the device lock so concurrent replicas on a
        # GIL-bound host still overlap realistically.
        if decode_latency is None:
            decode_latency = float(os.environ.get(DECODE_LATENCY_ENV,
                                                  "0") or 0)
        if prefill_token_latency is None:
            prefill_token_latency = float(os.environ.get(
                PREFILL_TOKEN_LATENCY_ENV, "0") or 0)
        self._decode_latency = float(decode_latency)
        self._prefill_token_latency = float(prefill_token_latency)
        # Tick accounting, written only by the scheduler thread: the
        # flight-recorder breadcrumb that says whether a dead batcher
        # was mid-dispatch or mid-fetch, and the source for the
        # serving_pipeline_depth gauge.
        self.ticks_dispatched = 0
        self.ticks_fetched = 0
        # Bench-only escape hatch (bench_serve.py --hotpath "before"
        # capture): fetch each live slot's token with its own blocking
        # device->host transfer, reproducing the pre-pipelining loop's
        # per-slot `int(out[i])` cost shape.  Never set in production.
        self._per_slot_fetch = False
        self._queue: "_WaitQueue" = _WaitQueue()
        self._stop = threading.Event()
        # Set when the scheduler loop dies unrecoverably (an exception
        # inside a donated prefill leaves self._cache referencing
        # donated buffers; a device error mid-dispatch or mid-fetch
        # poisons the tick pipeline — see _tick_fatal).  Once set,
        # every submit fails loudly instead of queueing against a dead
        # KV cache; _fatal_phase says which tick phase died.
        self.fatal_error: Optional[BaseException] = None
        self._fatal_phase: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        # Shared with other users of the same device (e.g. the server's
        # non-batched generate path) so at most one model computation is
        # in flight at a time; taken per decode tick / prefill, not for
        # whole generations.
        # Named hot lock: blocking here stalls every decode tick
        # (docs/ANALYSIS.md, lockcheck).
        self._device_lock = name_lock(device_lock or threading.Lock(),
                                      "batcher.device_lock")

        cfg = model.config
        if getattr(cfg, "page_size", 0) > 0:
            # Prefill runs on the dense layout and the batcher derives
            # the paged decode model itself — a pre-paged model here
            # would make prefill read all-scratch tables (garbage) and
            # break install.  The layout is the batcher's to choose:
            # pass page_size= to this constructor instead.
            raise ValueError(
                "ContinuousBatcher requires a dense-layout model "
                "(config.page_size == 0); use the page_size argument "
                "to enable the paged cache")
        self._jnp = jnp
        self._jax = jax
        params = {"params": variables["params"]}

        # Paged KV cache (page_size > 0): decode runs against a shared
        # block pool with per-slot block tables instead of per-slot dense
        # rows.  cache_blocks sizes the pool (default: worst case, every
        # slot at max_seq_len); smaller pools oversubscribe — admission
        # waits for free blocks, so many short sequences can share the
        # memory one worst-case slot would pin.  Prefill stays on the
        # dense layout (batch-1 row, scattered into the pool on install).
        self.page_size = page_size
        # Chunked prefill (paged only): admit long prompts through
        # fixed-width batch-1 paged applies that share the pool, so peak
        # activation memory is O(chunk) instead of O(prompt) — what lets
        # 7B serve a 4k context on one v5e chip (BENCH_LLAMA_SERVE.json:
        # the dense 4k prefill is the only program that does not fit).
        self._prefill_chunk = int(prefill_chunk)
        if self._prefill_chunk > 0 and page_size <= 0:
            raise ValueError(
                "prefill_chunk requires the paged cache (page_size > 0); "
                "the dense layout prefills whole prompts")
        if kv_cache_dtype != "auto" and page_size <= 0:
            # Never silently serve an unquantized cache the caller
            # believes is int8 (same loud-misconfig convention as
            # server.py's kv_page_size guard).
            raise ValueError(
                f"kv_cache_dtype={kv_cache_dtype!r} requires the paged "
                f"cache (page_size > 0)")
        if page_size > 0:
            decode_cfg = dataclasses.replace(
                cfg, page_size=page_size, cache_blocks=cache_blocks,
                kv_cache_dtype=kv_cache_dtype)
            # Keep the model's mesh: dropping it would silently turn the
            # decode path's activation sharding hints into no-ops under
            # tensor-parallel serving.
            self._decode_model = type(model)(
                decode_cfg, mesh=getattr(model, "mesh", None))
            nb = decode_cfg.pool_blocks(max_slots)
            self._free_blocks = list(range(1, nb))  # 0 = reserved scratch
            self._total_blocks = nb - 1
            self._slot_blocks: dict = {}
            self._slot_shared: dict = {}   # slot -> shared-prefix blocks
            self._blocks_per_row = decode_cfg.blocks_per_row
            # Prefix cache: token-prefix tuple -> pool block id holding
            # that prefix's page of K/V; _block_meta refcounts registered
            # blocks (refs = live slots whose tables map the block;
            # refs == 0 blocks stay cached until evicted under pressure).
            self._prefix_cache = bool(prefix_cache)
            self._registry: dict = {}
            self._block_meta: dict = {}
            # block id -> chain digest of the token prefix it completes
            # (prefix_page_digests form); the compact hit-index the
            # replica advertises to the fleet router (server.py
            # /fleet-state).
            self._block_digest: dict = {}
            self._prefix_clock = 0
            self._retire_count = 0
            self.prefix_stats = {"lookups": 0, "hit_blocks": 0,
                                 "hit_tokens": 0, "evicted": 0}
            self._suffix_prefill_cache: dict = {}
            # Disaggregated serving (serving/kv_transfer.py): KV pages
            # pushed by a prefill replica wait here until the scheduler
            # thread imports them — ALL pool/registry mutation stays on
            # the scheduler thread, same contract as admission.
            self._kv_imports: deque = deque()
            self._kv_imports_lock = name_lock(
                threading.Lock(), "batcher.kv_imports_lock")
        else:
            self._decode_model = model
        decode_model = self._decode_model

        # Persistent slot cache, initialized by tracing a dummy decode.
        _, state = decode_model.apply(
            params, jnp.zeros((max_slots, 1), jnp.int32), decode=True,
            mutable=["cache"])
        cache = state["cache"]
        if hasattr(cache, "unfreeze"):
            cache = cache.unfreeze()
        self._cache = self._reset_cache(cache)

        @jax.jit
        def decode_step(cache, tokens, temps, top_ps, keys, top_ks):
            logits, state = decode_model.apply(
                {**params, "cache": cache}, tokens[:, None], decode=True,
                mutable=["cache"])
            nxt, keys = _select_rows(logits[:, -1], temps, top_ps, keys,
                                     top_ks)
            return state["cache"], nxt.astype(jnp.int32), keys

        self._decode_step = decode_step
        self._prefill_cache = {}
        self._max_seq_len = cfg.max_seq_len

        # Speculative decoding (greedy slots): a small same-vocab draft
        # proposes draft_len tokens per tick through its OWN per-slot
        # dense cache; the target verifies all slots in ONE width-(k+1)
        # decode and commits its own argmax prefix + bonus.  A tick with
        # any sampling slot falls back to plain width-1 decode (the
        # acceptance rule is only lossless for argmax).
        self.draft_len = int(draft_len)
        self._draft_model = draft_model
        # Training-free drafting (serving/drafts.py): proposals come from
        # host-side n-gram lookup over the request's own context — no
        # draft model, cache, or prefill.  Same verify/acceptance path.
        if draft_strategy is not None:
            from .drafts import DRAFT_STRATEGIES
            if draft_strategy not in DRAFT_STRATEGIES:
                raise ValueError(f"unknown draft_strategy "
                                 f"{draft_strategy!r}; "
                                 f"one of {DRAFT_STRATEGIES}")
            if draft_model is not None:
                raise ValueError(
                    "draft_strategy and draft_model are exclusive")
            if self.draft_len < 1:
                raise ValueError("draft_len must be >= 1")
        self._draft_strategy = draft_strategy
        self._pl_ngram = int(prompt_lookup_ngram)
        if (draft_model is None) != (draft_variables is None):
            raise ValueError("draft_model and draft_variables go together")
        if draft_model is not None:
            dcfg = draft_model.config
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError("draft/target vocab_size mismatch")
            if getattr(dcfg, "page_size", 0) > 0:
                raise ValueError("draft model must be dense-layout")
            if dcfg.max_seq_len < cfg.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len {dcfg.max_seq_len} < target "
                    f"{cfg.max_seq_len}: verify rounds write past it")
            if self.draft_len < 1:
                raise ValueError("draft_len must be >= 1")
            dparams = {"params": draft_variables["params"]}
            _, dstate = draft_model.apply(
                dparams, jnp.zeros((max_slots, 1), jnp.int32),
                decode=True, mutable=["cache"])
            dcache = dstate["cache"]
            if hasattr(dcache, "unfreeze"):
                dcache = dcache.unfreeze()
            self._draft_cache = self._reset_cache(dcache)

            @jax.jit
            def draft_step(cache, tokens):
                logits, state = draft_model.apply(
                    {**dparams, "cache": cache}, tokens, decode=True,
                    mutable=["cache"])
                return (state["cache"],
                        jnp.argmax(logits[:, -1], axis=-1)
                        .astype(jnp.int32))

            self._draft_step = draft_step
            self._draft_prefill_cache = {}
            self._dparams = dparams
            # slot -> highest committed position whose K/V the draft
            # cache validly holds.  Plain-tick interludes advance the
            # committed stream without the draft seeing it; on
            # spec-resume a lagging slot is re-prefilled, else its
            # proposals would be argmax over zero K/V forever.
            self._draft_pos: dict = {}
        if draft_model is not None or draft_strategy is not None:
            # Shared by both draft kinds: ONE width-(k+1) target forward
            # scoring all proposals.
            @jax.jit
            def verify_step(cache, tokens):
                logits, state = decode_model.apply(
                    {**params, "cache": cache}, tokens, decode=True,
                    mutable=["cache"])
                return (state["cache"],
                        jnp.argmax(logits, axis=-1).astype(jnp.int32))

            self._verify_step = verify_step
        self.spec_stats = {"spec_ticks": 0, "plain_ticks": 0,
                           "accepted_drafts": 0, "drafted": 0}
        if draft_model is not None or draft_strategy is not None:
            # Speculative batchers keep the serialized tick: acceptance
            # needs every committed token on the host before the next
            # round, and a verify round already amortizes its one
            # round-trip over k+1 tokens.  Plain-tick interludes
            # (sampling neighbors) stay serialized too, so the emitted
            # streams are trivially identical to the reference loop's.
            self.pipelined = False

    # -- cache plumbing ----------------------------------------------------
    def _padded_scatter(self, arr, idxs: List[int], vals):
        """``arr.at[idxs].set(vals)`` with idxs/vals PADDED to
        max_slots by repeating their FIRST entry (index and value must
        pad together: duplicate writes are order-independent only
        because every duplicate carries the same value).  Keeps XLA at
        exactly ONE compiled scatter shape per array instead of one per
        observed wave size (profiling found per-wave-size recompiles)."""
        jnp = self._jnp
        pad = self.max_slots - len(idxs)
        idx = jnp.asarray(idxs + [idxs[0]] * pad, jnp.int32)
        if isinstance(vals[0], int):
            padded = jnp.asarray(vals + [vals[0]] * pad, jnp.int32)
        else:  # device arrays (rng keys)
            padded = jnp.stack(list(vals) + [vals[0]] * pad)
        return arr.at[idx].set(padded)

    def _reset_cache(self, cache):
        return self._jax.tree_util.tree_map(self._jnp.zeros_like, cache)

    def _prefill(self, tokens: List[int], sample_args):
        """Single-sequence prefill -> (cache_row_tree, next_token, key).
        sample_args = (temperature, top_p, rng_key, top_k) scalars for
        the new sequence's first sampled token."""
        jax, jnp = self._jax, self._jnp
        width = _bucket(len(tokens), self._max_seq_len)
        fn = self._prefill_cache.get(width)
        if fn is None:
            params = {"params": self.variables["params"]}

            @jax.jit
            def prefill(padded, length, temp, top_p, key, top_k):
                logits, state = self.model.apply(
                    params, padded, decode=True, mutable=["cache"])
                cache = state["cache"]
                nxt, key = _select_rows(logits[:, length - 1],
                                        temp[None], top_p[None],
                                        key[None], top_k[None])
                return cache, nxt[0].astype(jnp.int32), key[0]

            fn = self._prefill_cache[width] = prefill
        padded = jnp.asarray([tokens + [0] * (width - len(tokens))],
                             jnp.int32)
        return fn(padded, len(tokens), *sample_args)

    def _install_dense_row(self, cache, slot: int, row_cache,
                           length: int):
        """Copy a batch-1 prefill cache into row `slot` of a dense
        per-slot cache (the target's dense layout AND the draft's)."""
        jnp = self._jnp
        if hasattr(row_cache, "unfreeze"):
            row_cache = row_cache.unfreeze()

        def rec(dst, src):
            if hasattr(dst, "items"):
                return {k: rec(dst[k], src[k]) for k in dst}
            if dst.ndim >= 2:  # cached_key/value [B, L, KH, D]
                L = min(dst.shape[1], src.shape[1])
                return dst.at[slot, :L].set(src[0, :L])
            return dst.at[slot].set(jnp.int32(length))  # cache_index [B]
        return rec(cache, row_cache)

    def _install(self, slot: int, row_cache, length: int):
        """Copy a batch-1 prefill cache into persistent slot `slot`."""
        if self.page_size > 0:
            if hasattr(row_cache, "unfreeze"):
                row_cache = row_cache.unfreeze()
            return self._install_paged(slot, row_cache, length)
        self._cache = self._install_dense_row(self._cache, slot,
                                              row_cache, length)

    # -- speculative decoding ----------------------------------------------
    def _draft_prefill_install(self, slot: int, tokens: List[int]):
        """Prefill the prompt through the draft model (batch-1 dense)
        and install the row into the draft slot cache."""
        jax, jnp = self._jax, self._jnp
        width = _bucket(len(tokens), self._draft_model.config.max_seq_len)
        fn = self._draft_prefill_cache.get(width)
        if fn is None:
            dparams = self._dparams
            draft_model = self._draft_model

            @jax.jit
            def dprefill(padded):
                _, state = draft_model.apply(dparams, padded, decode=True,
                                             mutable=["cache"])
                return state["cache"]

            fn = self._draft_prefill_cache[width] = dprefill
        padded = jnp.asarray([tokens + [0] * (width - len(tokens))],
                             jnp.int32)
        self._draft_cache = self._install_dense_row(
            self._draft_cache, slot, fn(padded), len(tokens))
        self._draft_pos[slot] = len(tokens) - 1

    def _speculative_tick(self, slots, next_tokens):
        """One speculation round across every active (all-greedy) slot:
        k draft proposals through the draft's per-slot cache, ONE
        width-(k+1) target verify, per-slot longest-prefix acceptance +
        bonus, per-row cache_index rollback over rejected positions
        (stale K/V past the index is masked and overwritten — the same
        contract the variable-length decode path relies on).  Inactive
        slots ride along: their dense rows are garbage that admit
        resets, and their paged tables point at reserved scratch
        block 0.  Mirrors models/speculative.py at slot granularity."""
        import numpy as np

        from ..models.llama import _set_cache_index

        jnp = self._jnp
        k = self.draft_len
        active = [i for i, r in enumerate(slots) if r is not None]
        hists = {i: slots[i].tokens + slots[i].output for i in active}
        m = np.zeros((self.max_slots,), np.int64)
        for i in active:
            # Committed-and-cached length: everything but the newest
            # emitted token is in both caches (plain-tick invariant).
            m[i] = len(hists[i]) - 1

        t_last = np.zeros((self.max_slots,), np.int32)
        for i in active:
            t_last[i] = hists[i][m[i]]

        if self._draft_strategy is not None:
            # Training-free drafting: host-side n-gram lookup over each
            # slot's committed stream (prompt + output through position
            # m).  No draft cache, no device work — microseconds.
            from .drafts import propose_prompt_lookup

            drafted = np.zeros((self.max_slots, k), np.int32)
            for i in active:
                drafted[i] = propose_prompt_lookup(
                    hists[i][:m[i] + 1], k, self._pl_ngram)
        else:
            # Model draft proposes k tokens: re-feed the last two
            # committed tokens at index m-1 (one identical K/V rewrite)
            # so the draft cache is current through m, then extend one
            # token at a time.  Device calls hold the shared lock;
            # host-side acceptance/emission runs after it is released
            # (the plain tick's contract).
            feed = np.zeros((self.max_slots, 2), np.int32)
            for i in active:
                feed[i] = (hists[i][m[i] - 1], hists[i][m[i]])
            with self._device_lock:
                # Spec-resume catch-up: a plain-tick interlude (sampling
                # neighbor) advances the committed stream without the
                # draft seeing it; the 2-token re-feed only covers
                # positions m-1/m, so a slot whose coverage lags further
                # gets a full re-prefill of its committed prefix.
                for i in active:
                    if self._draft_pos.get(i, -1) < m[i] - 2:
                        self._draft_prefill_install(i, hists[i][:m[i] + 1])
                d_cache = _set_cache_index(
                    self._draft_cache,
                    jnp.asarray(np.maximum(m - 1, 0), jnp.int32))
                d_cache, g = self._draft_step(d_cache, jnp.asarray(feed))
                drafts = [g]
                for _ in range(k - 1):
                    d_cache, g = self._draft_step(d_cache, g[:, None])
                    drafts.append(g)
                self._draft_cache = d_cache
                self.telemetry["dispatches_total"].inc(k)
                # ONE [B, k] transfer for the whole proposal matrix
                # instead of k [B] transfers (stack on device first).
                drafted = np.asarray(jnp.stack(drafts, axis=1))
                self.telemetry["transfers_total"].inc()

        return self._verify_and_accept(slots, next_tokens, m, t_last,
                                       drafted)

    def _verify_and_accept(self, slots, next_tokens, m, t_last, drafted):
        """ONE width-(k+1) target verify of `drafted` across all slots,
        then longest-prefix acceptance + bonus, emission, and per-row
        cache_index rollback over rejected positions.  Shared by the
        model-draft and training-free-draft paths."""
        import numpy as np

        from ..models.llama import _set_cache_index

        jnp = self._jnp
        k = self.draft_len
        active = [i for i, r in enumerate(slots) if r is not None]
        with self._device_lock:
            # Target verifies all slots in one width-(k+1) forward.
            verify_tokens = np.concatenate([t_last[:, None], drafted],
                                           axis=1)
            cache = _set_cache_index(
                self._cache, jnp.asarray(np.maximum(m, 0), jnp.int32))
            cache, greedy = self._verify_step(
                cache, jnp.asarray(verify_tokens, dtype=jnp.int32))
            # Publish the post-verify cache BEFORE retirements:
            # _retire_slot rewrites self._cache (block table back to
            # scratch), and a later overwrite from a stale local would
            # undo that.
            self._cache = cache
            self.telemetry["dispatches_total"].inc()
            g_np = np.asarray(greedy)                   # [B, k+1]
            self.telemetry["transfers_total"].inc()
            self.telemetry["ticks_total"].inc()

        # Acceptance + emission per slot (lock released: emit() runs
        # streaming callbacks).
        match = drafted == g_np[:, :-1]
        accepted = np.cumprod(match, axis=1).sum(axis=1)
        self.spec_stats["spec_ticks"] += 1
        carry_idx: List[int] = []
        carry_tok: List[int] = []
        for i in active:
            req = slots[i]
            if req.cancelled.is_set():
                req.done.set()
                slots[i] = None
                self._retire_slot(i)
                continue
            remaining = req.max_new_tokens - len(req.output)
            self.spec_stats["drafted"] += min(k, remaining)
            j = int(accepted[i])
            emit = g_np[i, :j + 1]
            take = int(min(len(emit), remaining))
            if req.stop_tokens:
                # Truncate at the first stop token (emitted inclusive):
                # tokens past it were speculated beyond the sequence end.
                for pos in range(take):
                    if int(emit[pos]) in req.stop_tokens:
                        take = pos + 1
                        break
            self.spec_stats["accepted_drafts"] += min(j, take)
            for tok in emit[:take]:
                req.emit(int(tok))
            if self._draft_model is not None:
                # Draft coverage: positions m+1..m+min(j, take) hold
                # accepted (committed) drafts; the bonus slot is
                # garbage.  Clamp to draft_len-1: on a full-acceptance
                # round the draft's last proposal is never fed back, so
                # the highest position it actually wrote is
                # m+draft_len-1.  (Training-free drafts keep no cache.)
                self._draft_pos[i] = int(
                    m[i] + min(j, take, self.draft_len - 1))
            m[i] += take
            if req.finished:
                req.done.set()
                slots[i] = None
                self._retire_slot(i)
            else:
                # Keep the plain-tick invariant for a possible fallback
                # tick: next_tokens carries the newest emitted token.
                # Staged host-side and scattered once below — one
                # dispatch per round instead of one per surviving slot.
                carry_idx.append(i)
                carry_tok.append(int(req.output[-1]))
        if carry_idx:
            next_tokens = self._padded_scatter(next_tokens, carry_idx,
                                               carry_tok)

        # Roll every row's write position back over rejected slots.
        self._cache = _set_cache_index(
            self._cache, jnp.asarray(np.maximum(m, 0), jnp.int32))
        return next_tokens

    # -- paged-pool plumbing ----------------------------------------------
    def _blocks_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.page_size)

    def _chain_key(self, parent: Optional[int], tokens: List[int],
                   j: int):
        """Content key of prompt block j: (parent pool block id, that
        page's tokens).  The parent id stands in for the whole prefix —
        O(page) per block instead of O(prefix) — and is unambiguous
        while the parent is registered; leaf-first eviction (children
        before parents) keeps stale parent ids from ever matching."""
        page = self.page_size
        return (parent, tuple(tokens[j * page:(j + 1) * page]))

    def _match_prefix(self, tokens: List[int]) -> List[int]:
        """Longest chain of cached full prompt blocks, capped so at
        least one prompt token is left to prefill (its logits seed the
        first sampled token)."""
        if not self._prefix_cache:
            return []
        hits: List[int] = []
        parent: Optional[int] = None
        max_full = (len(tokens) - 1) // self.page_size
        self.prefix_stats["lookups"] += 1
        self.telemetry["prefix_lookups"].inc()
        for j in range(max_full):
            blk = self._registry.get(self._chain_key(parent, tokens, j))
            if blk is None:
                break
            hits.append(blk)
            parent = blk
        return hits

    def _alloc_blocks(self, slot: int, total_tokens: int,
                      tokens: Optional[List[int]] = None) -> bool:
        """Reserve the slot's block budget (prompt + max new tokens,
        known at admission) or decline.  Cached prefix blocks satisfy
        the head of the budget; refcount-0 cached blocks are evicted
        LRU to make room before declining."""
        shared = self._match_prefix(tokens) if tokens else []
        need = self._blocks_needed(total_tokens) - len(shared)
        shared_set = set(shared)
        if need > len(self._free_blocks) + sum(
                1 for b, m in self._block_meta.items()
                if m["refs"] == 0 and b not in shared_set):
            # Infeasible even after full eviction: decline WITHOUT
            # evicting, so a too-large deferred request does not wipe
            # the reusable prefix cache for nothing.  (Every refs-0
            # block is eventually reachable by leaf-first eviction:
            # children always have refs <= their parent's.)
            return False
        while len(self._free_blocks) < need:
            if not self._evict_one(shared_set):
                return False
        self._prefix_clock += 1
        for blk in shared:
            meta = self._block_meta[blk]
            meta["refs"] += 1
            meta["last"] = self._prefix_clock
        self.prefix_stats["hit_blocks"] += len(shared)
        self.prefix_stats["hit_tokens"] += len(shared) * self.page_size
        if shared:
            self.telemetry["prefix_hit_blocks"].inc(len(shared))
            self.telemetry["prefix_hit_tokens"].inc(
                len(shared) * self.page_size)
        priv = [self._free_blocks.pop() for _ in range(need)]
        self._slot_blocks[slot] = shared + priv
        self._slot_shared[slot] = len(shared)
        return True

    def _evict_one(self, protect: set) -> bool:
        """Evict ONE cached block back to the free list (leaf-first
        LRU): a block is evictable once no slot references it AND no
        registered child chains through it (children always have
        refs <= parent's, so freeing leaves unlocks parents on
        subsequent passes).  Scheduler thread only."""
        victim = min(
            (b for b, m in self._block_meta.items()
             if m["refs"] == 0 and not m["children"]
             and b not in protect),
            key=lambda b: self._block_meta[b]["last"], default=None)
        if victim is None:
            return False
        meta = self._block_meta.pop(victim)
        del self._registry[meta["key"]]
        self._block_digest.pop(victim, None)
        if meta["parent"] is not None:
            parent_meta = self._block_meta.get(meta["parent"])
            if parent_meta is not None:
                parent_meta["children"].discard(victim)
        self._free_blocks.append(victim)
        self.prefix_stats["evicted"] += 1
        self.telemetry["prefix_evicted"].inc()
        return True

    def _register_blocks(self, slot: int, tokens: List[int]) -> None:
        """Content-address this slot's full prompt blocks for future
        prefix hits (the slot itself holds one reference on each)."""
        if not self._prefix_cache:
            return
        blocks = self._slot_blocks[slot]
        parent = (blocks[self._slot_shared[slot] - 1]
                  if self._slot_shared[slot] else None)
        for j in range(self._slot_shared[slot],
                       len(tokens) // self.page_size):
            key = self._chain_key(parent, tokens, j)
            existing = self._registry.get(key)
            if existing is not None:
                # concurrent duplicate; keep the first, chain onward
                # through it so later blocks of THIS prompt still
                # register under the canonical parent
                parent = existing
                continue
            blk = blocks[j]
            self._registry[key] = blk
            self._block_meta[blk] = {"key": key, "refs": 1,
                                     "last": self._prefix_clock,
                                     "parent": parent, "children": set()}
            self._block_digest[blk] = _page_digest(
                "" if parent is None
                else self._block_digest.get(parent, ""),
                tokens[j * self.page_size:(j + 1) * self.page_size])
            if parent is not None and parent in self._block_meta:
                self._block_meta[parent]["children"].add(blk)
            parent = blk

    def prefix_digest(self) -> List[str]:
        """The replica's advertised prefix-cache hit index: the chain
        digests (prefix_page_digests form) of every registered prompt
        block.  Read from HTTP threads while the scheduler mutates the
        underlying dict — retry the snapshot on a concurrent resize."""
        if self.page_size <= 0 or not self._prefix_cache:
            return []
        for _ in range(8):
            try:
                return sorted(self._block_digest.values())
            except RuntimeError:
                continue
        return []

    # -- disaggregated KV-page transfer (serving/kv_transfer.py) -----------
    def free_blocks(self) -> int:
        """Pool blocks not reserved by any live slot (cached refcount-0
        blocks count as free: they are evictable on demand).  Read from
        HTTP threads; a momentarily stale value only skews routing, so
        no lock is taken."""
        if self.page_size <= 0:
            return 0
        for _ in range(8):
            try:
                cached = sum(1 for m in self._block_meta.values()
                             if m["refs"] == 0)
                return len(self._free_blocks) + cached
            except RuntimeError:
                continue
        return len(self._free_blocks)

    def export_kv_pages(self, digests: List[str]) -> List[dict]:
        """Snapshot the requested prefix-cache pages for transfer to a
        decode replica: for each chain digest this replica has
        registered, the page's tokens, parent digest, and raw pool
        K/V leaves (numpy, host-side).

        Read-only over the immutable cache tree, so it is safe from
        HTTP threads while the scheduler ticks; a block that is evicted
        and reallocated mid-export is caught by re-checking its digest
        after the leaf gather and dropped (best-effort protocol — a
        missing page just means the importer prefills that span)."""
        import numpy as np
        if self.page_size <= 0:
            raise ValueError(
                "export_kv_pages requires the paged KV cache "
                "(page_size > 0)")
        cache = self._cache  # immutable tree; ticks swap the reference
        by_digest: dict = {}
        for _ in range(8):
            try:
                by_digest = {d: b
                             for b, d in list(self._block_digest.items())}
                break
            except RuntimeError:
                continue

        jnp = self._jnp
        entries: List[tuple] = []  # (digest, blk, parent, tokens)
        for digest in digests:
            blk = by_digest.get(digest)
            if blk is None:
                continue
            meta = self._block_meta.get(blk)
            if meta is None:
                continue
            parent_blk = meta["parent"]
            parent = ("" if parent_blk is None
                      else self._block_digest.get(parent_blk, ""))
            entries.append((digest, blk, parent,
                            [int(t) for t in meta["key"][1]]))

        leaf_paths: List[tuple] = []

        def walk(node, prefix):
            if "pool_key" in node:
                for name in node:
                    if name.startswith("pool_"):
                        leaf_paths.append((prefix + name, node[name]))
                return
            for k in node:
                walk(node[k], prefix + k + "/")

        walk(cache, "")

        pages: List[dict] = []
        for off in range(0, len(entries), _EXPORT_WAVE_WIDTH):
            wave = entries[off:off + _EXPORT_WAVE_WIDTH]
            blks = [e[1] for e in wave]
            # Batched fixed-width gather, mirroring the import-side
            # scatter: padding to the wave width keeps it at ONE
            # compiled program per leaf shape, and batching keeps it at
            # a few dispatches per wave — a per-page eager gather costs
            # two GIL-contended dispatches per page, which at 32k
            # tokens (2k pages) is minutes of export under live decode
            # traffic, starving the dispatching router past its
            # upstream timeout.
            pad = blks + [blks[-1]] * (_EXPORT_WAVE_WIDTH - len(blks))
            idx = jnp.asarray(pad)
            rows = {path: np.asarray(leaf[idx])
                    for path, leaf in leaf_paths}
            for i, (digest, blk, parent, tokens) in enumerate(wave):
                if self._block_digest.get(blk) != digest:
                    continue  # evicted/reallocated mid-gather: drop
                pages.append({"digest": digest, "parent": parent,
                              "tokens": tokens,
                              "leaves": {path: arr[i]
                                         for path, arr in rows.items()}})
                self.telemetry["kv_pages_exported"].inc()
        return pages

    def import_kv_pages(self, pages: List[dict],
                        timeout: float = 30.0) -> dict:
        """Install transferred KV pages into this replica's pool and
        registry (decode-replica side).  Called from HTTP threads: the
        pages are queued for the scheduler thread — the only thread
        allowed to mutate the pool — and this call blocks until that
        import wave completes.  Returns per-page accounting
        ``{"imported", "deduped", "rejected"}``."""
        if self.page_size <= 0:
            raise ValueError(
                "import_kv_pages requires the paged KV cache "
                "(page_size > 0)")
        if self._stop.is_set():
            raise self._shutdown_error()
        result = {"imported": 0, "deduped": 0, "rejected": 0}
        done = threading.Event()
        with self._kv_imports_lock:
            self._kv_imports.append((pages, result, done))
        self._queue.poke()
        if not done.wait(timeout):
            raise TimeoutError("KV-page import timed out")
        if self._stop.is_set() and self.fatal_error is not None:
            raise self._shutdown_error()
        return result

    def _drain_kv_imports(self) -> None:
        """Scheduler thread: install every queued KV-page wave.  Pages
        arrive parent-first (chain order); each is digest-verified and
        registered exactly like a locally-prefilled block, then the
        whole wave's K/V data lands in ONE gathered ``.at[blks].set``
        per pool leaf — a per-page functional update would copy the
        entire pool per page, turning a long-prompt transfer (32k
        tokens = 2k pages) into gigabytes of memcpy.  Staged blocks are
        unreadable-by-construction until the scatter lands: prefix
        matching happens on this same thread, strictly after this
        method returns.  Best-effort: a page whose parent is missing or
        whose digest fails verification is rejected (its descendants
        will be too), and pool exhaustion rejects rather than stealing
        blocks from live slots."""
        while True:
            with self._kv_imports_lock:
                if not self._kv_imports:
                    return
                pages, result, done = self._kv_imports.popleft()
            protected: set = set()
            staged: List[tuple] = []  # (blk, wire leaves dict)
            try:
                shapes = self._pool_leaf_shapes()
                for page in pages:
                    verdict, blk = self._stage_import(page, protected,
                                                      shapes)
                    result[verdict] += 1
                    if verdict == "imported":
                        staged.append((blk, page.get("leaves", {})))
                        self.telemetry["kv_pages_imported"].inc()
                self._scatter_staged(staged)
            except Exception as exc:
                # Import shares the cache tree with decode ticks; a
                # failure here (device error mid-scatter) poisons it
                # the same way a failed donated prefill does — fail the
                # batcher loudly, never serve from a half-written pool.
                self._tick_fatal(exc, "kv-import")
                return
            finally:
                done.set()

    def _pool_leaf_shapes(self) -> dict:
        """Leaf path -> per-block shape of every pool_* array (a
        shape-only walk of the cache tree; no data touched)."""
        shapes: dict = {}

        def walk(node, prefix):
            if "pool_key" in node:
                for name, leaf in node.items():
                    if name.startswith("pool_"):
                        shapes[prefix + name] = tuple(leaf.shape[1:])
                return
            for k in node:
                walk(node[k], prefix + k + "/")

        walk(self._cache, "")
        return shapes

    def _stage_import(self, page: dict, protected: set,
                      shapes: dict) -> tuple:
        """Verify one transferred page and claim a pool block for it.
        Returns ``(verdict, blk)``; on "imported" the block is
        REGISTERED (so later pages in the wave can chain through it as
        a parent) but its data is not yet in the pool — the caller
        batch-scatters every staged block before the scheduler does
        anything else."""
        import numpy as np
        tokens = [int(t) for t in page.get("tokens", ())]
        digest = page.get("digest", "")
        parent_digest = page.get("parent", "")
        if (len(tokens) != self.page_size
                or _page_digest(parent_digest, tokens) != digest):
            self.telemetry["kv_import_rejected"].labels(
                "digest_mismatch").inc()
            return "rejected", None
        # Parent chain: root pages have parent ""; others must chain
        # through an already-registered block (shipped parent-first or
        # already cached here).
        parent_blk: Optional[int] = None
        if parent_digest:
            for b, d in self._block_digest.items():
                if d == parent_digest:
                    parent_blk = b
                    break
            if parent_blk is None:
                self.telemetry["kv_import_rejected"].labels(
                    "missing_parent").inc()
                return "rejected", None
        key = (parent_blk, tuple(tokens))
        if key in self._registry or digest in self._block_digest.values():
            return "deduped", None
        leaves = page.get("leaves", {})
        for path, shape in shapes.items():
            arr = leaves.get(path)
            if arr is None or tuple(np.shape(arr)) != shape:
                self.telemetry["kv_import_rejected"].labels(
                    "shape").inc()
                return "rejected", None
        if not self._free_blocks and not self._evict_one(protected):
            self.telemetry["kv_import_rejected"].labels(
                "pool_exhausted").inc()
            return "rejected", None
        blk = self._free_blocks.pop()
        self._prefix_clock += 1
        self._registry[key] = blk
        self._block_meta[blk] = {"key": key, "refs": 0,
                                 "last": self._prefix_clock,
                                 "parent": parent_blk, "children": set()}
        self._block_digest[blk] = digest
        if parent_blk is not None and parent_blk in self._block_meta:
            self._block_meta[parent_blk]["children"].add(blk)
        protected.add(blk)
        return "imported", blk

    def _scatter_staged(self, staged: List[tuple]) -> None:
        """Land an import wave's K/V data: one gathered functional
        update per pool leaf (every staged page was shape-verified)."""
        if not staged:
            return
        import numpy as np
        jnp = self._jnp
        # Pad every wave to the FIXED import width by repeating the
        # last entry (same index, same values — the duplicate write is
        # idempotent), chunking oversized batches first.
        # `.at[blks].set` compiles one XLA program per distinct wave
        # width; unpadded, every transfer's unique page count would
        # compile a fresh scatter under the device lock — a compile
        # storm that stalls decode for seconds per import.  Fixed
        # width = exactly one program per leaf for the replica's
        # lifetime.  The width is deliberately NARROW: the device lock
        # is dropped between waves, so a live decode stream on this
        # replica stalls for at most one narrow scatter, not one full
        # 64-page transfer batch (which measurably moves decode p99
        # during a 32k-token import).
        for off in range(0, len(staged), _IMPORT_WAVE_WIDTH):
            wave = staged[off:off + _IMPORT_WAVE_WIDTH]
            wave = wave + [wave[-1]] * (_IMPORT_WAVE_WIDTH - len(wave))
            blks = jnp.asarray([blk for blk, _ in wave])

            def scatter(node, prefix):
                if "pool_key" in node:
                    out = dict(node)
                    for name, leaf in node.items():
                        if not name.startswith("pool_"):
                            continue
                        stack = np.stack([lv[prefix + name]
                                          for _, lv in wave])
                        out[name] = leaf.at[blks].set(
                            jnp.asarray(stack).astype(leaf.dtype))
                    return out
                return {k: scatter(node[k], prefix + k + "/")
                        for k in node}

            with self._device_lock:
                self._cache = scatter(self._cache, "")

    def _retire_slot(self, slot: int) -> None:
        """Drop the slot's block references and point its table back at
        scratch block 0, so the still-ticking inactive row cannot write
        into blocks about to be reallocated.  Registered blocks stay in
        the prefix cache at refcount-1 (evicted only under pressure);
        unregistered ones return to the free list."""
        if self._draft_model is not None:
            # Draft coverage is per-slot state too; EVERY retirement
            # path funnels here (plain tick, spec tick, admission,
            # cancellation), so this is the one cleanup point.
            self._draft_pos.pop(slot, None)
        if self.page_size <= 0:
            return
        blocks = self._slot_blocks.pop(slot, None)
        self._slot_shared.pop(slot, None)
        if not blocks:
            return
        for blk in blocks:
            meta = self._block_meta.get(blk)
            if meta is not None:
                meta["refs"] -= 1
            else:
                self._free_blocks.append(blk)
        self._retire_count += 1
        from ..models.llama import replace_cache_leaf
        self._cache = replace_cache_leaf(
            self._cache, "block_table", lambda t: t.at[slot].set(0))

    def _table_row(self, blocks: List[int]):
        """Slot block-table row: allocated blocks in logical order,
        unmapped tail entries at scratch block 0."""
        jnp = self._jnp
        row = jnp.zeros((self._blocks_per_row,), jnp.int32)
        return row.at[:len(blocks)].set(jnp.asarray(blocks, jnp.int32))

    def _install_paged(self, slot: int, row_cache, length: int):
        """Scatter a batch-1 dense prefill row into the slot's allocated
        pool blocks and publish its block table."""
        jnp = self._jnp
        blocks = self._slot_blocks[slot]
        barr = jnp.asarray(blocks, jnp.int32)
        span = len(blocks) * self.page_size
        table_row = self._table_row(blocks)

        def rec(dst, src):
            if "pool_key" in dst:
                from ..models.llama import quantize_kv

                out = dict(dst)
                int8 = "pool_key_scale" in dst
                for pool, dense in (("pool_key", "cached_key"),
                                    ("pool_value", "cached_value")):
                    seq = src[dense][0]          # [L, KH, D]
                    take = min(seq.shape[0], span)
                    chunk = jnp.zeros((span,) + seq.shape[1:], seq.dtype)
                    chunk = chunk.at[:take].set(seq[:take])
                    if int8:
                        # Prefill ran on the dense bf16 layout; the
                        # paged pool stores int8 + per-token scales.
                        q8, sc = quantize_kv(chunk)
                        out[pool] = dst[pool].at[barr].set(
                            q8.reshape(len(blocks), self.page_size,
                                       *seq.shape[1:]))
                        out[pool + "_scale"] = \
                            dst[pool + "_scale"].at[barr].set(
                                sc.reshape(len(blocks), self.page_size,
                                           seq.shape[1]))
                    else:
                        out[pool] = dst[pool].at[barr].set(
                            chunk.reshape(len(blocks), self.page_size,
                                          *seq.shape[1:]))
                out["block_table"] = dst["block_table"].at[slot].set(
                    table_row)
                out["cache_index"] = dst["cache_index"].at[slot].set(
                    jnp.int32(length))
                return out
            return {k: rec(dst[k], src[k]) for k in dst}
        self._cache = rec(self._cache, row_cache)

    # -- prefix-cached suffix prefill --------------------------------------
    def _suffix_fn(self, width: int):
        """Jitted per suffix-width bucket: batch-1 apply of the PAGED
        model on the prompt suffix.  The batch-1 view aliases the shared
        pools and maps the slot's table (shared prefix + private blocks)
        with cache_index = shared_len, so the multi-token paged decode
        branch attends across the cached prefix while scattering suffix
        K/V only into the private blocks (every write position is
        >= shared_len)."""
        fn = self._suffix_prefill_cache.get(width)
        if fn is None:
            import functools

            jax, jnp = self._jax, self._jnp
            params = {"params": self.variables["params"]}
            decode_model = self._decode_model

            # Donate the cache: the caller always replaces self._cache
            # with the returned tree, and without donation every
            # suffix/chunk apply holds a SECOND copy of the whole KV
            # pool — at 7B tp1 that extra ~2.2 GB is the difference
            # between the chunked-prefill fits verdict
            # (BENCH_LLAMA_SERVE.json, compiled WITH donation) holding
            # on hardware or OOMing.
            @functools.partial(jax.jit, donate_argnums=(0,))
            def suffix_prefill(cache, table_row, shared_len, padded,
                               length, temp, top_p, key, top_k):
                def to_b1(node):
                    if "pool_key" in node:
                        return {**node, "block_table": table_row[None],
                                "cache_index": shared_len[None]}
                    return {k: to_b1(v) for k, v in node.items()}

                logits, state = decode_model.apply(
                    {**params, "cache": to_b1(cache)}, padded,
                    decode=True, mutable=["cache"])

                def back(dst, src):
                    if "pool_key" in dst:
                        out = {**dst, "pool_key": src["pool_key"],
                               "pool_value": src["pool_value"]}
                        # int8 pools: the suffix apply also wrote the
                        # per-token dequant scales — dropping them would
                        # leave stale zeros and silently zero the K/V.
                        for sc in ("pool_key_scale", "pool_value_scale"):
                            if sc in src:
                                out[sc] = src[sc]
                        return out
                    return {k: back(dst[k], src[k]) for k in dst}

                nxt, key = _select_rows(logits[:, length - 1],
                                        temp[None], top_p[None],
                                        key[None], top_k[None])
                return (back(cache, state["cache"]),
                        nxt[0].astype(jnp.int32), key[0])

            fn = self._suffix_prefill_cache[width] = suffix_prefill
        return fn

    def _prefill_suffix(self, slot: int, tokens: List[int], sample_args):
        """Prefill only the uncached prompt suffix into `slot` (the
        shared prefix is already resident in the pool), publish the
        slot's table, and sample the first token."""
        jnp = self._jnp
        shared_len = self._slot_shared[slot] * self.page_size
        suffix = tokens[shared_len:]
        if 0 < self._prefill_chunk < len(suffix):
            return self._prefill_chunked(slot, tokens, sample_args,
                                         start_len=shared_len)
        blocks = self._slot_blocks[slot]
        width = _bucket(len(suffix), self._max_seq_len)
        table_row = self._table_row(blocks)
        padded = jnp.asarray([suffix + [0] * (width - len(suffix))],
                             jnp.int32)
        temp, top_p, key, top_k = sample_args
        new_cache, first, key1 = self._suffix_fn(width)(
            self._cache, table_row, jnp.int32(shared_len), padded,
            len(suffix), temp, top_p, key, top_k)
        from ..models.llama import replace_cache_leaf
        new_cache = replace_cache_leaf(
            new_cache, "block_table", lambda t: t.at[slot].set(table_row))
        self._cache = replace_cache_leaf(
            new_cache, "cache_index",
            lambda t: t.at[slot].set(jnp.int32(len(tokens))))
        return first, key1

    def _prefill_chunked(self, slot: int, tokens: List[int], sample_args,
                         start_len: int = 0):
        """Chunked paged prefill: drive the prompt (or its uncached
        suffix, ``start_len`` > 0) through the paged model in fixed-width
        batch-1 applies sharing the pool — each chunk is one `_suffix_fn`
        call at width=prefill_chunk, so ONE compiled program serves every
        prompt length and peak activation memory is O(chunk).

        Tail padding writes junk K/V at positions past the prompt; those
        positions are masked until the decode loop overwrites them (the
        same stale-K/V contract every rollback path relies on).  The
        sampling key is NOT threaded through chunks: only the final
        chunk's sample is consumed, with the original key — so the first
        emitted token is bit-identical to the unchunked paths'."""
        jnp = self._jnp
        chunk = self._prefill_chunk
        blocks = self._slot_blocks[slot]
        table_row = self._table_row(blocks)
        suffix = tokens[start_len:]
        temp, top_p, key, top_k = sample_args
        cache = self._cache
        pos = start_len
        first = key1 = None
        for off in range(0, len(suffix), chunk):
            piece = suffix[off:off + chunk]
            padded = jnp.asarray([piece + [0] * (chunk - len(piece))],
                                 jnp.int32)
            cache, first, key1 = self._suffix_fn(chunk)(
                cache, table_row, jnp.int32(pos), padded, len(piece),
                temp, top_p, key, top_k)
            pos += len(piece)
        from ..models.llama import replace_cache_leaf
        cache = replace_cache_leaf(
            cache, "block_table", lambda t: t.at[slot].set(table_row))
        self._cache = replace_cache_leaf(
            cache, "cache_index",
            lambda t: t.at[slot].set(jnp.int32(len(tokens))))
        return first, key1

    # -- public API --------------------------------------------------------
    def _headroom(self, temperature: float) -> int:
        """Cache positions past prompt + max_new a verify round may
        touch (the last round can draft past the needed tokens).  Only
        greedy requests ever speculate, so sampling requests are not
        charged for it."""
        if (self._draft_model is None and self._draft_strategy is None) \
                or temperature > 0.0:
            return 0
        return self.draft_len + 1

    def _enqueue(self, tokens, max_new_tokens, temperature, top_p, seed,
                 on_token=None, stop_tokens=(), top_k=0,
                 trace_ctx=None) -> _Request:
        headroom = self._headroom(temperature)
        if len(tokens) + max_new_tokens + headroom > self._max_seq_len:
            raise ValueError(
                f"prompt ({len(tokens)}) + max_new_tokens "
                f"({max_new_tokens}) + speculation headroom "
                f"({headroom}) exceeds max_seq_len "
                f"{self._max_seq_len}")
        if self.page_size > 0:
            need = self._blocks_needed(
                len(tokens) + max_new_tokens + headroom)
            if need > self._total_blocks:
                raise ValueError(
                    f"request needs {need} cache blocks but the pool "
                    f"only has {self._total_blocks} (cache_blocks too "
                    f"small)")
        if self._stop.is_set():
            raise self._shutdown_error()
        if seed is None:
            import random
            seed = random.getrandbits(31)
        req = _Request(list(map(int, tokens)), max_new_tokens,
                       temperature=float(temperature), top_p=float(top_p),
                       top_k=int(top_k), seed=int(seed),
                       on_token=on_token,
                       stop_tokens=frozenset(map(int, stop_tokens)),
                       metrics=self.telemetry,
                       submitted_at=time.perf_counter(),
                       trace_ctx=trace_ctx,
                       submitted_wall=time.time())
        self._queue.put(req)
        # The fatal/stop path is asynchronous: the scheduler may have
        # stopped and drained between the _stop check above and this
        # put, leaving req stranded (the client would block its full
        # timeout).  Re-check and fail it here; racing the drain is
        # harmless (both set the same terminal state).
        if self._stop.is_set():
            req.error = self._shutdown_error()
            req.done.set()
            raise req.error
        self.telemetry["queue_depth"].set(self._queue.qsize())
        return req

    def submit(self, tokens: List[int], max_new_tokens: int,
               timeout: float = 300.0, temperature: float = 0.0,
               top_p: float = 1.0, seed: Optional[int] = None,
               stop_tokens=(), top_k: int = 0,
               trace_ctx=None) -> List[int]:
        if max_new_tokens <= 0:
            return []  # match generate()'s [B, 0] semantics
        req = self._enqueue(tokens, max_new_tokens, temperature, top_p,
                            seed, stop_tokens=stop_tokens, top_k=top_k,
                            trace_ctx=trace_ctx)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return req.output

    def submit_iter(self, tokens: List[int], max_new_tokens: int,
                    timeout: float = 300.0, temperature: float = 0.0,
                    top_p: float = 1.0, seed: Optional[int] = None,
                    stop_tokens=(), top_k: int = 0, trace_ctx=None):
        """Streaming submit: yields each generated id as the batcher
        produces it (tokens from this slot's decode ticks)."""
        if max_new_tokens <= 0:
            return
        sentinel = object()
        out: "queue.Queue" = queue.Queue()
        req = self._enqueue(tokens, max_new_tokens, temperature, top_p,
                            seed, on_token=out.put,
                            stop_tokens=stop_tokens, top_k=top_k,
                            trace_ctx=trace_ctx)
        threading.Thread(
            target=lambda: (req.done.wait(timeout), out.put(sentinel)),
            daemon=True).start()
        try:
            while True:
                item = out.get(timeout=timeout)
                if item is sentinel:
                    break
                yield item
        finally:
            # Closed early (client disconnect -> GeneratorExit): cancel
            # so the batcher frees the slot instead of decoding for
            # nobody.
            req.cancelled.set()
        if req.error is not None:
            raise req.error
        if not req.done.is_set():
            raise TimeoutError("generation timed out")

    def _shutdown_error(self) -> RuntimeError:
        if self.fatal_error is not None:
            return RuntimeError(
                f"batcher failed fatally during "
                f"{self._fatal_phase or 'admission'} (see the "
                f"batcher-fatal debug bundle): {self.fatal_error!r}")
        return RuntimeError("batcher stopped")

    def start(self) -> "ContinuousBatcher":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- scheduler loop ----------------------------------------------------
    def _tick_fatal(self, exc: BaseException, phase: str, **extra) -> None:
        """The scheduler cannot continue (donated prefill consumed the
        KV cache, a device error mid-dispatch, a poisoned fetch, or a
        streaming callback blowing up mid-emission): fail the whole
        batcher loudly — black-box bundle FIRST, so when submit()
        raises, the evidence (phase, pipeline depth, last dispatched /
        fetched tick) is already on disk."""
        self.fatal_error = exc
        self._fatal_phase = phase
        self._stop.set()
        from ..telemetry import flight
        flight.record(
            "serving", "fatal_error", phase=phase,
            error=f"{type(exc).__name__}: {exc}",
            queue_depth=self._queue.qsize(),
            pipeline_depth=self.ticks_dispatched - self.ticks_fetched,
            last_dispatched_tick=self.ticks_dispatched,
            last_fetched_tick=self.ticks_fetched, **extra)
        flight.dump_bundle(
            "batcher-fatal",
            registry=self.telemetry["registry"],
            once_key=f"batcher-fatal-{id(self)}")

    def _loop(self) -> None:
        import numpy as np

        jax, jnp = self._jax, self._jnp
        tm = self.telemetry
        slots: List[Optional[_Request]] = [None] * self.max_slots
        # Per-slot sampling state lives in host-side numpy mirrors;
        # admissions write the mirrors and each wave uploads them ONCE
        # (one H2D per array) instead of chaining five per-request
        # .at[i].set dispatches.
        h_temps = np.zeros((self.max_slots,), np.float32)
        h_top_ps = np.ones((self.max_slots,), np.float32)
        h_top_ks = np.zeros((self.max_slots,), np.int32)
        temps = jnp.asarray(h_temps)
        top_ps = jnp.asarray(h_top_ps)
        top_ks = jnp.asarray(h_top_ks)
        keys = jnp.zeros((self.max_slots, 2), jnp.uint32)
        # Tokens feeding the NEXT dispatched step (device-resident; the
        # previous step's output with admission firsts scattered in).
        next_tokens = jnp.zeros((self.max_slots,), jnp.int32)
        # The in-flight decode step: (on-device token array, snapshot of
        # slots at dispatch time).  At most one step is outstanding.
        pending: Optional[tuple] = None
        # A request that could not get cache blocks waits here (FIFO
        # order preserved) until retirements free enough of the pool.
        deferred: Optional[_Request] = None
        deferred_mark = -1

        def dispatch_step():
            """Dispatch one decode step across every slot (JAX async:
            returns immediately with on-device futures).  Inactive
            slots decode garbage into their own rows; admit resets
            them.  Returns the (out, slots-snapshot) pipeline record."""
            nonlocal next_tokens, keys
            with self._device_lock:
                if self._decode_latency:
                    time.sleep(self._decode_latency)
                self._cache, out, keys = self._decode_step(
                    self._cache, next_tokens, temps, top_ps, keys,
                    top_ks)
            next_tokens = out
            self.ticks_dispatched += 1
            tm["dispatches_total"].inc()
            tm["pipeline_depth"].set(
                self.ticks_dispatched - self.ticks_fetched)
            return out, list(slots)

        def process_step(step) -> None:
            """Fetch the step's whole token array in ONE device→host
            transfer, then emit / stop-check / retire.  Lanes whose
            request retired or was replaced after the dispatch hold
            overrun tokens — discarded here, which is what keeps
            pipelined streams byte-identical to the serialized loop's."""
            out, snap = step
            live = [i for i, req in enumerate(snap)
                    if req is not None and req is slots[i]]
            self.ticks_fetched += 1
            tm["pipeline_depth"].set(
                self.ticks_dispatched - self.ticks_fetched)
            if not live:
                return  # pure-overrun step (everything retired since
                        # dispatch): drop it without paying a transfer
            if self._per_slot_fetch:
                # Reference cost shape (bench before-capture only): one
                # blocking transfer per live slot.
                out_np = {i: int(out[i]) for i in live}
                tm["transfers_total"].inc(len(live))
            else:
                out_np = np.asarray(out)
                tm["transfers_total"].inc()
            tm["ticks_total"].inc()
            # Counted at processing, not dispatch: dropped overrun
            # steps emit nothing and must not skew spec/plain ratios.
            self.spec_stats["plain_ticks"] += 1
            for i in live:
                req = snap[i]
                if req.cancelled.is_set():
                    # Covers cancellation landing between dispatch and
                    # fetch: the token is dropped, the slot freed.
                    req.done.set()
                    slots[i] = None
                    self._retire_slot(i)
                    continue
                req.emit(int(out_np[i]))
                if req.finished:
                    req.done.set()
                    slots[i] = None
                    self._retire_slot(i)

        while not self._stop.is_set():
            # Transferred KV pages (disaggregated serving) install
            # before this tick's admissions, so a /generate that raced
            # its own page push still hits the prefix cache.
            if self.page_size > 0 and self._kv_imports:
                self._drain_kv_imports()
                if self._stop.is_set():
                    break
            # Pipelined dispatch-ahead: enqueue step k+1 from step k's
            # still-on-device tokens BEFORE fetching step k, so the
            # device computes k+1 while the host runs step k's
            # emission/retirement and the next admission wave.  Any
            # lane those host decisions invalidate is an overrun token
            # process_step() discards next iteration.
            try:
                ahead = None
                if (pending is not None and self.pipelined
                        and any(s is not None for s in slots)):
                    ahead = dispatch_step()
            except Exception as exc:
                self._tick_fatal(exc, "dispatch")
                break
            try:
                if pending is not None:
                    process_step(pending)
                pending = ahead
            except Exception as exc:
                self._tick_fatal(exc, "fetch")
                break

            # Admit new requests into free slots; per-slot state is
            # staged host-side and uploaded once after the wave.
            admitted = False
            wave_idx: List[int] = []
            wave_first: List[int] = []
            wave_keys: list = []
            for i in range(self.max_slots):
                if slots[i] is not None:
                    continue
                if deferred is not None:
                    if deferred.cancelled.is_set():
                        # Reap a dead deferred request immediately: the
                        # no-retirement gate below would otherwise pin
                        # it (and stall all later FIFO requests) until
                        # some unrelated retirement bumps _retire_count.
                        deferred.done.set()
                        deferred = None
                        continue
                    if (self.page_size > 0
                            and deferred_mark == self._retire_count):
                        # Nothing retired since the failed allocation:
                        # the (prefix-match + eviction-scan) retry
                        # cannot succeed, so don't burn it every tick.
                        break
                    req, deferred = deferred, None
                else:
                    try:
                        req = self._queue.get_nowait()
                    except queue.Empty:
                        break
                if req.cancelled.is_set():
                    # A dead client's request must not reserve blocks or
                    # burn a prefill (deferral windows are unbounded
                    # under an oversubscribed pool).
                    req.done.set()
                    continue
                if self.page_size > 0 and not self._alloc_blocks(
                        i, len(req.tokens) + req.max_new_tokens
                        + self._headroom(req.temperature),
                        tokens=req.tokens):
                    deferred = req  # pool exhausted; retry after retires
                    deferred_mark = self._retire_count
                    req.was_deferred = True
                    break
                req.admitted_at = time.perf_counter()
                tm["queue_wait_seconds"].labels(
                    "deferred" if req.was_deferred else "direct").observe(
                        req.admitted_at - req.submitted_at)
                donated = False
                try:
                    key0 = jax.random.fold_in(
                        jax.random.PRNGKey(req.seed), len(req.tokens))
                    sample_args = (jnp.float32(req.temperature),
                                   jnp.float32(req.top_p), key0,
                                   jnp.int32(req.top_k))
                    shared = (self._slot_shared.get(i, 0)
                              if self.page_size > 0 else 0)
                    with self._device_lock:
                        if self._prefill_token_latency:
                            # Injected prefill occupancy scales with the
                            # tokens actually prefilled — a prefix hit
                            # pays only for its suffix, which is what
                            # fleet routing must be able to observe.
                            time.sleep(self._prefill_token_latency
                                       * max(0, len(req.tokens)
                                             - shared * self.page_size))
                        if shared > 0:
                            # _suffix_fn donates self._cache; from here
                            # a failure is NOT slot-local (see below).
                            donated = True
                            first, key1 = self._prefill_suffix(
                                i, req.tokens, sample_args)
                        elif 0 < self._prefill_chunk < len(req.tokens):
                            donated = True
                            first, key1 = self._prefill_chunked(
                                i, req.tokens, sample_args)
                        else:
                            row_cache, first, key1 = self._prefill(
                                req.tokens, sample_args)
                            self._install(i, row_cache, len(req.tokens))
                        if (self._draft_model is not None
                                and req.temperature <= 0.0):
                            # Sampling slots never speculate, so their
                            # draft rows can stay garbage.
                            self._draft_prefill_install(i, req.tokens)
                    if self.page_size > 0:
                        self._register_blocks(i, req.tokens)
                    first_i = int(first)
                    req.emit(first_i)
                    if req.finished:
                        req.done.set()
                        self._retire_slot(i)
                        continue
                    slots[i] = req
                    h_temps[i] = req.temperature
                    h_top_ps[i] = req.top_p
                    h_top_ks[i] = req.top_k
                    wave_idx.append(i)
                    wave_first.append(first_i)
                    wave_keys.append(key1)
                    admitted = True
                except Exception as exc:
                    req.error = exc
                    if donated:
                        # The failed call may have consumed (donated)
                        # the KV-cache buffers: self._cache is no longer
                        # trustworthy, and every active slot decodes
                        # from it.  Retiring just this slot and
                        # continuing would leave the batcher bricked
                        # but apparently alive — accepting work it can
                        # only fail (or worse, serve from garbage).
                        # Fail the whole batcher loudly instead (the
                        # bundle lands BEFORE req unblocks).
                        self._tick_fatal(exc, "admission-prefill",
                                         prompt_tokens=len(req.tokens))
                        req.done.set()
                        break
                    # Dense prefill does not donate: the failure is
                    # slot-local — surface it, don't kill the loop.
                    req.done.set()
                    self._retire_slot(i)

            if self._stop.is_set():
                break  # fatal admission failure or external stop: drain

            if wave_idx:
                # One padded scatter per array for the whole admission
                # wave (_padded_scatter: one compiled shape): first
                # tokens and sampling keys land on the in-flight step's
                # outputs (inputs of the step after it), and the staged
                # sampling params upload as three fresh arrays.
                try:
                    next_tokens = self._padded_scatter(
                        next_tokens, wave_idx, wave_first)
                    keys = self._padded_scatter(keys, wave_idx,
                                                wave_keys)
                    temps = jnp.asarray(h_temps)
                    top_ps = jnp.asarray(h_top_ps)
                    top_ks = jnp.asarray(h_top_ks)
                except Exception as exc:
                    # A failed wave scatter leaves admitted slots with
                    # un-published tokens/keys: same
                    # dead-loop-with-queued-victims hazard as a failed
                    # dispatch — fail loudly, not silently.
                    self._tick_fatal(exc, "admission-scatter")
                    break

            active_count = sum(1 for s in slots if s is not None)
            tm["queue_depth"].set(self._queue.qsize())
            tm["active_slots"].set(active_count)
            if active_count:
                tm["batch_size"].observe(active_count)

            if not active_count:
                if not admitted and pending is None:
                    # Idle: wait for work WITHOUT dequeuing — the old
                    # get(timeout)+put idiom re-enqueued the peeked
                    # request behind anything submitted in between,
                    # breaking admission FIFO.
                    self._queue.wait_nonempty(0.05)
                continue

            # Speculation: when a draft (model or training-free
            # strategy) is configured and every active slot is greedy,
            # one tick = k proposals + ONE target verify committing
            # 1..k+1 tokens per slot.  Any sampling slot forces plain
            # ticks (acceptance is argmax-only).  `pending` is always
            # None here: speculative batchers never dispatch ahead, and
            # a serialized plain tick was consumed at the loop top.
            if ((self._draft_model is not None
                 or self._draft_strategy is not None) and all(
                    r.temperature <= 0.0 for r in slots if r is not None)):
                # Takes the device lock internally, only around the
                # draft/verify device calls.
                try:
                    next_tokens = self._speculative_tick(slots,
                                                         next_tokens)
                except Exception as exc:
                    self._tick_fatal(exc, "speculative-tick")
                    break
                continue

            # Plain tick: dispatch (pipeline bootstrap, or every tick in
            # serialized mode); fetched at the next loop top.
            if pending is None:
                try:
                    pending = dispatch_step()
                except Exception as exc:
                    self._tick_fatal(exc, "dispatch")
                    break

        # drain on shutdown (submit() rejects once _stop is set, so this
        # converges; get_nowait is the only safe concurrent drain).  On
        # a fatal prefill failure the error names the cause, so pending
        # and in-flight requests fail loudly, not with a bare "stopped".
        if deferred is not None:
            deferred.error = self._shutdown_error()
            deferred.done.set()
        if self.page_size > 0:
            # Unblock KV-page importers waiting on a dead scheduler
            # (import_kv_pages re-checks fatal state after the event).
            with self._kv_imports_lock:
                while self._kv_imports:
                    _, _, done = self._kv_imports.popleft()
                    done.set()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = self._shutdown_error()
            req.done.set()
        for req in slots:
            if req is not None:
                req.error = self._shutdown_error()
                req.done.set()
