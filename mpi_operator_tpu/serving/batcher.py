"""Continuous batching for the KV-cache decode path.

Requests arrive at arbitrary times; instead of serializing whole
generations (single-flight) the batcher keeps B persistent cache slots
and runs ONE decode step per tick across every active slot — new
requests are prefilled into free slots between ticks and finished slots
are freed immediately (vLLM-style iteration-level scheduling, greedy
decoding).  Built on the per-row cache index (models/llama.py): each
slot decodes at its own position, so mixed-length, mixed-arrival
sequences coexist in one batch.

The decode step is jitted once for the fixed slot count; prefill is
jitted per padded prompt-width bucket (powers of two) to bound
recompilation.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class _Request:
    tokens: List[int]
    max_new_tokens: int
    done: threading.Event = field(default_factory=threading.Event)
    output: List[int] = field(default_factory=list)
    error: Optional[Exception] = None


def _bucket(n: int, cap: int) -> int:
    width = 8
    while width < n:
        width *= 2
    return min(width, cap)  # never pad past the cache length


class ContinuousBatcher:
    """Greedy continuous-batching scheduler over `model`'s decode path."""

    def __init__(self, model, variables, max_slots: int = 4,
                 device_lock: Optional[threading.Lock] = None):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.variables = variables
        self.max_slots = max_slots
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Shared with other users of the same device (e.g. the server's
        # non-batched generate path) so at most one model computation is
        # in flight at a time; taken per decode tick / prefill, not for
        # whole generations.
        self._device_lock = device_lock or threading.Lock()

        cfg = model.config
        self._jnp = jnp
        self._jax = jax
        params = {"params": variables["params"]}

        # Persistent slot cache, initialized by tracing a dummy decode.
        _, state = model.apply(
            params, jnp.zeros((max_slots, 1), jnp.int32), decode=True,
            mutable=["cache"])
        cache = state["cache"]
        if hasattr(cache, "unfreeze"):
            cache = cache.unfreeze()
        self._cache = self._reset_cache(cache)

        @jax.jit
        def decode_step(cache, tokens):
            logits, state = model.apply(
                {**params, "cache": cache}, tokens[:, None], decode=True,
                mutable=["cache"])
            return state["cache"], jnp.argmax(
                logits[:, -1], axis=-1).astype(jnp.int32)

        self._decode_step = decode_step
        self._prefill_cache = {}
        self._max_seq_len = cfg.max_seq_len

    # -- cache plumbing ----------------------------------------------------
    def _reset_cache(self, cache):
        return self._jax.tree_util.tree_map(self._jnp.zeros_like, cache)

    def _prefill(self, tokens: List[int]):
        """Single-sequence prefill -> (cache_row_tree, next_token)."""
        jax, jnp = self._jax, self._jnp
        width = _bucket(len(tokens), self._max_seq_len)
        fn = self._prefill_cache.get(width)
        if fn is None:
            params = {"params": self.variables["params"]}

            @jax.jit
            def prefill(padded, length):
                logits, state = self.model.apply(
                    params, padded, decode=True, mutable=["cache"])
                cache = state["cache"]
                next_tok = jnp.argmax(logits[0, length - 1]).astype(jnp.int32)
                return cache, next_tok

            fn = self._prefill_cache[width] = prefill
        padded = jnp.asarray([tokens + [0] * (width - len(tokens))],
                             jnp.int32)
        return fn(padded, len(tokens))

    def _install(self, slot: int, row_cache, length: int):
        """Copy a batch-1 prefill cache into persistent slot `slot`."""
        jnp = self._jnp
        if hasattr(row_cache, "unfreeze"):
            row_cache = row_cache.unfreeze()

        def rec(dst, src):
            if hasattr(dst, "items"):
                return {k: rec(dst[k], src[k]) for k in dst}
            if dst.ndim >= 2:  # cached_key/value [B, L, KH, D]
                L = min(dst.shape[1], src.shape[1])
                return dst.at[slot, :L].set(src[0, :L])
            return dst.at[slot].set(jnp.int32(length))  # cache_index [B]
        self._cache = rec(self._cache, row_cache)

    # -- public API --------------------------------------------------------
    def submit(self, tokens: List[int], max_new_tokens: int,
               timeout: float = 300.0) -> List[int]:
        if max_new_tokens <= 0:
            return []  # match generate()'s [B, 0] semantics
        if len(tokens) + max_new_tokens > self._max_seq_len:
            raise ValueError(
                f"prompt ({len(tokens)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"{self._max_seq_len}")
        if self._stop.is_set():
            raise RuntimeError("batcher stopped")
        req = _Request(list(map(int, tokens)), max_new_tokens)
        self._queue.put(req)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return req.output

    def start(self) -> "ContinuousBatcher":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- scheduler loop ----------------------------------------------------
    def _loop(self) -> None:
        jnp = self._jnp
        slots: List[Optional[_Request]] = [None] * self.max_slots
        next_tokens = jnp.zeros((self.max_slots,), jnp.int32)

        while not self._stop.is_set():
            # Admit new requests into free slots.
            admitted = False
            for i in range(self.max_slots):
                if slots[i] is not None:
                    continue
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                try:
                    with self._device_lock:
                        row_cache, first = self._prefill(req.tokens)
                        self._install(i, row_cache, len(req.tokens))
                    req.output.append(int(first))
                    if len(req.output) >= req.max_new_tokens:
                        req.done.set()
                        continue
                    slots[i] = req
                    next_tokens = next_tokens.at[i].set(int(first))
                    admitted = True
                except Exception as exc:  # surface, don't kill the loop
                    req.error = exc
                    req.done.set()

            if not any(s is not None for s in slots):
                if not admitted:
                    # idle: block briefly for work
                    try:
                        req = self._queue.get(timeout=0.05)
                        self._queue.put(req)
                    except queue.Empty:
                        pass
                continue

            # One decode step across every slot (inactive slots decode
            # garbage into their own rows; they are reset on admit).
            with self._device_lock:
                self._cache, out = self._decode_step(self._cache,
                                                     next_tokens)
            next_tokens = out
            for i, req in enumerate(slots):
                if req is None:
                    continue
                req.output.append(int(out[i]))
                if len(req.output) >= req.max_new_tokens:
                    req.done.set()
                    slots[i] = None

        # drain on shutdown (submit() rejects once _stop is set, so this
        # converges; get_nowait is the only safe concurrent drain)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = RuntimeError("batcher stopped")
            req.done.set()
        for req in slots:
            if req is not None:
                req.error = RuntimeError("batcher stopped")
                req.done.set()
