"""Continuous batching for the KV-cache decode path.

Requests arrive at arbitrary times; instead of serializing whole
generations (single-flight) the batcher keeps B persistent cache slots
and runs ONE decode step per tick across every active slot — new
requests are prefilled into free slots between ticks and finished slots
are freed immediately (vLLM-style iteration-level scheduling;
per-slot greedy or nucleus sampling).  Built on the per-row cache index (models/llama.py): each
slot decodes at its own position, so mixed-length, mixed-arrival
sequences coexist in one batch.

The decode step is jitted once for the fixed slot count; prefill is
jitted per padded prompt-width bucket (powers of two) to bound
recompilation.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class _Request:
    tokens: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0
    done: threading.Event = field(default_factory=threading.Event)
    output: List[int] = field(default_factory=list)
    error: Optional[Exception] = None
    on_token: Optional[object] = None  # callable(int), streaming hook
    cancelled: threading.Event = field(default_factory=threading.Event)

    def emit(self, token: int) -> None:
        self.output.append(token)
        if self.on_token is not None:
            self.on_token(token)


def _bucket(n: int, cap: int) -> int:
    width = 8
    while width < n:
        width *= 2
    return min(width, cap)  # never pad past the cache length


class ContinuousBatcher:
    """Continuous-batching scheduler over `model`'s decode path; each
    slot carries its own (temperature, top_p, rng) so greedy and
    sampling requests share decode ticks."""

    def __init__(self, model, variables, max_slots: int = 4,
                 device_lock: Optional[threading.Lock] = None):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.variables = variables
        self.max_slots = max_slots
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Shared with other users of the same device (e.g. the server's
        # non-batched generate path) so at most one model computation is
        # in flight at a time; taken per decode tick / prefill, not for
        # whole generations.
        self._device_lock = device_lock or threading.Lock()

        cfg = model.config
        self._jnp = jnp
        self._jax = jax
        params = {"params": variables["params"]}

        # Persistent slot cache, initialized by tracing a dummy decode.
        _, state = model.apply(
            params, jnp.zeros((max_slots, 1), jnp.int32), decode=True,
            mutable=["cache"])
        cache = state["cache"]
        if hasattr(cache, "unfreeze"):
            cache = cache.unfreeze()
        self._cache = self._reset_cache(cache)

        @jax.jit
        def decode_step(cache, tokens, temps, top_ps, keys):
            logits, state = model.apply(
                {**params, "cache": cache}, tokens[:, None], decode=True,
                mutable=["cache"])
            nxt, keys = _select_rows(logits[:, -1], temps, top_ps, keys)
            return state["cache"], nxt.astype(jnp.int32), keys

        self._decode_step = decode_step
        self._prefill_cache = {}
        self._max_seq_len = cfg.max_seq_len

    # -- cache plumbing ----------------------------------------------------
    def _reset_cache(self, cache):
        return self._jax.tree_util.tree_map(self._jnp.zeros_like, cache)

    def _prefill(self, tokens: List[int], sample_args):
        """Single-sequence prefill -> (cache_row_tree, next_token, key).
        sample_args = (temperature, top_p, rng_key) scalars for the new
        sequence's first sampled token."""
        jax, jnp = self._jax, self._jnp
        width = _bucket(len(tokens), self._max_seq_len)
        fn = self._prefill_cache.get(width)
        if fn is None:
            params = {"params": self.variables["params"]}

            @jax.jit
            def prefill(padded, length, temp, top_p, key):
                logits, state = self.model.apply(
                    params, padded, decode=True, mutable=["cache"])
                cache = state["cache"]
                nxt, key = _select_rows(logits[:, length - 1],
                                        temp[None], top_p[None],
                                        key[None])
                return cache, nxt[0].astype(jnp.int32), key[0]

            fn = self._prefill_cache[width] = prefill
        padded = jnp.asarray([tokens + [0] * (width - len(tokens))],
                             jnp.int32)
        return fn(padded, len(tokens), *sample_args)

    def _install(self, slot: int, row_cache, length: int):
        """Copy a batch-1 prefill cache into persistent slot `slot`."""
        jnp = self._jnp
        if hasattr(row_cache, "unfreeze"):
            row_cache = row_cache.unfreeze()

        def rec(dst, src):
            if hasattr(dst, "items"):
                return {k: rec(dst[k], src[k]) for k in dst}
            if dst.ndim >= 2:  # cached_key/value [B, L, KH, D]
                L = min(dst.shape[1], src.shape[1])
                return dst.at[slot, :L].set(src[0, :L])
            return dst.at[slot].set(jnp.int32(length))  # cache_index [B]
        self._cache = rec(self._cache, row_cache)

    # -- public API --------------------------------------------------------
    def _enqueue(self, tokens, max_new_tokens, temperature, top_p, seed,
                 on_token=None) -> _Request:
        if len(tokens) + max_new_tokens > self._max_seq_len:
            raise ValueError(
                f"prompt ({len(tokens)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"{self._max_seq_len}")
        if self._stop.is_set():
            raise RuntimeError("batcher stopped")
        if seed is None:
            import random
            seed = random.getrandbits(31)
        req = _Request(list(map(int, tokens)), max_new_tokens,
                       temperature=float(temperature), top_p=float(top_p),
                       seed=int(seed), on_token=on_token)
        self._queue.put(req)
        return req

    def submit(self, tokens: List[int], max_new_tokens: int,
               timeout: float = 300.0, temperature: float = 0.0,
               top_p: float = 1.0, seed: Optional[int] = None) -> List[int]:
        if max_new_tokens <= 0:
            return []  # match generate()'s [B, 0] semantics
        req = self._enqueue(tokens, max_new_tokens, temperature, top_p,
                            seed)
        if not req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return req.output

    def submit_iter(self, tokens: List[int], max_new_tokens: int,
                    timeout: float = 300.0, temperature: float = 0.0,
                    top_p: float = 1.0, seed: Optional[int] = None):
        """Streaming submit: yields each generated id as the batcher
        produces it (tokens from this slot's decode ticks)."""
        if max_new_tokens <= 0:
            return
        sentinel = object()
        out: "queue.Queue" = queue.Queue()
        req = self._enqueue(tokens, max_new_tokens, temperature, top_p,
                            seed, on_token=out.put)
        threading.Thread(
            target=lambda: (req.done.wait(timeout), out.put(sentinel)),
            daemon=True).start()
        try:
            while True:
                item = out.get(timeout=timeout)
                if item is sentinel:
                    break
                yield item
        finally:
            # Closed early (client disconnect -> GeneratorExit): cancel
            # so the batcher frees the slot instead of decoding for
            # nobody.
            req.cancelled.set()
        if req.error is not None:
            raise req.error
        if not req.done.is_set():
            raise TimeoutError("generation timed out")

    def start(self) -> "ContinuousBatcher":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- scheduler loop ----------------------------------------------------
    def _loop(self) -> None:
        jax, jnp = self._jax, self._jnp
        slots: List[Optional[_Request]] = [None] * self.max_slots
        next_tokens = jnp.zeros((self.max_slots,), jnp.int32)
        temps = jnp.zeros((self.max_slots,), jnp.float32)
        top_ps = jnp.ones((self.max_slots,), jnp.float32)
        keys = jnp.zeros((self.max_slots, 2), jnp.uint32)

        while not self._stop.is_set():
            # Admit new requests into free slots.
            admitted = False
            for i in range(self.max_slots):
                if slots[i] is not None:
                    continue
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                try:
                    key0 = jax.random.fold_in(
                        jax.random.PRNGKey(req.seed), len(req.tokens))
                    sample_args = (jnp.float32(req.temperature),
                                   jnp.float32(req.top_p), key0)
                    with self._device_lock:
                        row_cache, first, key1 = self._prefill(
                            req.tokens, sample_args)
                        self._install(i, row_cache, len(req.tokens))
                    req.emit(int(first))
                    if len(req.output) >= req.max_new_tokens:
                        req.done.set()
                        continue
                    slots[i] = req
                    next_tokens = next_tokens.at[i].set(int(first))
                    temps = temps.at[i].set(req.temperature)
                    top_ps = top_ps.at[i].set(req.top_p)
                    keys = keys.at[i].set(key1)
                    admitted = True
                except Exception as exc:  # surface, don't kill the loop
                    req.error = exc
                    req.done.set()

            if not any(s is not None for s in slots):
                if not admitted:
                    # idle: block briefly for work
                    try:
                        req = self._queue.get(timeout=0.05)
                        self._queue.put(req)
                    except queue.Empty:
                        pass
                continue

            # One decode step across every slot (inactive slots decode
            # garbage into their own rows; they are reset on admit).
            with self._device_lock:
                self._cache, out, keys = self._decode_step(
                    self._cache, next_tokens, temps, top_ps, keys)
            next_tokens = out
            for i, req in enumerate(slots):
                if req is None:
                    continue
                if req.cancelled.is_set():
                    req.done.set()
                    slots[i] = None
                    continue
                req.emit(int(out[i]))
                if len(req.output) >= req.max_new_tokens:
                    req.done.set()
                    slots[i] = None

        # drain on shutdown (submit() rejects once _stop is set, so this
        # converges; get_nowait is the only safe concurrent drain)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = RuntimeError("batcher stopped")
            req.done.set()
        for req in slots:
            if req is not None:
                req.error = RuntimeError("batcher stopped")
                req.done.set()


def _select_rows(logits, temps, top_ps, keys):
    """Per-row greedy/nucleus selection: logits [B, V], temps/top_ps [B],
    keys [B, 2].  Row semantics mirror models.llama._select_token
    (smallest prefix with mass >= top_p); rows with temperature <= 0 are
    greedy.  Returns (tokens [B], advanced keys [B, 2])."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / jnp.maximum(temps, 1e-6)[:, None]
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cumulative < top_ps[:, None], axis=-1)
    threshold = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                    axis=-1)
    nucleus = jnp.where(
        (scaled < threshold) & (top_ps[:, None] < 1.0), -jnp.inf, scaled)
    sampled = jax.vmap(lambda l, k: jax.random.categorical(k, l))(
        nucleus, keys)
    new_keys = jax.vmap(lambda k: jax.random.split(k, 1)[0])(keys)
    return jnp.where(temps <= 0.0, greedy, sampled), new_keys
