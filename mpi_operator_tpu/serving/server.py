"""Minimal inference HTTP server.

Serves a Llama-family model's KV-cache generation
(models/llama.generate) over HTTP:

    POST /generate {"tokens": [[...]], "max_new_tokens": 8,
                    "temperature": 0.0, "top_p": 1.0}
      -> {"tokens": [[...]]}
    POST /generate {..., "stream": true}   -> text/event-stream (SSE),
      one data event per token, then {"done": true, "tokens": [...]}
    GET /healthz
    GET /metrics  -> Prometheus text exposition: queue depth, batch
      size, TTFT and per-token latency histograms, queue-wait
      (submit -> admission, with a deferred variant for pool-exhaustion
      stalls) and the decode hot-path tick/dispatch/transfer counters
      (telemetry subsystem) plus the process default registry
      (train/checkpoint metrics when the same process also trains)

With continuous batching the steady-state decode tick is pipelined
(``pipelined=None`` -> batcher default: on; see serving/batcher.py):
the device never waits on host-side token processing, and each tick
fetches all slots' tokens in one device->host transfer.

The accelerator is a serial resource behind a per-step device lock;
with ``max_batch_slots > 0`` concurrent requests share decode ticks via
the continuous batcher.  No reference counterpart — the reference is
training-only orchestration; this rounds out the workload stack's
lifecycle (train -> checkpoint -> serve).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..telemetry.metrics import (Registry, expose_with_defaults,
                                 new_serving_metrics, record_build_info)
from ..telemetry.trace import TraceContext

# Sliding-window attention forces the materialized-score XLA path
# (ops/attention.py window branch), so an S-token prefill allocates an
# O(S^2) f32 score matrix; past this prompt length that footprint
# dominates unless chunked prefill bounds it (ADVICE round-5).
_SWA_PROMPT_THRESHOLD = 2048
_swa_chunk_warned = False


def _warn_swa_unchunked(cfg) -> None:
    global _swa_chunk_warned
    if _swa_chunk_warned:
        return
    _swa_chunk_warned = True
    import warnings
    warnings.warn(
        f"sliding_window={cfg.sliding_window} with "
        f"max_seq_len={cfg.max_seq_len} and kv_prefill_chunk=0: SWA "
        f"uses the materialized-score attention path, so a long-prompt "
        f"prefill allocates O(S^2) activation memory. Set "
        f"kv_prefill_chunk (e.g. 512) to bound it — see "
        f"docs/RESILIENCE.md#swa-long-prompt-footgun.",
        RuntimeWarning, stacklevel=3)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def _respond(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            server: "InferenceServer" = self.server.inference  # type: ignore
            fatal = getattr(server._batcher, "fatal_error", None)
            if fatal is not None:
                # A bricked batcher must fail its health check, not sit
                # behind a green /healthz accepting doomed requests.
                self._respond(503, {"status": "failed",
                                    "error": str(fatal)})
            else:
                self._respond(200, {"status": "ok"})
        elif self.path == "/metrics":
            server: "InferenceServer" = self.server.inference  # type: ignore
            body = expose_with_defaults(server.telemetry_registry).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/fleet-state":
            # Metrics-adjacent fleet surface: the compact state the
            # router polls — queue depth for power-of-two-choices and
            # the prefix-cache digest index for prefix-aware placement
            # (docs/PERF.md "Serving fleet").
            server: "InferenceServer" = self.server.inference  # type: ignore
            self._respond(200, server.fleet_state())
        elif self.path == "/debug-bundle":
            # On-demand black box: freeze the flight ring + metrics for
            # a live-but-misbehaving server without killing it.
            server: "InferenceServer" = self.server.inference  # type: ignore
            from ..telemetry import flight
            path = flight.dump_bundle(
                "serving-on-demand", registry=server.telemetry_registry)
            if path is None:
                self._respond(500, {"error": "bundle dump failed"})
            else:
                self._respond(200, {"bundle": path})
        else:
            self._respond(404, {"error": "not found"})

    def do_POST(self):
        if self.path == "/prefill":
            return self._prefill()
        if self.path == "/kv/pages":
            return self._kv_pages()
        if self.path != "/generate":
            return self._respond(404, {"error": "not found"})
        server: "InferenceServer" = self.server.inference  # type: ignore
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            tokens = req["tokens"]
            stop = req.get("stop") or []  # null = unset
            if req.get("eos_token_id") is not None:
                stop = list(stop) + [req["eos_token_id"]]
            kwargs = dict(
                max_new_tokens=int(req.get("max_new_tokens", 16)),
                temperature=float(req.get("temperature", 0.0)),
                top_p=float(req.get("top_p", 1.0)),
                top_k=int(req.get("top_k") or 0),
                seed=req.get("seed"),
                stop_tokens=tuple(map(int, stop)),
                # Causal-trace carrier from the fleet router: replica-
                # side queue-wait/prefill spans parent to its request.
                trace_ctx=TraceContext.decode(req.get("trace_context")))
            if req.get("stream"):
                return self._stream(server, tokens, kwargs)
            out = server.generate(tokens, **kwargs)
            self._respond(200, {"tokens": out})
        except Exception as exc:
            self._respond(400, {"error": str(exc)})

    def _prefill(self) -> None:
        """POST /prefill — the prefill stage of a disaggregated request
        (serving/kv_transfer.py): chunk-prefill the prompt into this
        replica's paged pool (the request retires at admission, so no
        decode tick is ever spent here), then push the populated pages
        the destination decode replica is missing.  Returns the prompt's
        chain digests plus transfer accounting."""
        server: "InferenceServer" = self.server.inference  # type: ignore
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            tokens = [int(t) for t in req["tokens"]]
            transfer = req.get("transfer") or {}
            out = server.prefill(
                tokens, dest_url=transfer.get("url"),
                have=transfer.get("have"),
                trace_ctx=TraceContext.decode(req.get("trace_context")))
            self._respond(200, out)
        except Exception as exc:
            self._respond(400, {"error": str(exc)})

    def _kv_pages(self) -> None:
        """POST /kv/pages — receive content-addressed KV pages from a
        prefill replica and install them into the local pool (decode
        side of the disaggregated handoff).  Best-effort: the response
        reports per-page accounting; rejected pages are simply
        prefilled locally by the next /generate."""
        server: "InferenceServer" = self.server.inference  # type: ignore
        try:
            from . import kv_transfer
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            b = server._batcher
            if b is None or b.page_size <= 0:
                return self._respond(400, {
                    "error": "KV-page import requires the paged cache "
                             "(kv_page_size > 0)"})
            pages = kv_transfer.decode_pages(req.get("pages") or [])
            self._respond(200, b.import_kv_pages(pages))
        except Exception as exc:
            self._respond(400, {"error": str(exc)})

    def _stream(self, server: "InferenceServer", tokens, kwargs) -> None:
        """SSE: one `data: {"token": t}` event per generated token, then
        `data: {"done": true, "tokens": [...]}`."""
        it = server.stream(tokens, **kwargs)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def emit(payload: dict) -> None:
            chunk = f"data: {json.dumps(payload)}\n\n".encode()
            self.wfile.write(f"{len(chunk):x}\r\n".encode() + chunk
                             + b"\r\n")
            self.wfile.flush()

        produced = []
        try:
            try:
                for tok in it:
                    produced.append(tok)
                    emit({"token": tok})
                emit({"done": True, "tokens": produced})
            except (BrokenPipeError, ConnectionResetError):
                # Client went away mid-stream: stop generating (closing
                # the iterator cancels a batcher slot) and abort the
                # connection quietly — headers/body already went out, so
                # a 400 response is impossible.
                raise
            except Exception as exc:
                emit({"error": str(exc)})
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            self.close_connection = True
        finally:
            it.close()


class InferenceServer:
    # Loopback by default, like RemoteApiServer (k8s/http_api.py):
    # /generate is unauthenticated and compute-expensive, so exposing it
    # on all interfaces must be an explicit opt-in (host="0.0.0.0").
    def __init__(self, model, variables, host: str = "127.0.0.1",
                 port: int = 0, max_batch_slots: int = 0, mesh=None,
                 kv_page_size: int = 0, kv_cache_blocks: int = 0,
                 kv_prefix_cache: bool = True, kv_cache_dtype: str = "auto",
                 draft_model=None, draft_variables=None,
                 draft_strategy: Optional[str] = None,
                 draft_len: int = 4, prompt_lookup_ngram: int = 3,
                 kv_prefill_chunk: int = 0, weight_dtype: str = "auto",
                 pipelined: Optional[bool] = None,
                 telemetry_registry: Optional[Registry] = None,
                 role: str = "unified", model_name: str = ""):
        # Disaggregated serving identity (serving/disagg.py): which
        # stage this replica runs and which model it holds.  The role
        # only changes what the replica ADVERTISES (/fleet-state) and
        # what the router sends it; either role can serve either verb,
        # so a mid-failover fallback is always correct.
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be 'unified', 'prefill' or 'decode', "
                f"got {role!r}")
        if role != "unified" and kv_page_size <= 0:
            # The disagg handoff IS the paged pool; without it there is
            # nothing to transfer and the fleet would silently degrade
            # to unified serving (ISSUE 17 fail-fast satellite).
            raise ValueError(
                f"role={role!r} (disaggregated serving) requires a "
                f"paged KV cache (kv_page_size > 0); unpaged replicas "
                f"can only serve unified")
        self.role = role
        self.model_name = model_name
        if weight_dtype not in ("auto", "int8"):
            raise ValueError(
                f"weight_dtype must be 'auto' or 'int8', "
                f"got {weight_dtype!r}")
        if weight_dtype == "int8" and \
                getattr(model.config, "weight_dtype", "auto") != "int8":
            # Weight-only int8 serving: swap in the quantized model and
            # quantize the weights up front (models/quant.py) — halves
            # weight HBM, which is most of what decode streams per step.
            # NOTE: the caller must drop its own reference to the
            # full-precision variables, or both copies stay resident
            # and the halving never lands (see examples/llama_serve.py).
            import dataclasses

            from ..models.quant import quantize_params

            qcfg = dataclasses.replace(model.config, weight_dtype="int8")
            model = type(model)(qcfg, mesh=getattr(model, "mesh", None))
            variables = {**variables,
                         "params": quantize_params(variables["params"],
                                                   qcfg)}
        self.model = model
        self.variables = variables
        self.mesh = mesh
        # Config-less models are legal on the metrics-only path
        # (tests serve /metrics without loading a model).
        cfg = getattr(model, "config", None)
        if (cfg is not None
                and getattr(cfg, "sliding_window", None) is not None
                and getattr(cfg, "max_seq_len", 0) > _SWA_PROMPT_THRESHOLD
                and kv_prefill_chunk <= 0):
            _warn_swa_unchunked(cfg)
        # Optional speculative decoding (greedy requests on the
        # non-batched path): a small same-vocab draft model proposes,
        # the target verifies — output is exactly the greedy decode.
        if (draft_model is None) != (draft_variables is None):
            raise ValueError(
                "draft_model and draft_variables go together")
        self.draft_model = draft_model
        self.draft_variables = draft_variables
        if mesh is not None:
            # Tensor-parallel serving: place the params by their Megatron
            # PartitionSpecs so decode matmuls shard over 'tp' (and
            # 'fsdp'); generation then runs under this mesh.
            from ..models.llama import llama_param_specs
            from ..parallel.mesh import shard_params

            specs = llama_param_specs(model.config)["params"]
            self.variables = {
                **variables,
                "params": shard_params(variables["params"], specs, mesh),
            }
        self._lock = threading.Lock()
        # Serving telemetry (queue depth, batch size, TTFT, per-token
        # latency) lives on its own registry, scraped at GET /metrics
        # alongside the process default registry.
        self.telemetry_registry = telemetry_registry or Registry()
        self.telemetry = new_serving_metrics(self.telemetry_registry)
        record_build_info()
        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http.inference = self  # type: ignore[attr-defined]
        self.port = self._http.server_address[1]
        self._thread: Optional[threading.Thread] = None
        # Optional continuous batching (greedy single-sequence requests
        # share decode ticks instead of serializing whole generations).
        # The batcher shares this server's device lock, so batcher ticks
        # and non-batched generations still never overlap on the device.
        self._batcher = None
        if kv_page_size > 0 and max_batch_slots <= 0:
            raise ValueError(
                "kv_page_size requires continuous batching "
                "(max_batch_slots > 0); the non-batched path uses the "
                "dense cache")
        if kv_cache_dtype != "auto" and kv_page_size <= 0:
            raise ValueError(
                f"kv_cache_dtype={kv_cache_dtype!r} requires "
                f"kv_page_size > 0 (only the paged pool is quantized)")
        if draft_strategy is not None and max_batch_slots <= 0:
            raise ValueError(
                "draft_strategy requires continuous batching "
                "(max_batch_slots > 0); the non-batched path speculates "
                "via draft_model only")
        if kv_prefill_chunk > 0 and max_batch_slots <= 0:
            raise ValueError(
                "kv_prefill_chunk requires continuous batching "
                "(max_batch_slots > 0); the non-batched path prefills "
                "whole prompts through the dense cache")
        if max_batch_slots > 0:
            from .batcher import ContinuousBatcher
            # The draft rides into the batcher too: greedy batched
            # requests speculate (k draft steps + one verify per tick)
            # whenever every active slot is greedy.
            self._batcher = ContinuousBatcher(model, self.variables,
                                              max_slots=max_batch_slots,
                                              device_lock=self._lock,
                                              page_size=kv_page_size,
                                              cache_blocks=kv_cache_blocks,
                                              prefix_cache=kv_prefix_cache,
                                              kv_cache_dtype=kv_cache_dtype,
                                              draft_model=draft_model,
                                              draft_variables=draft_variables,
                                              draft_strategy=draft_strategy,
                                              draft_len=draft_len,
                                              prompt_lookup_ngram=(
                                                  prompt_lookup_ngram),
                                              prefill_chunk=(
                                                  kv_prefill_chunk),
                                              pipelined=pipelined,
                                              telemetry_registry=(
                                                  self.telemetry_registry))

    # -- inference ---------------------------------------------------------
    def generate(self, tokens, max_new_tokens: int = 16,
                 temperature: float = 0.0, top_p: float = 1.0,
                 seed=None, stop_tokens=(), top_k: int = 0,
                 trace_ctx=None) -> list:
        # Counted in finally, like stream(): requests_total covers every
        # request served, successful or not (see new_serving_metrics help).
        try:
            with self.telemetry["request_seconds"].time():
                return self._generate(tokens,
                                      max_new_tokens=max_new_tokens,
                                      temperature=temperature, top_p=top_p,
                                      seed=seed, stop_tokens=stop_tokens,
                                      top_k=top_k, trace_ctx=trace_ctx)
        finally:
            self.telemetry["requests_total"].inc()

    def _generate(self, tokens, max_new_tokens: int = 16,
                  temperature: float = 0.0, top_p: float = 1.0,
                  seed=None, stop_tokens=(), top_k: int = 0,
                  trace_ctx=None) -> list:
        import jax
        import jax.numpy as jnp

        from ..models.llama import generate

        # Accept one sequence or a batch of VARIABLE-LENGTH sequences
        # (lists or numpy/jnp arrays): right-pad to a rectangle and let
        # the per-row cache index decode each row from its own prompt end.
        if hasattr(tokens, "tolist"):
            tokens = tokens.tolist()
        tokens = list(tokens)
        if tokens and isinstance(tokens[0], (list, tuple)) or \
                (tokens and hasattr(tokens[0], "tolist")):
            rows = [list(map(int, r)) for r in tokens]
        else:
            rows = [list(map(int, tokens))]
        if any(not r for r in rows):
            raise ValueError("empty prompt")
        # Single-sequence requests ride the continuous batcher so
        # concurrent clients share decode ticks (each slot carries its
        # own temperature/top_p/rng).
        if self._batcher is not None and len(rows) == 1:
            return [self._batcher.submit(
                rows[0], max_new_tokens, temperature=temperature,
                top_p=top_p, seed=seed, stop_tokens=stop_tokens,
                top_k=top_k, trace_ctx=trace_ctx)]
        lengths = [len(r) for r in rows]
        width = max(lengths)
        prompt = jnp.asarray([r + [0] * (width - len(r)) for r in rows],
                             jnp.int32)
        prompt_lengths = jnp.asarray(lengths, jnp.int32) \
            if len(set(lengths)) > 1 else None
        rng = jax.random.PRNGKey(int(seed)) if seed is not None else None
        draft_len = 4
        # Both models bound the speculation window; a request that only
        # fits the target falls back to plain decode instead of erroring.
        spec_fits = all(
            prompt.shape[1] + max_new_tokens + draft_len + 1
            <= m.config.max_seq_len
            for m in (self.model, self.draft_model)
            if m is not None)
        speculate = (self.draft_model is not None and temperature <= 0.0
                     and prompt_lengths is None and spec_fits)
        with self._lock:  # accelerator is single-flight
            if speculate:
                from ..models.speculative import speculative_generate
                out = speculative_generate(
                    self.model, self.variables, self.draft_model,
                    self.draft_variables, prompt, max_new_tokens,
                    draft_len=draft_len)
            else:
                out = generate(self.model, self.variables, prompt,
                               max_new_tokens, temperature=temperature,
                               top_p=top_p, rng=rng,
                               prompt_lengths=prompt_lengths,
                               stop_tokens=stop_tokens, top_k=top_k)
        result = [[int(t) for t in row] for row in out]
        if stop_tokens and speculate:
            # The speculative path decodes the full budget; truncating
            # at the first stop token is equivalent to stopping there
            # (same fill convention as generate(), shared helper).
            import numpy as np

            from ..models.llama import fill_after_stop
            result = fill_after_stop(np.array(result, dtype=np.int64),
                                     stop_tokens).tolist()
        return result

    def stream(self, tokens, max_new_tokens: int = 16,
               temperature: float = 0.0, top_p: float = 1.0, seed=None,
               stop_tokens=(), top_k: int = 0, trace_ctx=None):
        """Yield generated ids one at a time for ONE sequence (the SSE
        source).  Rides the continuous batcher when enabled; otherwise
        takes the device lock per decode step so slow stream consumers
        never monopolize the accelerator."""
        start = time.perf_counter()
        try:
            yield from self._stream(tokens, max_new_tokens=max_new_tokens,
                                    temperature=temperature, top_p=top_p,
                                    seed=seed, stop_tokens=stop_tokens,
                                    top_k=top_k, trace_ctx=trace_ctx)
        finally:
            # Streaming requests count toward the request-level metrics
            # too (duration covers the full stream, including aborts).
            self.telemetry["request_seconds"].observe(
                time.perf_counter() - start)
            self.telemetry["requests_total"].inc()

    def _stream(self, tokens, max_new_tokens: int = 16,
                temperature: float = 0.0, top_p: float = 1.0, seed=None,
                stop_tokens=(), top_k: int = 0, trace_ctx=None):
        import jax

        if hasattr(tokens, "tolist"):  # numpy/jnp arrays, like generate()
            tokens = tokens.tolist()
        tokens = list(tokens)
        if tokens and (isinstance(tokens[0], (list, tuple))
                       or hasattr(tokens[0], "tolist")):
            if len(tokens) != 1:
                raise ValueError("streaming supports one sequence")
            tokens = tokens[0]
        rows = list(map(int, tokens))
        if not rows:
            raise ValueError("empty prompt")
        if self._batcher is not None:
            yield from self._batcher.submit_iter(
                rows, max_new_tokens, temperature=temperature, top_p=top_p,
                seed=seed, stop_tokens=stop_tokens, top_k=top_k,
                trace_ctx=trace_ctx)
            return

        from ..models.llama import stream_generate
        rng = jax.random.PRNGKey(int(seed)) if seed is not None else None
        # Take the device lock PER STEP, not for the whole generation: a
        # slow SSE client must never hold the accelerator hostage while
        # the socket drains.
        gen = stream_generate(
            self.model, self.variables, rows, max_new_tokens,
            temperature=temperature, top_p=top_p, rng=rng,
            stop_tokens=stop_tokens, top_k=top_k)
        start = time.perf_counter()
        last = None
        try:
            while True:
                with self._lock:
                    try:
                        tok = next(gen)
                    except StopIteration:
                        return
                now = time.perf_counter()
                if last is None:
                    self.telemetry["ttft_seconds"].observe(now - start)
                else:
                    self.telemetry["token_latency_seconds"].observe(
                        now - last)
                last = now
                yield tok
        finally:
            gen.close()

    def prefill(self, tokens, dest_url: Optional[str] = None,
                have=None, trace_ctx=None) -> dict:
        """Disaggregated prefill stage: populate this replica's paged
        prefix cache with the prompt's full pages (a max_new_tokens=1
        submit retires at admission — chunked prefill runs, pages
        register, and no decode tick is ever consumed), then push the
        pages ``dest_url`` is missing over the KV-transfer channel.

        Returns the prompt's chain digests and transfer accounting;
        with ``dest_url=None`` it is a pure cache-warm."""
        from . import kv_transfer
        from .batcher import prefix_page_digests
        b = self._batcher
        if b is None or b.page_size <= 0:
            raise ValueError(
                "prefill stage requires the paged cache "
                "(max_batch_slots > 0 and kv_page_size > 0)")
        rows = [int(t) for t in tokens]
        if not rows:
            raise ValueError("empty prompt")
        digests = prefix_page_digests(rows, b.page_size)
        with self.telemetry["request_seconds"].time():
            # Greedy, budget 1: the emitted token is discarded — the
            # decode replica re-derives it from the transferred pages
            # (byte-identical; K/V depends only on the token prefix).
            b.submit(rows, 1, temperature=0.0, seed=0,
                     trace_ctx=trace_ctx)
        out = {"digests": digests, "shipped": 0, "deduped": 0,
               "imported": 0, "rejected": 0, "bytes": 0}
        if dest_url and digests:
            out.update(kv_transfer.transfer_pages(
                b, digests, dest_url, have=have))
        return out

    def fleet_state(self) -> dict:
        """The GET /fleet-state payload (see _Handler): live queue
        depth + slot occupancy for load balancing, the batcher's
        advertised prefix-cache digests for prefix-aware routing, and
        the disagg identity (role/model) + free pool blocks the router
        schedules decode placement by."""
        b = self._batcher
        if b is None:
            return {"healthy": True, "queue_depth": 0, "active_slots": 0,
                    "slots": 0, "page_size": 0, "prefix_digests": [],
                    "role": self.role, "model": self.model_name,
                    "free_blocks": 0}
        return {
            "healthy": b.fatal_error is None,
            "queue_depth": b._queue.qsize(),
            "active_slots": int(b.telemetry["active_slots"].value),
            "slots": b.max_slots,
            "page_size": b.page_size,
            "prefix_digests": b.prefix_digest(),
            "role": self.role,
            "model": self.model_name,
            "free_blocks": b.free_blocks(),
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "InferenceServer":
        if self._batcher is not None:
            self._batcher.start()
        self._thread = threading.Thread(target=self._http.serve_forever,
                                        daemon=True, name="inference")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._batcher is not None:
            self._batcher.stop()
        if self._thread is not None:
            # shutdown() blocks on serve_forever's shut-down event; it
            # would wait forever on a server that was never start()ed
            # (library use: generate() without the HTTP endpoint).
            self._http.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._http.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
