"""Minimal inference HTTP server.

Serves a Llama-family model's KV-cache generation
(models/llama.generate) over HTTP:

    POST /generate {"tokens": [[...]], "max_new_tokens": 8,
                    "temperature": 0.0, "top_p": 1.0}
      -> {"tokens": [[...]]}
    GET /healthz

Requests execute single-flight behind a lock (the accelerator is a
serial resource); continuous batching is roadmap.  No reference
counterpart — the reference is training-only orchestration; this rounds
out the workload stack's lifecycle (train -> checkpoint -> serve).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def _respond(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            self._respond(200, {"status": "ok"})
        else:
            self._respond(404, {"error": "not found"})

    def do_POST(self):
        if self.path != "/generate":
            return self._respond(404, {"error": "not found"})
        server: "InferenceServer" = self.server.inference  # type: ignore
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            tokens = req["tokens"]
            out = server.generate(
                tokens,
                max_new_tokens=int(req.get("max_new_tokens", 16)),
                temperature=float(req.get("temperature", 0.0)),
                top_p=float(req.get("top_p", 1.0)),
                seed=req.get("seed"))
            self._respond(200, {"tokens": out})
        except Exception as exc:
            self._respond(400, {"error": str(exc)})


class InferenceServer:
    # Loopback by default, like RemoteApiServer (k8s/http_api.py):
    # /generate is unauthenticated and compute-expensive, so exposing it
    # on all interfaces must be an explicit opt-in (host="0.0.0.0").
    def __init__(self, model, variables, host: str = "127.0.0.1",
                 port: int = 0):
        self.model = model
        self.variables = variables
        self._lock = threading.Lock()
        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http.inference = self  # type: ignore[attr-defined]
        self.port = self._http.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- inference ---------------------------------------------------------
    def generate(self, tokens, max_new_tokens: int = 16,
                 temperature: float = 0.0, top_p: float = 1.0,
                 seed=None) -> list:
        import jax
        import jax.numpy as jnp

        from ..models.llama import generate

        prompt = jnp.asarray(tokens, jnp.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        rng = jax.random.PRNGKey(int(seed)) if seed is not None else None
        with self._lock:  # accelerator is single-flight
            out = generate(self.model, self.variables, prompt,
                           max_new_tokens, temperature=temperature,
                           top_p=top_p, rng=rng)
        return [[int(t) for t in row] for row in out]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "InferenceServer":
        self._thread = threading.Thread(target=self._http.serve_forever,
                                        daemon=True, name="inference")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
