"""Fleet router — the HTTP front door over N InferenceServer replicas.

One replica's content-addressed prefix cache (serving/batcher.py) only
pays off fleet-wide if requests sharing a prefix land on the replica
that already holds it.  The router places each request by, in order:

1. **Session affinity** — a request carrying a ``"session"`` key sticks
   to the replica that served the session before (its private suffix
   blocks and any registered prompt pages are resident there).
2. **Prefix-aware placement** — the prompt's full pages are chain-
   digested (`batcher.prefix_page_digests`) and matched against each
   replica's advertised hit index (GET /fleet-state, backed by
   `ContinuousBatcher.prefix_digest`); the replica with the longest
   cached run wins (ties broken by load).  The winner's index is
   optimistically extended with the request's own digests so a burst of
   same-prefix requests converges on one replica before the next poll.
3. **Power-of-two-choices** — cold prefixes sample two replicas and
   take the less loaded one (router-local in-flight count + last-polled
   queue depth): near-optimal load spread at O(1) state, no global
   scan.

``policy="round_robin"`` disables 1–3 (the bench baseline: same fleet,
placement-blind).

A request in flight on a replica that dies (transport failure, or an
upstream error whose replica then fails its health check) is retried on
a healthy replica EXACTLY once.  Generation is deterministic given the
request's sampling seed (the router injects one when the client
sampled without a seed), so the retry replays the same stream; for SSE
relays the retry skips the tokens already forwarded — zero lost, zero
duplicated tokens, counter-asserted via
``mpi_operator_router_{retries,requests_lost}_total``.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..telemetry.metrics import (Registry, expose_with_defaults,
                                 new_router_metrics, record_build_info)
from ..analysis.lockcheck import name_lock
from ..telemetry.trace import TraceContext, default_tracer
from .batcher import prefix_page_digests


class _ClientGone(ConnectionError):
    """The DOWNSTREAM client went away mid-relay.  Distinct from
    upstream (replica) failure: it must never mark a replica dead,
    burn the retry, or count a lost request."""


# Per-process router generation counter: request trace ids must be
# unique across router INSTANCES too — a later router handed the same
# ephemeral port by the OS must not restart req-<port>-1 and merge two
# different requests' spans under one trace id.
_ROUTER_GENERATIONS = itertools.count(1)

# Bound on the session-affinity map: oldest pins evict FIFO past this,
# so a long-lived router under unbounded distinct sessions stays O(1)
# memory (a re-seen evicted session just re-pins via prefix/P2C).
MAX_SESSIONS = 65536


class _Replica:
    """Router-side view of one fleet member."""

    def __init__(self, name: str, url: str, role: str = "unified",
                 model: str = ""):
        self.name = name
        self.url = url  # http://host:port
        self.alive = True
        self.outstanding = 0          # router-local in-flight requests
        self.queue_depth = 0          # last-polled batcher queue depth
        self.active_slots = 0
        self.slots = 0
        self.page_size = 0
        self.digests: set = set()     # advertised prefix-cache index
        # Disaggregated serving (ISSUE 17): the stage this replica runs
        # ("unified" serves both), the model it holds ("" = any), and
        # its last-polled free pool blocks — the decode-placement
        # signal (a decode replica out of blocks defers admissions).
        self.role = role
        self.model = model
        self.free_blocks = 0

    @property
    def load(self) -> float:
        return self.outstanding + self.queue_depth + self.active_slots

    def serves(self, model: str) -> bool:
        """Model match: a replica with no declared model serves any
        request; a request with no model accepts any replica."""
        return not self.model or not model or self.model == model

    def host_port(self) -> tuple:
        hostport = self.url.split("//", 1)[-1]
        host, _, port = hostport.partition(":")
        return host, int(port or 80)


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def _respond(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        router: "FleetRouter" = self.server.router  # type: ignore
        if self.path == "/healthz":
            n = len(router.healthy_replicas())
            self._respond(200 if n else 503,
                          {"status": "ok" if n else "no-replicas",
                           "replicas": n})
        elif self.path == "/metrics":
            body = expose_with_defaults(router.telemetry_registry).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._respond(404, {"error": "not found"})

    def do_POST(self):
        if self.path != "/generate":
            return self._respond(404, {"error": "not found"})
        router: "FleetRouter" = self.server.router  # type: ignore
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length))
        except Exception as exc:
            return self._respond(400, {"error": str(exc)})
        try:
            if payload.get("stream"):
                router.relay_stream(payload, self)
            else:
                code, body = router.relay(payload)
                self._respond(code, body)
        except (BrokenPipeError, ConnectionResetError, OSError):
            self.close_connection = True


class FleetRouter:
    """See module docstring.  ``policy``: "prefix" (affinity → prefix →
    P2C, the default) or "round_robin" (placement-blind baseline)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 policy: str = "prefix", refresh_interval: float = 0.25,
                 upstream_timeout: float = 300.0, seed: int = 0,
                 telemetry_registry: Optional[Registry] = None):
        if policy not in ("prefix", "round_robin"):
            raise ValueError(f"unknown router policy {policy!r}")
        self.policy = policy
        self.refresh_interval = float(refresh_interval)
        self.upstream_timeout = float(upstream_timeout)
        self.telemetry_registry = telemetry_registry or Registry()
        self.telemetry = new_router_metrics(self.telemetry_registry)
        self._replicas: Dict[str, _Replica] = {}
        self._sessions: Dict[str, str] = {}  # session -> replica name
        # Multi-model serving (ISSUE 17): per-model traffic counters
        # (the rebalancer's prefill/decode ratio signal and the idle
        # reaper's last-arrival clock), measured cold starts, and the
        # scale-to-zero wake hook (set_waker).
        self._model_stats: Dict[str, dict] = {}
        self._cold_starts: Dict[str, List[float]] = {}
        self._waker = None
        # Named hot lock: blocking here serializes every placement
        # (docs/ANALYSIS.md, lockcheck).
        self._lock = name_lock(threading.Lock(), "router.state")
        self._rng = random.Random(seed)
        self._rr_counter = 0
        self._page_size = 0
        self._stop = threading.Event()
        self._refresher: Optional[threading.Thread] = None
        self._http = ThreadingHTTPServer((host, port), _RouterHandler)
        self._http.router = self  # type: ignore[attr-defined]
        self.port = self._http.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._req_counter = 0
        # pid + per-process generation uniquify request trace ids
        # across router instances and across processes (replica-side
        # spans of two deployments must never alias one trace).
        self._trace_prefix = (f"req-{os.getpid() & 0xFFFFFF:x}"
                              f"-{next(_ROUTER_GENERATIONS)}")
        record_build_info()

    # -- membership --------------------------------------------------------
    def add_replica(self, name: str, url: str, role: str = "unified",
                    model: str = "") -> None:
        with self._lock:
            self._replicas[name] = _Replica(name, url, role=role,
                                            model=model)
        self.refresh_replica(name)
        self._update_replica_gauge()

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)
            self._sessions = {s: r for s, r in self._sessions.items()
                              if r != name}
        self._update_replica_gauge()

    def healthy_replicas(self) -> List[_Replica]:
        with self._lock:
            return [r for r in self._replicas.values() if r.alive]

    def _update_replica_gauge(self) -> None:
        self.telemetry["replicas"].set(len(self.healthy_replicas()))

    def mark_dead(self, name: str) -> None:
        with self._lock:
            r = self._replicas.get(name)
            if r is not None:
                r.alive = False
        self._update_replica_gauge()

    def replica_stats(self) -> dict:
        """Autoscaler-facing snapshot: per-replica load plus fleet
        aggregates (serving/autoscaler.py)."""
        with self._lock:
            reps = list(self._replicas.values())
        per = [{"name": r.name, "alive": r.alive,
                "queue_depth": r.queue_depth,
                "outstanding": r.outstanding,
                "active_slots": r.active_slots, "slots": r.slots}
               for r in reps]
        alive = [p for p in per if p["alive"]]
        return {
            "replicas": len(alive),
            "queue_depth_total": sum(p["queue_depth"] + p["outstanding"]
                                     for p in alive),
            "per_replica": per,
        }

    # -- multi-model accounting / scale-to-zero ---------------------------
    def set_waker(self, waker) -> None:
        """Install the scale-to-zero wake hook: ``waker(model) -> bool``
        blocks until the model's replicas are serving (True) or the
        wake failed (False).  With no waker installed, a request for a
        fully-drained model is load-shed with 503 — the 503-vs-wake
        decision is exactly whether this hook exists."""
        self._waker = waker

    def _model_stat(self, model: str) -> dict:
        # caller holds self._lock
        s = self._model_stats.get(model)
        if s is None:
            s = {"requests": 0, "prefill_tokens": 0, "decode_tokens": 0,
                 "inflight": 0, "last_request": 0.0}
            self._model_stats[model] = s
        return s

    def model_stats(self) -> Dict[str, dict]:
        """Per-model traffic snapshot: cumulative prompt (prefill) and
        emitted (decode) token counters — the pool rebalancer's ratio
        signal — plus in-flight count and last-arrival time (the idle
        reaper's drain signal)."""
        with self._lock:
            return {m: dict(s) for m, s in self._model_stats.items()}

    def _note_arrival(self, payload: dict) -> str:
        model = str(payload.get("model", "") or "")
        prompt = len(self._prompt_row(payload))
        with self._lock:
            s = self._model_stat(model)
            s["requests"] += 1
            s["prefill_tokens"] += prompt
            s["inflight"] += 1
            s["last_request"] = time.monotonic()
        return model

    def _note_done(self, model: str, emitted: int) -> None:
        with self._lock:
            s = self._model_stat(model)
            s["inflight"] -= 1
            s["decode_tokens"] += int(emitted)

    def _ensure_capacity(self, model: str) -> None:
        """Scale-to-zero wake-on-traffic: when no decode-capable
        replica exists for the request's model and a waker is
        installed, wake the model SYNCHRONOUSLY (the requester pays
        the cold start — measured and published per model) instead of
        load-shedding with 503."""
        with self._lock:
            if any(r.alive and r.role != "prefill" and r.serves(model)
                   for r in self._replicas.values()):
                return
            waker = self._waker
        if waker is None:
            return  # _pick will raise -> clean 503 load-shed
        self.telemetry["model_wakes"].labels(model or "-").inc()
        t0 = time.perf_counter()
        ok = False
        try:
            ok = bool(waker(model))
        finally:
            cold = time.perf_counter() - t0
            if ok:
                self.telemetry["cold_start_seconds"].labels(
                    model or "-").observe(cold)
                with self._lock:
                    self._cold_starts.setdefault(model, []).append(cold)

    def cold_start_stats(self) -> Dict[str, List[float]]:
        """Measured cold-start durations by model (routing metrics
        surface for the scale-to-zero acceptance gate; also exposed as
        the mpi_operator_serve_cold_start_seconds histogram)."""
        with self._lock:
            return {m: list(v) for m, v in self._cold_starts.items()}

    # -- disaggregated prefill stage --------------------------------------
    def _dispatch_prefill(self, payload: dict, decode: _Replica,
                          plan: dict, ctx) -> None:
        """Run the prefill stage for a disaggregated request: pick the
        least-queued prefill replica for the model and have it prefill
        the prompt + push the pages the decode replica is missing.
        Best-effort — any failure falls back to decode-side
        self-prefill (the decode replica simply misses its prefix
        cache), so correctness never rides on this path."""
        missing = plan.get("missing") or []
        if not missing:
            return
        model = plan.get("model", "")
        with self._lock:
            pool = [r for r in self._replicas.values()
                    if r.alive and r.role == "prefill"
                    and r.serves(model)]
            if not pool:
                return
            # Prefill placement is queue-depth driven (ISSUE 17): the
            # stage is compute-bound and FIFO, so shortest queue wins.
            pf = min(pool, key=lambda r: (r.queue_depth + r.outstanding,
                                          r.name))
            pf.outstanding += 1
        self.telemetry["disagg_prefills"].inc()
        import http.client
        try:
            with default_tracer().span("disagg_prefill", ctx=ctx,
                                       replica=pf.name):
                host, port = pf.host_port()
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.upstream_timeout)
                body = json.dumps({
                    "tokens": self._prompt_row(payload),
                    "transfer": {"url": decode.url,
                                 "have": plan.get("have") or []},
                    "trace_context": payload.get("trace_context"),
                }).encode()
                try:
                    conn.request(
                        "POST", "/prefill", body=body,
                        headers={"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    reply = json.loads(resp.read())
                    status = resp.status
                finally:
                    conn.close()
            if status != 200:
                raise RuntimeError(reply.get("error", status))
            self.telemetry["kv_pages_shipped"].inc(
                int(reply.get("shipped", 0)))
            self.telemetry["kv_pages_deduped"].inc(
                int(reply.get("deduped", 0)))
            self.telemetry["kv_transfer_bytes"].inc(
                int(reply.get("bytes", 0)))
            # The prefill replica now holds these pages too: advertise
            # them so same-prefix requests dedup before the next poll.
            with self._lock:
                pf.digests.update(plan.get("digests") or [])
        except Exception:
            self.telemetry["disagg_fallback"].inc()
            self._replica_dead(pf)  # transport death marks it dead
        finally:
            with self._lock:
                pf.outstanding -= 1

    # -- replica state refresh --------------------------------------------
    def refresh_replica(self, name: str) -> bool:
        with self._lock:
            r = self._replicas.get(name)
        if r is None:
            return False
        import http.client
        try:
            host, port = r.host_port()
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("GET", "/fleet-state")
                resp = conn.getresponse()
                state = json.loads(resp.read())
            finally:
                conn.close()
        except Exception:
            if r.alive:
                self.mark_dead(name)
            return False
        with self._lock:
            r.queue_depth = int(state.get("queue_depth", 0))
            r.active_slots = int(state.get("active_slots", 0))
            r.slots = int(state.get("slots", 0))
            r.page_size = int(state.get("page_size", 0))
            # Authoritative replace: evictions on the replica must
            # retire optimistic entries, or routing chases ghosts.
            r.digests = set(state.get("prefix_digests", ()))
            r.alive = bool(state.get("healthy", True))
            r.free_blocks = int(state.get("free_blocks", 0))
            # The replica's own identity wins over the add_replica
            # hint (a pool rebalance restarts a replica under a new
            # role; the router must follow, not remember).
            r.role = str(state.get("role", r.role) or "unified")
            r.model = str(state.get("model", r.model) or "")
            if r.page_size:
                self._page_size = r.page_size
        self._update_replica_gauge()
        return True

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval):
            # Concurrent per-replica polls: one hung replica (2s
            # timeout) must not hold every other member's queue-depth/
            # digest state stale for the whole cycle.
            polls = [threading.Thread(target=self.refresh_replica,
                                      args=(name,), daemon=True)
                     for name in list(self._replicas)]
            for t in polls:
                t.start()
            for t in polls:
                t.join(timeout=2.5)

    # -- placement ---------------------------------------------------------
    @staticmethod
    def _prompt_row(payload: dict) -> List[int]:
        tokens = payload.get("tokens") or []
        if tokens and isinstance(tokens[0], (list, tuple)):
            tokens = tokens[0] if tokens else []
        return [int(t) for t in tokens]

    def _pick(self, payload: dict, exclude=(),
              plan: Optional[dict] = None) -> _Replica:
        """Choose a replica for this request (see module docstring for
        the policy ladder) and account the placement path.

        Only decode-capable replicas (role "unified" or "decode",
        model match) are candidates — prefill replicas never take
        /generate.  When ``plan`` is given it is filled with the
        disagg prefill stage to run BEFORE the relay: the prompt's
        chain digests, the subset the chosen decode replica was
        missing (pre-optimistic-extension, so dedup is honest), and
        the winner's advertised ``have`` set for the transfer."""
        # Digest the prompt BEFORE taking the router lock: the hash is
        # a pure function of payload + page_size, and hashing long
        # prompts under the lock would serialize every placement and
        # in-flight-counter update behind it.
        digests: List[str] = []
        page = self._page_size
        if self.policy != "round_robin" and page > 0:
            digests = prefix_page_digests(self._prompt_row(payload),
                                          page)
        model = str(payload.get("model", "") or "")
        with self._lock:
            candidates = [r for r in self._replicas.values()
                          if r.alive and r.name not in exclude
                          and r.role != "prefill" and r.serves(model)]
            if not candidates:
                raise RuntimeError(
                    f"no healthy replicas"
                    + (f" for model {model!r}" if model else ""))
            if self.policy == "round_robin":
                self._rr_counter += 1
                pick = candidates[self._rr_counter % len(candidates)]
                self.telemetry["routed_total"].labels("rr").inc()
                return pick
            session = payload.get("session")
            pick = path = None
            if session is not None:
                pinned = self._replicas.get(
                    self._sessions.get(str(session), ""))
                if pinned is not None and pinned.alive \
                        and pinned.name not in exclude \
                        and pinned.role != "prefill" \
                        and pinned.serves(model):
                    pick, path = pinned, "affinity"
            if pick is None and digests:
                best_hits = 0
                best: List[_Replica] = []
                for r in candidates:
                    hits = 0
                    for d in digests:
                        if d not in r.digests:
                            break
                        hits += 1
                    if hits > best_hits:
                        best_hits, best = hits, [r]
                    elif hits and hits == best_hits:
                        best.append(r)
                if best:
                    pick = min(best, key=lambda r: r.load)
                    path = "prefix"
            if pick is None:
                two = (self._rng.sample(candidates, 2)
                       if len(candidates) >= 2 else candidates)
                # Decode placement is block-pressure aware: load ties
                # break toward the replica with more free KV blocks
                # (ISSUE 17 — a decode replica out of blocks defers
                # admissions even at queue depth 0).
                pick = min(two, key=lambda r: (r.load, -r.free_blocks))
                path = "p2c"
            if plan is not None:
                plan["digests"] = digests
                plan["have"] = sorted(pick.digests)
                plan["missing"] = [d for d in digests
                                   if d not in pick.digests]
                plan["model"] = model
            # Optimistic index extension: the pick will register these
            # pages at admission (or receive them over the KV-transfer
            # channel); advertise them now so the next same-prefix
            # request follows without waiting for a poll.
            pick.digests.update(digests)
            if session is not None:
                self._sessions[str(session)] = pick.name
                while len(self._sessions) > MAX_SESSIONS:
                    self._sessions.pop(next(iter(self._sessions)))
            self.telemetry["routed_total"].labels(path).inc()
            return pick

    # -- causal tracing ----------------------------------------------------
    def _begin_trace(self, payload: dict) -> TraceContext:
        """Root one request's causal trace and inject the context into
        the upstream payload, so the replica's queue-wait/prefill spans
        parent to this router's ``request`` span across the HTTP hop.
        The root span itself is emitted at request end (_end_trace)
        with the id reserved here."""
        with self._lock:
            self._req_counter += 1
            n = self._req_counter
        trace_id = f"{self._trace_prefix}-{n}"
        root_id = default_tracer().allocate_id()
        ctx = TraceContext(trace_id=trace_id, span_id=root_id)
        payload["trace_context"] = ctx.encode()
        return ctx

    def _end_trace(self, ctx: TraceContext, start_wall: float,
                   dur: float, **attrs) -> None:
        default_tracer().emit("request", ts=start_wall, dur=dur,
                              trace_id=ctx.trace_id,
                              span_id=ctx.span_id, **attrs)

    def _trace_ttft(self, ctx: TraceContext, start_wall: float,
                    ttft: float) -> None:
        """The traced-TTFT milestone: router accept → first upstream
        token visible downstream — the request decomposition's terminal
        segment and the soak scorecard's traced_ttft_p99 source."""
        default_tracer().emit("request_ttft", ts=start_wall, dur=ttft,
                              ctx=ctx)

    # -- upstream plumbing -------------------------------------------------
    def _prepare(self, payload: dict) -> dict:
        # A sampled request without a seed would re-sample differently
        # on a retry replica; pin one so the replay is byte-identical.
        if float(payload.get("temperature", 0.0) or 0.0) > 0.0 \
                and payload.get("seed") is None:
            with self._lock:
                payload["seed"] = self._rng.getrandbits(31)
        return payload

    def _open(self, replica: _Replica, payload: dict):
        """POST /generate on the replica; returns (conn, response)."""
        import http.client
        host, port = replica.host_port()
        conn = http.client.HTTPConnection(host, port,
                                          timeout=self.upstream_timeout)
        body = json.dumps(payload).encode()
        conn.request("POST", "/generate", body=body,
                     headers={"Content-Type": "application/json"})
        return conn, conn.getresponse()

    def _replica_dead(self, replica: _Replica) -> bool:
        """Health-check a replica that returned an application error:
        only a dead replica's errors are retried (a live replica's
        error is deterministic and must be relayed, not replayed)."""
        import http.client
        try:
            host, port = replica.host_port()
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("GET", "/healthz")
                ok = conn.getresponse().status == 200
            finally:
                conn.close()
        except Exception:
            ok = False
        if not ok:
            self.mark_dead(replica.name)
        return not ok

    # -- request relay -----------------------------------------------------
    def relay(self, payload: dict) -> tuple:
        """Non-streaming relay with the exactly-once retry contract.
        Returns (status, body-dict) for the front-door handler."""
        self.telemetry["requests_total"].inc()
        payload = self._prepare(payload)
        model = self._note_arrival(payload)
        ctx = self._begin_trace(payload)
        start = time.perf_counter()
        start_wall = time.time()
        emitted = 0
        try:
            status, body = self._relay_attempts(payload, ctx, start,
                                                start_wall)
            if status == 200:
                rows = body.get("tokens") or []
                emitted = sum(len(r) for r in rows
                              if isinstance(r, (list, tuple)))
            return status, body
        finally:
            self._note_done(model, emitted)
            self._end_trace(ctx, start_wall, time.perf_counter() - start)

    def _relay_attempts(self, payload: dict, ctx: TraceContext,
                        start: float, start_wall: float) -> tuple:
        self._ensure_capacity(str(payload.get("model", "") or ""))
        exclude: List[str] = []
        for attempt in range(2):
            plan: dict = {}
            try:
                with default_tracer().span("route", ctx=ctx,
                                           attempt=attempt):
                    replica = self._pick(payload, exclude=exclude,
                                         plan=plan)
            except RuntimeError as exc:
                # Lost means an ACCEPTED request died past its retry;
                # a pre-dispatch 503 (no healthy replicas, nothing
                # attempted yet) is clean load-shedding, not a broken
                # retry contract.
                if attempt:
                    self.telemetry["requests_lost_total"].inc()
                return 503, {"error": str(exc)}
            self._dispatch_prefill(payload, replica, plan, ctx)
            with self._lock:
                replica.outstanding += 1
            failed = False
            try:
                conn, resp = self._open(replica, payload)
                try:
                    body = json.loads(resp.read())
                    status = resp.status
                finally:
                    conn.close()
            except Exception:
                failed = True
            finally:
                with self._lock:
                    replica.outstanding -= 1
            # Any response from a LIVE replica is the request's
            # outcome (errors included, 5xx or otherwise) — only a
            # dead replica's response or a transport failure retries,
            # mirroring relay_stream's non-200 path.
            if not failed and \
                    (status == 200 or not self._replica_dead(replica)):
                if status == 200:
                    # Non-streaming: the client sees nothing before the
                    # whole response, so completion IS first-token
                    # visibility — keeps the autoscaler's TTFT-SLO
                    # trigger live for plain-JSON clients.
                    ttft = time.perf_counter() - start
                    self.telemetry["ttft_seconds"].observe(ttft)
                    self._trace_ttft(ctx, start_wall, ttft)
                return status, body
            # Transport failure or a dead replica's error: retry once.
            if failed:
                self.mark_dead(replica.name)
            if attempt == 0:
                self.telemetry["retries_total"].inc()
                exclude.append(replica.name)
                continue
        self.telemetry["requests_lost_total"].inc()
        return 502, {"error": f"replica {replica.name} died and the "
                              f"single retry failed"}

    def relay_stream(self, payload: dict, handler) -> None:
        """SSE relay: forward upstream token events; on replica death
        mid-stream, replay on a healthy replica once, skipping the
        tokens already forwarded (deterministic generation given the
        pinned seed makes the replay exact)."""
        self.telemetry["requests_total"].inc()
        payload = self._prepare(payload)
        model = self._note_arrival(payload)
        ctx = self._begin_trace(payload)
        start = time.perf_counter()
        start_wall = time.time()
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        def emit(event: dict) -> None:
            # A failed client write is the CLIENT's death, not the
            # replica's: re-raise typed so the relay loop below aborts
            # without marking the upstream dead or burning the retry.
            try:
                chunk = f"data: {json.dumps(event)}\n\n".encode()
                handler.wfile.write(f"{len(chunk):x}\r\n".encode()
                                    + chunk + b"\r\n")
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError) as exc:
                raise _ClientGone(str(exc)) from exc

        def finish() -> None:
            try:
                handler.wfile.write(b"0\r\n\r\n")
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError) as exc:
                raise _ClientGone(str(exc)) from exc

        emitted = [0]
        try:
            self._relay_stream_attempts(payload, ctx, start, start_wall,
                                        emit, finish, emitted)
        finally:
            self._note_done(model, emitted[0])
            self._end_trace(ctx, start_wall,
                            time.perf_counter() - start, stream=True)

    def _relay_stream_attempts(self, payload: dict, ctx: TraceContext,
                               start: float, start_wall: float,
                               emit, finish,
                               emitted: Optional[list] = None) -> None:
        self._ensure_capacity(str(payload.get("model", "") or ""))
        sent = 0          # tokens already forwarded to the client
        first_at = None
        exclude: List[str] = []
        for attempt in range(2):
            plan: dict = {}
            try:
                with default_tracer().span("route", ctx=ctx,
                                           attempt=attempt):
                    replica = self._pick(payload, exclude=exclude,
                                         plan=plan)
            except RuntimeError as exc:
                if attempt:  # see relay(): pre-dispatch 503 != lost
                    self.telemetry["requests_lost_total"].inc()
                emit({"error": str(exc)})
                return finish()
            self._dispatch_prefill(payload, replica, plan, ctx)
            with self._lock:
                replica.outstanding += 1
            died = False
            try:
                try:
                    conn, resp = self._open(replica, payload)
                except Exception:
                    died = True
                    conn = None
                if not died and resp.status != 200:
                    # Plain-JSON rejection instead of an SSE stream: a
                    # LIVE replica's error is the request's outcome —
                    # relay it without marking the replica dead or
                    # burning the retry (only a dead replica's error
                    # re-dispatches, mirroring relay()).
                    try:
                        msg = json.loads(resp.read()).get(
                            "error", f"upstream status {resp.status}")
                    except Exception:
                        msg = f"upstream status {resp.status}"
                    conn.close()
                    if not self._replica_dead(replica):
                        emit({"error": msg})
                        return finish()
                    died = True
                if not died:
                    try:
                        skip = sent
                        for event in self._sse_events(resp):
                            if "token" in event:
                                if skip > 0:
                                    skip -= 1
                                    continue
                                if first_at is None:
                                    first_at = time.perf_counter()
                                    self.telemetry["ttft_seconds"]\
                                        .observe(first_at - start)
                                    self._trace_ttft(ctx, start_wall,
                                                     first_at - start)
                                sent += 1
                                if emitted is not None:
                                    emitted[0] = sent
                                emit(event)
                            elif "error" in event:
                                # A live replica's error is the
                                # request's real outcome; a dead one's
                                # is retried below.
                                if not self._replica_dead(replica):
                                    emit(event)
                                    return finish()
                                died = True
                                break
                            elif event.get("done"):
                                emit(event)
                                return finish()
                        else:
                            # Upstream closed without done/error.
                            died = True
                    except _ClientGone:
                        # Downstream client went away: abort the relay
                        # quietly — the replica is fine (closing the
                        # upstream connection cancels its slot), no
                        # retry, no lost-request accounting.
                        raise
                    except Exception:
                        died = True
                    finally:
                        conn.close()
            finally:
                with self._lock:
                    replica.outstanding -= 1
            if died:
                self.mark_dead(replica.name)
                if attempt == 0:
                    self.telemetry["retries_total"].inc()
                    exclude.append(replica.name)
                    continue
        self.telemetry["requests_lost_total"].inc()
        emit({"error": f"replica {replica.name} died and the single "
                       f"retry failed"})
        finish()

    @staticmethod
    def _sse_events(resp):
        """Parse `data: {...}` events off an upstream SSE response
        (http.client undoes the chunked framing)."""
        while True:
            line = resp.readline()
            if not line:
                return
            line = line.strip()
            if line.startswith(b"data: "):
                yield json.loads(line[6:])

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetRouter":
        self._thread = threading.Thread(target=self._http.serve_forever,
                                        daemon=True, name="fleet-router")
        self._thread.start()
        self._refresher = threading.Thread(target=self._refresh_loop,
                                           daemon=True,
                                           name="fleet-router-refresh")
        self._refresher.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._refresher is not None:
            self._refresher.join(timeout=5)
            self._refresher = None
        if self._thread is not None:
            self._http.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._http.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
