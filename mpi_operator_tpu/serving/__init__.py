"""Model serving over the KV-cache decode path."""

from .server import InferenceServer  # noqa: F401
