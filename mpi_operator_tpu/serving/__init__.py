"""Model serving over the KV-cache decode path."""

from .batcher import ContinuousBatcher  # noqa: F401
from .server import InferenceServer  # noqa: F401
