"""Model serving over the KV-cache decode path, and the fleet layer
(router + autoscaler + replica runner) that scales it horizontally."""

from .batcher import ContinuousBatcher  # noqa: F401
from .server import InferenceServer  # noqa: F401
from .router import FleetRouter  # noqa: F401
from .autoscaler import ServeAutoscaler  # noqa: F401
from .fleet import LocalServeFleet, ServeReplicaRunner  # noqa: F401
