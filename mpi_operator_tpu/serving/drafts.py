"""Training-free draft strategies for speculative decoding.

Prompt-lookup decoding (PLD): propose the continuation of the request's
OWN context.  Match the longest n-gram suffix of the committed stream
(prompt + generated) against an earlier occurrence and copy the k tokens
that followed it.  No draft model, no draft cache, no extra memory —
drafting is microseconds of host work, so every accepted token is pure
win: one width-(k+1) target verify replaces up to k+1 width-1 decode
steps.  Wins exactly where real serving workloads speculate well —
summarization, code editing, retrieval-grounded generation, and any
decode that re-quotes its context (on TPU the verify is additionally
MXU-friendly where width-1 decode is bandwidth-bound).

Same acceptance rule as the model-draft path (argmax longest-prefix +
bonus), so the emitted stream stays a valid greedy decode of the target
— speculation changes latency, never content.

No reference counterpart: kubeflow/mpi-operator ships no inference
stack (SURVEY.md §2.2); technique is public (prompt-lookup /
n-gram-matching speculative decoding).
"""

from __future__ import annotations

from typing import List, Sequence

DRAFT_STRATEGIES = ("prompt_lookup",)


def propose_prompt_lookup(history: Sequence[int], k: int,
                          max_ngram: int = 3,
                          window: int = 4096) -> List[int]:
    """Propose k tokens by n-gram continuation lookup over ``history``.

    Scans n-gram sizes ``max_ngram..1``; for each, finds the MOST RECENT
    earlier occurrence of the history's length-n suffix and copies the k
    tokens after it.  A continuation shorter than k is extended by
    cycling it (the repetition hypothesis that justified the match).
    No occurrence at any n: propose k repeats of the last
    token (cheap guess; rejection costs nothing — the verify forward has
    the same width either way).
    """
    import numpy as np

    if k < 1:
        return []
    if len(history) == 0:
        return [0] * k
    # Bounded window: matches in ancient context are rarely better than
    # recent ones, and the scan must stay cheap inside the serial decode
    # loop (numpy shifted-compare, not Python slices — O(n·window) C ops
    # per tick per slot).
    h = np.asarray(history[-window:] if len(history) > window
                   else history, dtype=np.int64)
    size = int(h.size)
    for n in range(min(max_ngram, size - 1), 0, -1):
        tail = h[size - n:]
        # Candidate starts 0..size-n-1 (the suffix itself sits at size-n).
        match = np.ones(size - n, dtype=bool)
        for j in range(n):
            match &= h[j:size - n + j] == tail[j]
        idx = np.nonzero(match)[0]
        if idx.size:
            s = int(idx[-1])  # most recent occurrence
            # s <= size-n-1, so the continuation base is never empty.
            base = h[s + n:]
            return [int(base[j % base.size]) for j in range(k)]
    return [int(h[-1])] * k
