"""Queue-driven fleet autoscaler with hysteresis.

Observes the router's per-replica load (queued + in-flight requests,
the same queue-depth signal `serving_queue_depth` exports) and the
router TTFT histogram, and steers the ServeJob's replica count by
writing ``status.desired_replicas`` through the status subresource —
the ServeJobController owns ALL actuation (pod create/delete), so a
scaling decision is an auditable status write, never a side channel.

Hysteresis, so the fleet neither flaps nor reacts to one bursty poll:

- **up**: mean queued-per-replica above ``target_queue_depth`` (or TTFT
  p99 over the optional SLO) for ``up_stable`` consecutive polls adds
  one replica;
- **down**: mean queued-per-replica at/below ``scale_down_queue_depth``
  for ``down_stable`` consecutive polls removes one replica — the down
  window is the longer one, since a too-eager scale-down immediately
  re-pays a replica cold start.

Bounds come from the ServeJob's ``spec.autoscale``
(min_replicas/max_replicas); the controller clamps again on its side,
so even a buggy or stale status write cannot scale past the spec.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..k8s.apiserver import TRANSPORT_ERRORS, Clientset


def histogram_quantile(snapshot: dict, q: float) -> float:
    """Quantile from a cumulative-bucket histogram snapshot
    (telemetry.metrics.Histogram.snapshot form): the upper bound of the
    first bucket whose cumulative count covers the quantile."""
    total = snapshot.get("count", 0)
    if total <= 0:
        return 0.0
    need = q * total
    for bound, cum in snapshot["buckets"].items():
        if cum >= need:
            return float(bound)
    return float(max(snapshot["buckets"]))


class ServeAutoscaler:
    """Polls ``router.replica_stats()`` and writes the ServeJob's
    ``status.desired_replicas``."""

    def __init__(self, clientset: Clientset, namespace: str, name: str,
                 router, poll_interval: float = 0.5,
                 up_stable: int = 2, down_stable: int = 4,
                 model: str = ""):
        self.client = clientset
        self.namespace = namespace
        self.name = name
        self.router = router
        self.poll_interval = float(poll_interval)
        self.up_stable = int(up_stable)
        self.down_stable = int(down_stable)
        # Label for the cold-start histogram; a multi-model fleet runs
        # one autoscaler per ServeJob, so the job IS the model.
        self.model = model or name
        self._up_hits = 0
        self._down_hits = 0
        self._ttft_count_seen = 0
        self._req_count_seen = 0.0
        self._wake_started: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Observable trail for tests/smokes: every applied transition
        # as (old_desired, new_desired, reason).
        self.transitions: list = []
        # Measured wake->serving cold starts (seconds), mirrored into
        # mpi_operator_serve_cold_start_seconds{model} on the router's
        # registry.
        self.cold_starts: list = []

    # -- decision ----------------------------------------------------------
    def _ttft_p99_since_last_poll(self) -> Optional[float]:
        hist = self.router.telemetry["ttft_seconds"]
        snap = hist.snapshot()
        if snap["count"] <= self._ttft_count_seen:
            return None
        # Approximate windowing: quantile over the cumulative histogram
        # (good enough for an SLO trigger; the counter watermark just
        # prevents acting on a silent, idle histogram).
        self._ttft_count_seen = snap["count"]
        return histogram_quantile(snap, 0.99)

    def evaluate_once(self) -> Optional[int]:
        """One poll: returns the new desired count when a transition
        was applied, else None."""
        try:
            job = self.client.serve_jobs(self.namespace).get(self.name)
        except TRANSPORT_ERRORS:
            return None  # ServeJob gone / API weather: next poll
        auto = job.spec.autoscale
        if auto is None:
            return None
        current = job.status.desired_replicas
        if current is None:
            current = job.spec.replicas or auto.min_replicas
        current = max(auto.min_replicas,
                      min(auto.max_replicas, current))

        stats = self.router.replica_stats()
        arrivals = self.router.telemetry["requests_total"].value \
            - self._req_count_seen
        self._req_count_seen += arrivals
        if stats["replicas"] == 0:
            self._down_hits = 0
            if current > 0:
                # Full-replica outage: zero alive replicas reads as
                # zero queue — absence of signal, not of demand.  Hold
                # rather than shrink the fleet exactly when it needs
                # capacity back.
                return None
            if arrivals <= 0:
                return None
            # Scaled to zero but traffic is arriving (the router is
            # 503ing it): demand is the request stream itself — wake
            # the fleet rather than deadlock at zero forever.
            desired = max(1, auto.min_replicas)
            try:
                self.client.serve_jobs(self.namespace).patch_status(
                    self.name, desired_replicas=desired,
                    scaling_reason="up: traffic while scaled to zero")
            except TRANSPORT_ERRORS:
                return None  # apiserver weather: next poll re-asserts
            self.transitions.append(
                (current, desired, "up: traffic while scaled to zero"))
            # Cold-start clock starts at the wake DECISION — the user
            # request is already waiting, so everything from here to
            # the first Ready replica is cost the requester pays.
            if self._wake_started is None:
                self._wake_started = time.monotonic()
            return desired
        replicas = stats["replicas"]
        if self._wake_started is not None:
            # First poll with a live replica after a wake: the fleet is
            # serving again — that elapsed span is the model's measured
            # cold-start cost (per-model histogram, ISSUE 17).
            elapsed = time.monotonic() - self._wake_started
            self._wake_started = None
            self.cold_starts.append(elapsed)
            hist = self.router.telemetry.get("cold_start_seconds")
            if hist is not None:
                hist.labels(self.model).observe(elapsed)
        per_replica = stats["queue_depth_total"] / replicas
        ttft_p99 = self._ttft_p99_since_last_poll()

        over = per_replica > auto.target_queue_depth
        reason = f"queue depth {per_replica:.2f}/replica"
        if not over and auto.ttft_p99_slo_seconds is not None \
                and ttft_p99 is not None \
                and ttft_p99 > auto.ttft_p99_slo_seconds:
            over = True
            reason = f"ttft p99 {ttft_p99:.3f}s over SLO"
        under = per_replica <= auto.scale_down_queue_depth

        if over:
            self._up_hits += 1
            self._down_hits = 0
        elif under:
            self._down_hits += 1
            self._up_hits = 0
        else:
            self._up_hits = self._down_hits = 0

        desired = current
        if self._up_hits >= self.up_stable \
                and current < auto.max_replicas:
            desired = current + 1
            self._up_hits = 0
        elif self._down_hits >= self.down_stable \
                and current > auto.min_replicas:
            desired = current - 1
            self._down_hits = 0
        if desired == current and job.status.desired_replicas is not None:
            return None
        direction = ("up" if desired > current
                     else "down" if desired < current else "hold")
        reason = f"{direction}: {reason}"
        try:
            self.client.serve_jobs(self.namespace).patch_status(
                self.name, desired_replicas=desired,
                scaling_reason=reason)
        except TRANSPORT_ERRORS:
            return None  # apiserver weather: next poll re-asserts
        if desired != current:
            self.transitions.append((current, desired, reason))
        return desired

    # -- lifecycle ---------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            self.evaluate_once()

    def start(self) -> "ServeAutoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
