"""Disaggregated prefill/decode serve fleet (ISSUE 17).

Prefill is compute-bound (one long matmul-heavy pass over the prompt);
decode is memory-bandwidth-bound (one token per tick, weights + KV
streamed every step).  On a unified replica the two interfere: a 32k
chunked prefill holds the device lock through admission and every
decode stream on that replica stalls for the duration.  This module
splits each model's replicas into two pools —

- **prefill pool**: replicas that ONLY run chunked prefill.  The
  router sends them the prompt with ``max_new_tokens=1``; the request
  retires at admission (zero decode ticks) and the populated KV pages
  are pushed to the chosen decode replica over the content-addressed
  page-transfer channel (serving/kv_transfer.py).  Pages the decode
  replica already advertises (prefix_page_digests chain) are never
  shipped.
- **decode pool**: replicas that serve /generate.  A transferred
  prefix is a prefix-cache hit, so the decode replica prefills only
  the suffix the transfer did not cover — output stays byte-identical
  to unified serving.

The router schedules the stages independently (prefill by queue
depth, decode by free KV blocks — serving/router.py), so a long
prompt saturates a prefill replica while decode p99 stands still.

Multi-model + weight paging + scale-to-zero: each model is a pool
pair charged against a PR 9 ClusterQueue through the
:class:`~..sched.capacity.ChipLedger`.  An idle model (no in-flight
requests past its idle timeout) is *paged out* — replicas stopped,
chips released back to the queue where training gangs can take them —
and woken synchronously by the router's wake-on-traffic hook when the
next request for it arrives (the requester pays the measured cold
start).  The measured cold-start cost is priced into the page-out
decision: a model that is expensive to wake must be idle
proportionally longer before it is drained.

A :class:`PoolRebalancer` thread runs the
:class:`~..sched.elastic.RatioBalancer` per model, moving one replica
at a time between the prefill and decode pools as the live
prefill/decode token ratio drifts — the serving twin of PR 15's
ElasticResizer.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.lockcheck import name_lock
from ..sched.capacity import ChipLedger
from ..sched.elastic import RatioBalancer
from ..telemetry import flight
from .router import FleetRouter


class DisaggConfigError(ValueError):
    """A disaggregated fleet was configured in a way that would
    silently degrade to unified serving (the failure mode ISSUE 17's
    fail-fast satellite forbids)."""


@dataclass
class ModelPoolSpec:
    """One model's slice of the fleet.

    ``server_factory(spec, role) -> InferenceServer`` must build an
    UNstarted server whose ``kv_page_size`` equals ``page_size`` and
    whose ``role``/``model_name`` match what the fleet asks for —
    the fleet validates the page size up front (fail fast) and the
    server constructor enforces role/paging consistency again.
    """
    name: str
    server_factory: Callable
    page_size: int
    prefill_replicas: int = 1
    decode_replicas: int = 2
    chips_per_replica: int = 1
    queue: str = "serve"
    #: Seconds with zero in-flight requests before the model is paged
    #: out (scale-to-zero).  ``None`` keeps the model resident forever.
    idle_timeout_s: Optional[float] = None
    balancer: RatioBalancer = field(default_factory=RatioBalancer)


def validate_spec(spec: ModelPoolSpec, unified: bool = False) -> None:
    """Fail-fast config validation (ISSUE 17 satellite): a disagg pool
    pair over an unpaged cache has no KV pages to transfer and would
    silently serve unified — reject it loudly at build time instead."""
    if not unified and spec.page_size <= 0:
        raise DisaggConfigError(
            f"model {spec.name!r}: disaggregated prefill/decode serving"
            f" requires a paged KV cache (page_size > 0), got"
            f" page_size={spec.page_size}; run the fleet with"
            f" unified=True if you want unpaged serving")
    if spec.prefill_replicas < (0 if unified else 1):
        raise DisaggConfigError(
            f"model {spec.name!r}: prefill_replicas must be >= 1")
    if spec.decode_replicas < 1:
        raise DisaggConfigError(
            f"model {spec.name!r}: decode_replicas must be >= 1")
    if spec.chips_per_replica < 1:
        raise DisaggConfigError(
            f"model {spec.name!r}: chips_per_replica must be >= 1")


class DisaggServeFleet:
    """Multi-model disaggregated serve fleet in one process (see
    module docstring).  ``unified=True`` runs the SAME specs as a
    single unified pool per model (prefill+decode replica budget, all
    role="unified") — the chip-parity baseline bench_disagg.py
    compares against."""

    def __init__(self, models: List[ModelPoolSpec],
                 ledger: Optional[ChipLedger] = None,
                 unified: bool = False,
                 policy: str = "prefix",
                 router_seed: int = 0,
                 router_refresh: float = 0.1,
                 rebalance_interval: float = 0.5,
                 reap_interval: float = 0.25,
                 cold_start_price: float = 2.0,
                 wake_timeout: float = 120.0):
        if not models:
            raise DisaggConfigError("fleet needs at least one model")
        seen = set()
        for spec in models:
            if spec.name in seen:
                raise DisaggConfigError(
                    f"duplicate model name {spec.name!r}")
            seen.add(spec.name)
            validate_spec(spec, unified=unified)
        self.models: Dict[str, ModelPoolSpec] = {
            s.name: s for s in models}
        self.ledger = ledger
        self.unified = bool(unified)
        self.rebalance_interval = float(rebalance_interval)
        self.reap_interval = float(reap_interval)
        # Cold-start pricing for page-out: a model must be idle for
        # idle_timeout + cold_start_price * EWMA(cold start seconds)
        # before it is drained — expensive wakes buy residency.
        self.cold_start_price = float(cold_start_price)
        self.wake_timeout = float(wake_timeout)
        self.router = FleetRouter(policy=policy, seed=router_seed,
                                  refresh_interval=router_refresh)
        self.router.set_waker(self._wake)
        self._lock = name_lock(threading.RLock(), "disagg.fleet")
        # (model, role) -> [(replica_name, server), ...]
        self._pools: Dict[Tuple[str, str], list] = {}
        # Pool sizes survive a sleep/wake cycle so a rebalanced split
        # is not lost to scale-to-zero.
        self._pool_sizes: Dict[str, Dict[str, int]] = {}
        self._awake: Dict[str, bool] = {}
        self._awake_since: Dict[str, float] = {}
        self._cold_ewma: Dict[str, float] = {}
        self._seq = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False

    # -- replica plumbing --------------------------------------------------
    def _roles_for(self, spec: ModelPoolSpec) -> Dict[str, int]:
        sizes = self._pool_sizes.get(spec.name)
        if sizes is None:
            if self.unified:
                sizes = {"unified":
                         spec.prefill_replicas + spec.decode_replicas}
            else:
                sizes = {"prefill": spec.prefill_replicas,
                         "decode": spec.decode_replicas}
            self._pool_sizes[spec.name] = sizes
        return sizes

    def _spawn(self, spec: ModelPoolSpec, role: str) -> None:
        # caller holds self._lock
        srv = spec.server_factory(spec, role)
        srv.start()
        self._seq += 1
        name = f"{spec.name}.{role}.{self._seq}"
        self._pools.setdefault((spec.name, role), []).append((name, srv))
        self.router.add_replica(name, srv.url, role=role,
                                model=spec.name)
        self._set_pool_gauge(spec.name, role)

    def _retire(self, model: str, role: str) -> bool:
        # caller holds self._lock; newest replica first (its prefix
        # cache is the coldest of the pool).
        pool = self._pools.get((model, role)) or []
        if not pool:
            return False
        name, srv = pool.pop()
        self.router.remove_replica(name)
        try:
            srv.stop()
        except Exception as exc:
            flight.record("serving", "disagg_replica_stop_error",
                          replica=name, error=repr(exc))
        self._set_pool_gauge(model, role)
        return True

    def _set_pool_gauge(self, model: str, role: str) -> None:
        self.router.telemetry["pool_replicas"].labels(model, role).set(
            len(self._pools.get((model, role)) or []))

    def pool_sizes(self, model: str) -> Dict[str, int]:
        with self._lock:
            return {role: len(pool) for (m, role), pool
                    in self._pools.items() if m == model}

    def replica_urls(self, model: Optional[str] = None,
                     role: Optional[str] = None) -> List[Tuple[str, str, str]]:
        """Snapshot of live replicas as ``(model, role, url)`` tuples,
        optionally filtered — the ops surface for cache pre-positioning
        (warming a document working set on every replica) and direct
        replica probes."""
        with self._lock:
            return [(m, r, srv.url)
                    for (m, r), pool in self._pools.items()
                    for _, srv in pool
                    if (model is None or m == model)
                    and (role is None or r == role)]

    # -- model lifecycle ---------------------------------------------------
    def _bring_up(self, spec: ModelPoolSpec) -> bool:
        """Charge chips and start every pool of a model.  All-or-
        nothing: a failed charge or spawn tears the model back down."""
        with self._lock:
            if self._awake.get(spec.name):
                return True
            sizes = self._roles_for(spec)
            chips = sum(sizes.values()) * spec.chips_per_replica
            if self.ledger is not None:
                if not self.ledger.charge(spec.name, spec.queue, chips):
                    flight.record("serving", "model_wake_denied",
                                  model=spec.name, queue=spec.queue,
                                  chips=chips)
                    return False
            try:
                for role, count in sizes.items():
                    for _ in range(count):
                        self._spawn(spec, role)
            except Exception as exc:
                flight.record("serving", "model_bring_up_failed",
                              model=spec.name, error=repr(exc))
                self._tear_down(spec.name)
                return False
            self._awake[spec.name] = True
            self._awake_since[spec.name] = time.monotonic()
        return True

    def _tear_down(self, model: str) -> None:
        # caller holds self._lock
        for (m, role) in [k for k in self._pools if k[0] == model]:
            while self._retire(m, role):
                pass
            # _retire left the gauge at 0; a paged-out model must
            # DISAPPEAR from the scrape, not report an empty pool
            # forever (stale-series contract).
            self.router.telemetry["pool_replicas"].remove(m, role)
        self._awake[model] = False
        if self.ledger is not None:
            self.ledger.release(model)

    def _wake(self, model: str) -> bool:
        """Router wake-on-traffic hook (synchronous; the requester
        pays).  Returns True once the model's decode path is serving
        again."""
        spec = self.models.get(model)
        if spec is None:
            return False  # unknown model: clean 503
        t0 = time.perf_counter()
        if not self._bring_up(spec):
            return False
        ok = self._wait_serving(model, self.wake_timeout)
        cold = time.perf_counter() - t0
        if ok:
            prev = self._cold_ewma.get(model)
            self._cold_ewma[model] = (cold if prev is None
                                      else 0.5 * prev + 0.5 * cold)
            flight.record("serving", "model_wake", model=model,
                          seconds=round(cold, 3))
        return ok

    def page_out(self, model: str) -> bool:
        """Drain an idle model: stop its replicas and release its
        chips back to the ClusterQueue (scale-to-zero page-out).
        Refuses while requests are in flight."""
        stats = self.router.model_stats().get(model)
        if stats is not None and stats["inflight"] > 0:
            return False
        with self._lock:
            if not self._awake.get(model):
                return False
            self._tear_down(model)
        flight.record("serving", "model_page_out", model=model)
        return True

    def awake(self, model: str) -> bool:
        with self._lock:
            return bool(self._awake.get(model))

    def cold_start_ewma(self, model: str) -> Optional[float]:
        return self._cold_ewma.get(model)

    # -- background loops --------------------------------------------------
    def _reap_once(self) -> None:
        now = time.monotonic()
        stats = self.router.model_stats()
        for model, spec in self.models.items():
            if spec.idle_timeout_s is None or not self.awake(model):
                continue
            s = stats.get(model)
            last = max(self._awake_since.get(model, now),
                       (s or {}).get("last_request", 0.0))
            if s is not None and s["inflight"] > 0:
                continue
            threshold = spec.idle_timeout_s + self.cold_start_price * \
                self._cold_ewma.get(model, 0.0)
            if now - last > threshold:
                self.page_out(model)

    def _reap_loop(self) -> None:
        while not self._stop.wait(self.reap_interval):
            try:
                self._reap_once()
            except Exception as exc:
                flight.record("serving", "disagg_reaper_error",
                              error=repr(exc))

    def rebalance_once(self) -> List[dict]:
        """One RatioBalancer pass over every awake model; applies at
        most one replica move per model.  Returns the applied moves."""
        applied: List[dict] = []
        if self.unified:
            return applied
        stats = self.router.model_stats()
        for model, spec in self.models.items():
            if not self.awake(model):
                continue
            s = stats.get(model)
            if s is None:
                continue
            sizes = self.pool_sizes(model)
            move = spec.balancer.observe(
                s["prefill_tokens"], s["decode_tokens"],
                sizes.get("prefill", 0), sizes.get("decode", 0))
            if move is None:
                continue
            t0 = time.perf_counter()
            with self._lock:
                if not self._awake.get(model):
                    spec.balancer.settle(move, "model_paged_out")
                    continue
                if not self._retire(model, move["from"]):
                    spec.balancer.settle(move, "source_pool_empty")
                    continue
                try:
                    self._spawn(spec, move["to"])
                except Exception as exc:
                    # Give the replica back to its old pool rather
                    # than leak a chip's worth of capacity.
                    flight.record("serving", "pool_rebalance_failed",
                                  model=model, error=repr(exc))
                    self._spawn(spec, move["from"])
                    spec.balancer.settle(
                        move, "spawn_failed",
                        time.perf_counter() - t0)
                    continue
                self._pool_sizes[model] = {
                    role: len(pool) for (m, role), pool
                    in self._pools.items() if m == model and pool}
            spec.balancer.settle(move, "applied",
                                 time.perf_counter() - t0)
            applied.append(move)
        return applied

    def _rebalance_loop(self) -> None:
        while not self._stop.wait(self.rebalance_interval):
            try:
                self.rebalance_once()
            except Exception as exc:
                flight.record("serving", "disagg_rebalancer_error",
                              error=repr(exc))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "DisaggServeFleet":
        self.router.start()
        for spec in self.models.values():
            if not self._bring_up(spec):
                self.stop()
                raise RuntimeError(
                    f"model {spec.name!r} failed to start (insufficient"
                    f" chips in queue {spec.queue!r}?)")
        for target, tag in ((self._reap_loop, "disagg-reaper"),
                            (self._rebalance_loop, "disagg-rebalancer")):
            t = threading.Thread(target=target, daemon=True, name=tag)
            t.start()
            self._threads.append(t)
        self._started = True
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        with self._lock:
            for model in list(self.models):
                if self._awake.get(model):
                    self._tear_down(model)
        self.router.stop()
        self._started = False

    def __enter__(self) -> "DisaggServeFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _wait_serving(self, model: str, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for r in self.router.healthy_replicas():
                if r.role != "prefill" and r.serves(model):
                    return True
            time.sleep(0.02)
        return False

    def wait_ready(self, timeout: float = 120.0) -> None:
        """Block until every model's full replica complement is
        healthy in the router."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            healthy = self.router.healthy_replicas()
            want = ok = 0
            with self._lock:
                for model in self.models:
                    if not self._awake.get(model):
                        continue
                    expect = sum(
                        self._pool_sizes.get(model, {}).values())
                    want += expect
                    ok += min(expect, sum(
                        1 for r in healthy if r.model == model))
            if want and ok >= want:
                return
            time.sleep(0.05)
        raise TimeoutError("disagg fleet never reached full strength")
