"""Paged KV-transfer protocol for disaggregated prefill/decode serving.

Disaggregation (serving/disagg.py, docs/SERVING.md) splits a model's
replicas into a *prefill* pool (compute-bound: chunked prefill only,
requests retire at admission) and a *decode* pool (memory-bandwidth
bound: steady-state ticks only).  The handoff between them is the
prompt's KV cache — and because the paged cache is already
content-addressed by `prefix_page_digests` chain digests
(serving/batcher.py), the handoff is a *content-addressed page
transfer*: the router tells the prefill replica which chain digests the
chosen decode replica already advertises, and only the missing pages
ever cross the wire.  A page that was shipped once (or computed locally
by the decode replica) is never shipped again.

Wire format (POST /kv/pages on the receiving replica, JSON):

    {"pages": [{"digest":  "<blake2b-8 chain digest>",
                "parent":  "<parent chain digest or ''>",
                "tokens":  [<page_size ints>],
                "leaves":  {"<cache-path>/pool_key":
                              {"b64": ..., "dtype": ..., "shape": ...},
                            ...}},
               ...]}

Pages are ordered parent-first so the receiver can rebuild the chain in
one pass.  The receiver verifies every digest against its own
`_page_digest` chain before installing — a transfer is *proposed*, not
trusted — and the whole protocol is best-effort: any rejected page just
means the decode replica prefills that span itself (correctness never
depends on a transfer landing).
"""

from __future__ import annotations

import base64
import json
from typing import Dict, List, Optional
from urllib import request as _urlreq

import numpy as np

#: Ceiling on pages per POST /kv/pages body; longer chains are shipped
#: in consecutive parent-first batches so one 32k-token prompt cannot
#: head-of-line-block a replica's HTTP handler on a single giant body.
MAX_PAGES_PER_PUSH = 64


class KVTransferError(RuntimeError):
    """A page push failed in transport (the receiving replica is
    unreachable or errored).  Callers fall back to decode-side
    self-prefill — this error is flow control, not data loss."""


def encode_leaf(arr) -> dict:
    """One pool leaf (numpy/JAX array) -> JSON-safe dict."""
    arr = np.asarray(arr)
    return {"b64": base64.b64encode(arr.tobytes()).decode("ascii"),
            "dtype": str(arr.dtype), "shape": list(arr.shape)}


def decode_leaf(spec: dict) -> np.ndarray:
    """Inverse of :func:`encode_leaf` (raises on malformed specs —
    the importer maps that to a rejected page, never a crash)."""
    raw = base64.b64decode(spec["b64"])
    return np.frombuffer(raw, dtype=np.dtype(spec["dtype"])).reshape(
        spec["shape"]).copy()


def encode_pages(pages: List[dict]) -> List[dict]:
    """Batcher ``export_kv_pages`` output -> wire form."""
    out = []
    for page in pages:
        out.append({"digest": page["digest"], "parent": page["parent"],
                    "tokens": [int(t) for t in page["tokens"]],
                    "leaves": {path: encode_leaf(leaf)
                               for path, leaf in page["leaves"].items()}})
    return out


def decode_pages(wire: List[dict]) -> List[dict]:
    """Wire form -> batcher ``import_kv_pages`` input.  A page whose
    leaves fail to decode is dropped here (best-effort), so one corrupt
    page cannot poison the rest of its batch."""
    out = []
    for page in wire:
        try:
            out.append({"digest": str(page["digest"]),
                        "parent": str(page.get("parent", "")),
                        "tokens": [int(t) for t in page["tokens"]],
                        "leaves": {path: decode_leaf(spec)
                                   for path, spec
                                   in page["leaves"].items()}})
        except (KeyError, TypeError, ValueError):
            continue
    return out


def payload_bytes(wire_pages: List[dict]) -> int:
    """Serialized size of a wire-form page list (the router's
    ``kv_transfer_bytes`` accounting)."""
    return len(json.dumps({"pages": wire_pages}).encode())


def push_pages(url: str, wire_pages: List[dict],
               timeout: float = 30.0) -> dict:
    """POST wire-form pages to ``url``/kv/pages in parent-first
    batches.  Returns aggregate receiver accounting
    ``{"imported", "deduped", "rejected", "bytes"}``."""
    total = {"imported": 0, "deduped": 0, "rejected": 0, "bytes": 0}
    for off in range(0, len(wire_pages), MAX_PAGES_PER_PUSH):
        batch = wire_pages[off:off + MAX_PAGES_PER_PUSH]
        body = json.dumps({"pages": batch}).encode()
        req = _urlreq.Request(
            url.rstrip("/") + "/kv/pages", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with _urlreq.urlopen(req, timeout=timeout) as resp:
                reply = json.loads(resp.read().decode())
        except Exception as exc:  # urllib raises a small zoo here
            raise KVTransferError(
                f"KV-page push to {url} failed: {exc}") from exc
        for key in ("imported", "deduped", "rejected"):
            total[key] += int(reply.get(key, 0))
        total["bytes"] += len(body)
    return total


def transfer_pages(batcher, digests: List[str], dest_url: str,
                   have: Optional[List[str]] = None,
                   timeout: float = 30.0) -> Dict[str, int]:
    """The prefill-replica side of a disaggregated handoff: export the
    chain pages for ``digests`` that the destination does NOT already
    advertise (``have``), and push them parent-first to ``dest_url``.

    Returns ``{"shipped", "deduped", "imported", "rejected",
    "bytes"}`` — ``deduped`` counts pages never exported because the
    destination's advertised digest set already contained them (the
    content-addressed dedup that keeps warm prefixes off the wire)."""
    have_set = set(have or ())
    missing = [d for d in digests if d not in have_set]
    stats = {"shipped": 0, "deduped": len(digests) - len(missing),
             "imported": 0, "rejected": 0, "bytes": 0}
    if not missing:
        return stats
    pages = batcher.export_kv_pages(missing)
    if not pages:
        return stats
    wire = encode_pages(pages)
    reply = push_pages(dest_url, wire, timeout=timeout)
    stats["shipped"] = len(wire)
    stats["imported"] = reply["imported"]
    # Receiver-side dedup (it learned the page since `have` was
    # snapshotted) folds into the dedup figure too.
    stats["deduped"] += reply["deduped"]
    stats["rejected"] = reply["rejected"]
    stats["bytes"] = reply["bytes"]
    return stats
