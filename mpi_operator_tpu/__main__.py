"""Command-line interface.

    python -m mpi_operator_tpu apiserver --port 8001
    python -m mpi_operator_tpu operator --master http://...:8001
    python -m mpi_operator_tpu cluster --port 8001     # all-in-one
    python -m mpi_operator_tpu submit -f job.yaml --master ...
    python -m mpi_operator_tpu get [-n ns] [--master ...]
    python -m mpi_operator_tpu events [-n ns] [--watch] [--master ...]
    python -m mpi_operator_tpu top [-n ns] [--once] [--master ...]
    python -m mpi_operator_tpu queues [-n ns] [--master ...]
    python -m mpi_operator_tpu debug-bundle NAME [-o dir] [--master ...]
    python -m mpi_operator_tpu trace TARGET [-n ns] [--spans FILE]
    python -m mpi_operator_tpu checkpoints NAME [-n ns] --store DIR
    python -m mpi_operator_tpu suspend/resume/delete NAME [--master ...]
    python -m mpi_operator_tpu version

The kubectl-shaped surface over the framework: `cluster` runs the
in-memory API server + operator + Job controller + kubelet in one
process and serves the store over HTTP so `submit`/`get` work from other
terminals — the single-host analogue of "kind + operator deployment +
kubectl apply" from the reference's workflow (README.md quick start).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time
from typing import Optional


def _client(master: str):
    from .k8s.apiserver import Clientset
    # kubectl-style: the same CLI drives a real kube-apiserver (kube REST
    # grammar, autodetected via GET /apis) or the native cluster protocol.
    from .k8s.kube_transport import (KubeApiServer, KubeConfig,
                                     probe_is_kube)
    if probe_is_kube(master):
        return Clientset(server=KubeApiServer(KubeConfig(server=master)))
    from .k8s.http_api import RemoteApiServer
    return Clientset(server=RemoteApiServer(master))


def cmd_apiserver(args) -> int:
    from .k8s.http_api import ApiHttpServer
    server = ApiHttpServer(port=args.port).start()
    print(f"apiserver listening on {server.url}")
    _wait_for_signal()
    server.stop()
    return 0


def cmd_operator(args, extra) -> int:
    from .server.app import run
    from .telemetry import flight
    app = run(extra)
    # Late-bound registry: the controller (and its metrics) only exist
    # once this replica wins leadership.
    flight.install_crash_handler(
        registry=lambda: app.controller.metrics.get("registry")
        if app.controller is not None else None)
    print("operator running (leader election + controller)")
    _wait_for_signal()
    app.stop()
    return 0


def _parse_slices(spec: str):
    """'--slices 2x256,1x8x8:spot' -> TpuSlice list: 'NxCHIPS' (derived
    near-square torus) or 'NxD1xD2[xD3]' (explicit torus shape);
    ':spot' marks the group preemptible/reclaimable
    (sched.api.parse_slices_spec, docs/SCHEDULING.md)."""
    from .sched.api import parse_slices_spec
    return parse_slices_spec(spec)


def cmd_cluster(args) -> int:
    from .k8s.http_api import ApiHttpServer
    from .server.cluster import LocalCluster
    from .telemetry import flight

    cluster = LocalCluster(
        sched_slices=_parse_slices(args.slices) if args.slices else None)
    flight.install_crash_handler(
        registry=cluster.controller.metrics.get("registry"))
    cluster.start()
    server = ApiHttpServer(store=cluster.client.server,
                           port=args.port).start()
    print(f"cluster up: apiserver {server.url}; submit jobs with\n"
          f"  python -m mpi_operator_tpu submit -f job.yaml"
          f" --master {server.url}")
    _wait_for_signal()
    server.stop()
    cluster.stop()
    return 0


def cmd_validate(args) -> int:
    """Client-side strict schema validation (kubectl --validate=strict
    analogue) against the generated CRD openAPIV3Schema."""
    import yaml

    from .codegen.schema_validate import validate_mpijob_dict

    with open(args.file) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    rc = 0
    for doc in docs:
        name = (doc.get("metadata") or {}).get("name", "<unnamed>")
        errors = validate_mpijob_dict(doc)
        if errors:
            rc = 1
            print(f"mpijob.kubeflow.org/{name} INVALID:")
            for e in errors:
                print(f"  {e}")
        else:
            print(f"mpijob.kubeflow.org/{name} valid")
    return rc


def cmd_submit(args) -> int:
    from .sdk import job_from_yaml

    with open(args.file) as f:
        job = job_from_yaml(f.read())
    if args.namespace:
        job.metadata.namespace = args.namespace
    job.metadata.namespace = job.metadata.namespace or "default"
    client = _client(args.master)
    created = client.mpi_jobs(job.metadata.namespace).create(job)
    print(f"mpijob.kubeflow.org/{created.metadata.name} created")
    if args.wait:
        from .sdk import MPIJobClient
        sdk = MPIJobClient(client, namespace=job.metadata.namespace)
        done = sdk.wait_for_completion(created.metadata.name,
                                       timeout=args.timeout)
        print(f"mpijob {done.metadata.name} succeeded")
    return 0


def _condition_summary(job) -> str:
    for ctype in ("Failed", "Succeeded", "Suspended", "Running",
                  "Admitted", "Queued", "Created"):
        for c in job.status.conditions:
            if c.type == ctype and c.status == "True":
                return ctype
    return "Pending"


def _age(when) -> str:
    """kubectl-style compact age ("42s", "3m", "2h") from a datetime."""
    if when is None:
        return ""
    import datetime
    secs = int((datetime.datetime.now(datetime.timezone.utc)
                - when).total_seconds())
    if secs < 0:
        secs = 0
    if secs < 120:
        return f"{secs}s"
    if secs < 7200:
        return f"{secs // 60}m"
    return f"{secs // 3600}h"


def _last_transition(job):
    """Most recent condition transition time (None when no conditions)."""
    times = [c.last_transition_time for c in job.status.conditions
             if c.last_transition_time is not None]
    return max(times) if times else None


def cmd_get(args) -> int:
    client = _client(args.master)
    jobs = client.mpi_jobs(args.namespace).list()
    print(f"{'NAME':24} {'STATUS':12} {'WORKERS':8} {'AGE':8} LAST-CHANGE")
    for job in jobs:
        workers = 0
        spec = job.spec.mpi_replica_specs.get("Worker")
        if spec is not None and spec.replicas:
            workers = spec.replicas
        age = _age(job.metadata.creation_timestamp)
        print(f"{job.metadata.name:24} {_condition_summary(job):12}"
              f" {workers:<8} {age:8} {_age(_last_transition(job))}")
    return 0


def _event_last_seen(event):
    """The sort key for event tails: aggregated repeats carry
    last_timestamp; singletons fall back to creation time."""
    import datetime
    return (event.last_timestamp or event.metadata.creation_timestamp
            or datetime.datetime(1970, 1, 1,
                                 tzinfo=datetime.timezone.utc))


def _format_event_line(event, with_object: bool = False) -> str:
    count = f"x{event.count}" if (event.count or 1) > 1 else ""
    line = (f"{_age(_event_last_seen(event)):>8} {event.type:8} "
            f"{event.reason:22} {count:>5} ")
    if with_object:
        ref = event.involved_object
        line += f"{ref.namespace}/{ref.name:24} "
    return line + event.message


def cmd_describe(args) -> int:
    client = _client(args.master)
    job = client.mpi_jobs(args.namespace).get(args.name)
    print(f"Name:      {job.metadata.name}")
    print(f"Namespace: {job.metadata.namespace}")
    print(f"Impl:      {job.spec.mpi_implementation}")
    worker = job.spec.mpi_replica_specs.get("Worker")
    print(f"Workers:   {worker.replicas if worker else 0}")
    print("Conditions:")
    for c in job.status.conditions:
        print(f"  {c.type:12} {c.status:6} {c.reason:20} {c.message}")
    events = [e for e in client.events(args.namespace).list()
              if e.involved_object.name == args.name]
    if events:
        # Aggregated tail: most recent last, repeats as one xN line.
        events.sort(key=_event_last_seen)
        print("Events:")
        print(f"  {'LAST-SEEN':>8} {'TYPE':8} {'REASON':22} {'COUNT':>5} "
              f"MESSAGE")
        for e in events:
            print(f"  {_format_event_line(e)}")
    return 0


def _watch_events(server, namespace, emit, stop=None,
                  poll_timeout: float = 0.2) -> None:
    """The resume-safe core of ``events --watch``.

    Lists current events first (recording the highest resourceVersion),
    then streams the Event watch.  A RELIST sentinel (the client-side
    contract after a 410 Expired) reconciles against a fresh list, so
    events created inside the gap are emitted exactly once instead of
    lost.  Runs until ``stop`` (a threading.Event) is set.
    """
    import threading as _threading

    from .k8s.apiserver import ApiError

    stop = stop or _threading.Event()
    seen_rv = 0

    def _emit_listed() -> None:
        nonlocal seen_rv
        events = sorted(server.list("v1", "Event", namespace),
                        key=_event_last_seen)
        # Compare against the watermark as of the list, not one moving
        # mid-loop: the display sort (last-seen) need not match rv order.
        prior = seen_rv
        for e in events:
            try:
                rv = int(e.metadata.resource_version or 0)
            except ValueError:
                rv = 0
            if prior == 0 or rv > prior:
                emit(e)
            seen_rv = max(seen_rv, rv)

    _emit_listed()
    while not stop.is_set():
        try:
            try:
                watch = server.watch("v1", "Event",
                                     str(seen_rv) if seen_rv else None)
            except TypeError:
                # Transport without resume support: start from now.
                watch = server.watch("v1", "Event")
        except ApiError as exc:
            if exc.code == "Expired":
                # Our resume RV fell out of the retained window: the
                # 410 relist path — reconcile from a fresh list.
                _emit_listed()
                continue
            if exc.code == "Unavailable":
                # Apiserver down (crash->respawn window): keep
                # re-dialing until it returns or the caller stops.
                stop.wait(0.2)
                continue
            raise
        reconnect = False
        try:
            while not stop.is_set():
                ev = watch.next(timeout=poll_timeout)
                if ev is None:
                    continue
                if ev.type == "CLOSED":
                    # Server closed the stream (apiserver restart):
                    # break to the outer loop, which re-dials from the
                    # seen-RV watermark (history replay or 410→relist).
                    reconnect = True
                    break
                if ev.type == "RELIST" or ev.obj is None:
                    _emit_listed()
                    continue
                if ev.type == "DELETED":
                    continue  # retention pruning is not news
                obj = ev.obj
                if obj.kind != "Event":
                    continue
                if namespace is not None \
                        and obj.metadata.namespace != namespace:
                    continue
                try:
                    rv = int(obj.metadata.resource_version or 0)
                except ValueError:
                    rv = 0
                if rv <= seen_rv:
                    continue  # replayed duplicate
                seen_rv = rv
                emit(obj)
        finally:
            watch.stop()
        if reconnect:
            continue
        return  # stream consumed to stop


def cmd_events(args) -> int:
    client = _client(args.master)
    header = (f"{'LAST-SEEN':>8} {'TYPE':8} {'REASON':22} {'COUNT':>5} "
              f"OBJECT / MESSAGE")
    print(header)

    def emit(e):
        print(_format_event_line(e, with_object=True), flush=True)

    if not args.watch:
        for e in sorted(client.events(args.namespace).list(),
                        key=_event_last_seen):
            emit(e)
        return 0
    try:
        _watch_events(client.server, args.namespace, emit)
    except KeyboardInterrupt:
        pass
    return 0


def _parse_metrics_text(text: str) -> dict:
    """Prometheus text exposition -> {family_or_series: float} (labeled
    series keep their label string; the bare family name maps to the
    SUM of its labeled series — e.g. per-shard workqueue counts roll up
    to the cluster total — or to the sample itself when unlabeled)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        try:
            val = float(value)
        except ValueError:
            continue
        out[name_part] = val
        family, brace, _ = name_part.partition("{")
        if brace:
            out[family] = out.get(family, 0.0) + val
        else:
            out[family] = val
    return out


def _top_snapshot(client, namespace, metrics: dict) -> str:
    """One frame of `top`: jobs, pod phase census, queue/goodput."""
    from .api import constants as api_constants

    lines = []
    jobs = client.mpi_jobs(namespace).list()
    pods = client.pods(namespace).list()
    phase_count: dict = {}
    for p in pods:
        phase_count[p.status.phase or "Unknown"] = \
            phase_count.get(p.status.phase or "Unknown", 0) + 1
    lines.append(f"{'JOB':24} {'STATUS':12} {'ACTIVE':>6} {'FAILED':>6} "
                 f"{'RESTARTS':>8} {'AGE':>6}")
    for job in jobs:
        worker = job.status.replica_statuses.get(
            api_constants.REPLICA_TYPE_WORKER)
        active = worker.active if worker else 0
        failed = worker.failed if worker else 0
        restarts = (job.metadata.annotations or {}).get(
            api_constants.GANG_RESTART_COUNT_ANNOTATION, "0")
        lines.append(
            f"{job.metadata.name:24} {_condition_summary(job):12} "
            f"{active:>6} {failed:>6} {restarts:>8} "
            f"{_age(job.metadata.creation_timestamp):>6}")
    census = ", ".join(f"{phase}={n}"
                       for phase, n in sorted(phase_count.items()))
    lines.append(f"pods: {len(pods)} ({census})" if pods else "pods: 0")
    if metrics:
        picks = []
        for label, family in (
                ("workqueue", "mpi_operator_workqueue_depth_count"),
                ("reconciles", "mpi_operator_reconcile_seconds_count"),
                ("gang-restarts", "mpi_operator_gang_restarts_total"),
                ("serve-queue", "serving_queue_depth"),
                ("goodput", "train_goodput_fraction"),
                ("steps", "train_step_seconds_count")):
            if family in metrics:
                picks.append(f"{label}={metrics[family]:g}")
        if picks:
            lines.append("metrics: " + "  ".join(picks))
    return "\n".join(lines)


def _cli_scrape_errors():
    from .telemetry.metrics import default_registry
    return default_registry().counter(
        "mpi_operator_cli_scrape_errors_total",
        "CLI /metrics scrapes that failed after the retry (top,"
        " debug-bundle, series)")


def _fetch_exposition(url: str, timeout: float = 5.0,
                      attempts: int = 2) -> Optional[str]:
    """GET a /metrics exposition, retrying once on transport errors.
    A scrape that still fails is COUNTED (the CLI's own error counter)
    and warned — a monitoring tool that only prints its blind spots is
    itself unmonitorable."""
    import http.client
    import urllib.request
    last: Optional[Exception] = None
    for _ in range(max(1, attempts)):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return resp.read().decode()
        except (OSError, ValueError,
                http.client.HTTPException) as exc:
            last = exc
    _cli_scrape_errors().inc()
    print(f"warning: could not scrape {url}: {last}", file=sys.stderr)
    return None


def cmd_top(args) -> int:
    client = _client(args.master)

    def fetch_metrics() -> dict:
        if not args.metrics_url:
            return {}
        text = _fetch_exposition(args.metrics_url)
        return _parse_metrics_text(text) if text else {}

    if args.once:
        print(_top_snapshot(client, args.namespace, fetch_metrics()))
        return 0
    try:
        while True:
            frame = _top_snapshot(client, args.namespace, fetch_metrics())
            # ANSI clear + home, like `watch`/`top`.
            print(f"\x1b[2J\x1b[Hmpi-operator-tpu top  "
                  f"(interval {args.interval}s, Ctrl-C to quit)\n"
                  f"{frame}", flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _fmt_resources(quantities: dict) -> str:
    """Compact resource rendering: 'tpu=512,pods=600' (the GKE resource
    prefix is dropped for width)."""
    if not quantities:
        return "-"
    parts = []
    for name, quantity in sorted(quantities.items()):
        short = name.rsplit("/", 1)[-1]
        parts.append(f"{short}={quantity}")
    return ",".join(parts)


def cmd_queues(args) -> int:
    """ClusterQueue usage table (the scheduler-side `top`): quota vs
    used from queue status, pending/admitted counted live from the
    namespace's queue-labeled MPIJobs — so the table is honest even
    when no scheduler is running (everything then shows as pending)."""
    from .api import constants as api_constants
    from .sched.api import (CLUSTER_QUEUE_KIND, LOCAL_QUEUE_KIND,
                            SCHED_GROUP_VERSION, job_queue_name)

    client = _client(args.master)
    server = client.server
    cqs = sorted(server.list(SCHED_GROUP_VERSION, CLUSTER_QUEUE_KIND),
                 key=lambda q: q.metadata.name)
    lqs = server.list(SCHED_GROUP_VERSION, LOCAL_QUEUE_KIND, args.namespace)
    lq_to_cq = {(lq.metadata.namespace, lq.metadata.name):
                lq.spec.cluster_queue for lq in lqs}
    pending: dict = {}
    admitted: dict = {}
    for job in client.mpi_jobs(args.namespace).list():
        queue = job_queue_name(job)
        if not queue:
            continue
        cq_name = lq_to_cq.get((job.metadata.namespace, queue))
        if cq_name is None:
            continue
        summary = _condition_summary(job)
        if summary in ("Succeeded", "Failed"):
            continue
        is_admitted = any(
            c.type == api_constants.JOB_ADMITTED and c.status == "True"
            for c in job.status.conditions)
        bucket = admitted if is_admitted else pending
        bucket[cq_name] = bucket.get(cq_name, 0) + 1
    print(f"{'NAME':20} {'COHORT':12} {'WEIGHT':>6} {'QUOTA':24} "
          f"{'USED':24} {'PENDING':>7} {'ADMITTED':>8} {'AGE':>6}")
    for cq in cqs:
        weight = cq.spec.weight if cq.spec.weight is not None else 1.0
        print(f"{cq.metadata.name:20} {cq.spec.cohort or '-':12} "
              f"{weight:>6g} {_fmt_resources(cq.spec.quotas):24} "
              f"{_fmt_resources(cq.status.used):24} "
              f"{pending.get(cq.metadata.name, 0):>7} "
              f"{admitted.get(cq.metadata.name, 0):>8} "
              f"{_age(cq.metadata.creation_timestamp):>6}")
    _print_gang_placements(client, args.namespace)
    return 0


def _print_gang_placements(client, namespace) -> None:
    """Per-gang placement table under `queues`: the torus shape each
    admitted gang landed on and the scheduler's predicted per-step
    collective cost — read straight from the placement/cost annotations
    (docs/SCHEDULING.md "Topology-aware placement")."""
    import json
    from .api import constants as api_constants
    from .sched.api import job_queue_name
    from .sched.elastic import (resize_state, resize_target,
                                settled_workers)
    from .sched.topology import decode_placement, placement_shape_summary

    rows = []
    for job in client.mpi_jobs(namespace).list():
        if not job_queue_name(job):
            continue
        annotations = job.metadata.annotations or {}
        slices = annotations.get(api_constants.SCHED_SLICES_ANNOTATION)
        if slices is None:
            continue
        shape = "-"
        blocks = decode_placement(annotations.get(
            api_constants.SCHED_PLACEMENT_ANNOTATION, ""))
        if blocks:
            shape = placement_shape_summary(blocks)
        # Elastic size column: current→target(state) while a resize
        # negotiates, plain worker count when settled.
        current = settled_workers(job)
        target = resize_target(job)
        state = resize_state(job)
        if target is not None and state:
            size = f"{current}->{target}({state})"
        else:
            size = str(current)
        # Annotations are user-tamperable input: anything malformed
        # renders as-is instead of crashing the verb.
        cost = "-"
        raw_cost = annotations.get(api_constants.SCHED_COST_ANNOTATION)
        if raw_cost:
            try:
                costs = json.loads(raw_cost)
                cost = f"{costs.get('hier_us', 0.0):.0f}us"
                if costs.get("flat_us") and costs.get("hier_us"):
                    cost += f" (flat {costs['flat_us']:.0f}us)"
            except (ValueError, TypeError, AttributeError):
                cost = raw_cost
        chips = 0
        for part in slices.split(","):
            try:
                chips += int(part.partition(":")[2] or 0)
            except ValueError:
                continue
        rows.append((job.metadata.name, size, chips,
                     len([p for p in slices.split(",") if p]),
                     shape, cost))
    if not rows:
        return
    print(f"\n{'GANG':24} {'WORKERS':>16} {'CHIPS':>6} {'SLICES':>6} "
          f"{'SHAPE':16} PREDICTED-COST")
    for name, size, chips, nslices, shape, cost in sorted(rows):
        print(f"{name:24} {size:>16} {chips:>6} {nslices:>6} "
              f"{shape:16} {cost}")


def cmd_checkpoints(args) -> int:
    """Manifest-chain view of one job's checkpoint data plane
    (docs/RESILIENCE.md "Checkpoint data plane"): every committed
    step with its kind/depth/base, the chunks that manifest actually
    names (a delta lists only dirty chunks), and whether the chain
    under it still restores — audited against the live blob set, so a
    garbage-collected or torn link shows up as NO with a reason."""
    from .ckpt.blobstore import BlobStore
    from .ckpt.manifest import (chain_complete, effective_chunks,
                                latest_restorable, resolve_chain)

    store = BlobStore(root=args.store)
    job = args.name if "/" in args.name else f"{args.namespace}/{args.name}"
    steps = store.manifest_steps(job)
    if not steps:
        known = ", ".join(store.jobs()) or "<none>"
        print(f"no committed checkpoints for {job} in {args.store}"
              f" (jobs with manifests: {known})", file=sys.stderr)
        return 1
    print(f"{'STEP':>8} {'KIND':6} {'DEPTH':>5} {'BASE':>8} "
          f"{'SHARDS':>6} {'CHUNKS':>6} {'BYTES':>12} RESTORABLE")
    for step in steps:
        manifest = store.read_manifest(job, step)
        if manifest is None:
            print(f"{step:>8} {'?':6} {'-':>5} {'-':>8} {'-':>6} "
                  f"{'-':>6} {'-':>12} no (manifest unreadable)")
            continue
        named = sum(len(s.get("chunks", {}))
                    for s in manifest["shards"].values())
        chain = resolve_chain(store, job, step)
        if chain is None:
            status = "no (chain link missing or over depth bound)"
        else:
            problems = chain_complete(store, chain)
            status = "yes" if not problems else f"no ({problems[0]})"
        base = manifest.get("base_step")
        print(f"{step:>8} {manifest['kind']:6} {manifest['depth']:>5} "
              f"{base if base is not None else '-':>8} "
              f"{manifest['num_shards']:>6} {named:>6} "
              f"{manifest['total_bytes']:>12} {status}")
    latest = latest_restorable(store, job)
    if latest is None:
        print("\nlatest restorable: NONE — committed manifests exist "
              "but no chain is fully readable")
        return 1
    step, chain = latest
    links = " <- ".join(f"{m['kind']}@{m['step']}" for m in chain)
    view = effective_chunks(chain)
    blobs = {ref["blob"] for chunks in view.values()
             for ref in chunks.values()}
    print(f"\nlatest restorable: step {step} "
          f"(chain {links}; {len(blobs)} distinct blob(s), "
          f"{chain[-1]['total_bytes']} bytes)")
    return 0


def cmd_debug_bundle(args) -> int:
    from .telemetry import flight

    client = _client(args.master)
    # Fail fast (NotFound) before writing anything.
    client.mpi_jobs(args.namespace).get(args.name)
    payload = flight.job_snapshot(client, args.namespace, args.name)
    metrics_text = None
    if args.metrics_url:
        metrics_text = _fetch_exposition(args.metrics_url)
    path = flight.dump_bundle(f"cli-{args.name}", directory=args.out,
                              job_payload=payload,
                              metrics_text=metrics_text)
    if path is None:
        print("error: bundle dump failed", file=sys.stderr)
        return 1
    print(f"debug bundle written: {path}")
    return 0


def cmd_alerts(args) -> int:
    """Print the canonical alert history a flight bundle embedded
    (alerts.json — the metrics plane's "what paged during this
    incident" record, docs/OBSERVABILITY.md)."""
    import glob
    import json

    from .telemetry import flight
    path = args.bundle
    if path and os.path.isdir(path):
        path = os.path.join(path, "alerts.json")
    if not path:
        candidates = sorted(
            glob.glob(os.path.join(flight.debug_dir(), "bundle-*",
                                   "alerts.json")),
            key=os.path.getmtime, reverse=True)
        if not candidates:
            print("no alert history found: no bundle with alerts.json"
                  f" under {flight.debug_dir()}", file=sys.stderr)
            return 1
        path = candidates[0]
    with open(path) as f:
        history = json.load(f)
    if not history:
        print(f"{path}: quiescent (no alerts fired)")
        return 0
    print(f"alert history ({path}):")
    width = max(len(h.get("alert", "")) for h in history)
    for h in history:
        labels = ",".join(f'{k}="{v}"' for k, v
                          in sorted(h.get("labels", {}).items()))
        print(f"  {h.get('severity', '-'):8} "
              f"{h.get('alert', '?'):{width}}  {{{labels}}}")
    return 0


def cmd_series(args) -> int:
    """Sample a live /metrics endpoint N times into a throwaway
    time-series store, then print every series matching the selector:
    last value, per-second rate for counters, windowed p99 for
    histograms."""
    from .obsplane import TimeSeriesStore, parse_exposition
    from .obsplane.store import parse_selector
    parse_selector(args.selector)  # malformed selectors fail fast
    store = TimeSeriesStore()
    samples = max(2, args.samples)
    for i in range(samples):
        text = _fetch_exposition(args.metrics_url)
        t = time.monotonic()
        if text:
            for name, kind, labels, sample in parse_exposition(text):
                store.add_sample(name, labels, sample, t, kind=kind)
        if i < samples - 1:
            time.sleep(args.interval)
    matched = store.select(args.selector)
    if not matched:
        print(f"no series match {args.selector}", file=sys.stderr)
        return 1
    at = time.monotonic()
    window = args.interval * samples + 1.0
    rates = {tuple(sorted(labels.items())): r for labels, r
             in store.rate(args.selector, window, at)}
    p99s = {tuple(sorted(labels.items())): v for labels, v
            in store.quantile_over_time(args.selector, 0.99, window,
                                        at)}
    for s in matched:
        key = tuple(sorted(s.labels.items()))
        label_s = ",".join(f'{k}="{v}"' for k, v in key)
        _, last = s.samples[-1]
        if isinstance(last, dict):
            parts = [f"count={last.get('count', 0)}",
                     f"sum={last.get('sum', 0.0):.6g}"]
            if key in p99s:
                parts.append(f"p99_over_window={p99s[key]:.6g}")
        else:
            parts = [f"last={last:.6g}"]
            if s.kind == "counter" and key in rates:
                parts.append(f"rate={rates[key]:.6g}/s")
        print(f"{s.name}{{{label_s}}}  " + "  ".join(parts))
    return 0


def cmd_trace(args) -> int:
    """Critical-path decomposition of one job or serve request
    (docs/OBSERVABILITY.md "Causal tracing & critical path").

    Span sources: the in-process tracer (embedders, tests), worker
    sidecar rings under $MPI_OPERATOR_FLIGHT_DIR, and any span/sidecar
    JSONL files given via --spans (a bundle's flight.jsonl works).
    """
    from .telemetry import critical_path as cp

    events = cp.collect_events(extra_files=args.spans)
    by_id = cp.traces(events)
    trace_id = cp.find_trace(by_id, args.target, args.namespace)
    if trace_id is None:
        known = sorted(by_id)
        print(f"error: no trace found for {args.target!r}"
              + (f"; known traces: {', '.join(known[:10])}" if known
                 else " (no traces recorded — pass --spans FILE?)"),
              file=sys.stderr)
        return 1
    spans = by_id[trace_id]
    decomp = cp.decompose(spans)
    if decomp is None:
        print(f"error: trace {trace_id} has no recognizable root span",
              file=sys.stderr)
        return 1
    print(cp.render(decomp))
    # Placement quality detail: the placement span carries the torus
    # shape the gang landed on and its predicted per-step collective
    # cost (docs/SCHEDULING.md "Topology-aware placement").  A
    # preempted-and-re-admitted gang emits one placement span per
    # admission — the LAST one is the current placement.
    placement_attrs = None
    for span in spans:
        if span.get("name") != "placement":
            continue
        attrs = span.get("attrs") or {}
        if attrs.get("shape") or attrs.get("cost_us") is not None:
            placement_attrs = attrs
    if placement_attrs is not None:
        detail = f"placement: shape {placement_attrs.get('shape', '-')}"
        if placement_attrs.get("cost_us") is not None:
            detail += (f", predicted cost"
                       f" {placement_attrs['cost_us']:.0f}us"
                       f"/step (hierarchical)")
        if placement_attrs.get("flat_cost_us") is not None:
            detail += f", flat {placement_attrs['flat_cost_us']:.0f}us"
        print(detail)
    orphans = cp.orphan_spans(spans)
    if orphans:
        print(f"warning: {len(orphans)} orphan span(s) — parents"
              f" missing from the collected set", file=sys.stderr)
    return 0


def cmd_lifecycle(args, action: str) -> int:
    from .sdk import MPIJobClient
    sdk = MPIJobClient(_client(args.master), namespace=args.namespace)
    if action == "suspend":
        sdk.suspend(args.name)
    elif action == "resume":
        sdk.resume(args.name)
    else:
        sdk.delete(args.name)
    print(f"mpijob.kubeflow.org/{args.name} {action}d"
          if action != "delete" else
          f"mpijob.kubeflow.org/{args.name} deleted")
    return 0


def cmd_analyze(args) -> int:
    """Static lint + analyzer self-test (docs/ANALYSIS.md)."""
    from .analysis import lint

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    if args.self_test:
        from .analysis import selftest
        ok, lines = selftest.run_self_test()
        print("\n".join(lines))
        return 0 if ok else 1

    baseline = args.baseline or os.path.join(root, lint.DEFAULT_BASELINE)
    if args.write_baseline:
        res = lint.run_lint(root, baseline_path=os.devnull)
        lint.write_baseline(baseline, root, res.findings)
        print(f"wrote {len(res.findings)} baseline entr"
              f"{'y' if len(res.findings) == 1 else 'ies'} to {baseline}")
        return 0

    res = lint.run_lint(root, baseline_path=baseline)
    for f in sorted(res.findings, key=lambda f: (f.path, f.line)):
        print(f.render())
    for entry in res.stale_baseline:
        print(f"stale baseline entry (matches nothing — remove it): "
              f"{entry}")
    suppressed = ""
    if res.baselined or res.pragma_suppressed:
        suppressed = (f" ({len(res.baselined)} baselined,"
                      f" {len(res.pragma_suppressed)} pragma-allowed)")
    print(f"analyze: {res.files_scanned} files, "
          f"{len(res.findings)} finding(s), "
          f"{len(res.stale_baseline)} stale baseline entr"
          f"{'y' if len(res.stale_baseline) == 1 else 'ies'}"
          + suppressed)
    return 0 if res.ok else 1


def cmd_version(args) -> int:
    from . import version
    info = version.info()
    print(f"mpi-operator-tpu {info['version']} (git {info['gitSHA']},"
          f" {info['goVersion']}, {info['platform']})")
    return 0


def _wait_for_signal() -> None:
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    while not stop:
        time.sleep(0.2)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="mpi-operator-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("apiserver", help="serve the API store over HTTP")
    p.add_argument("--port", type=int, default=8001)

    sub.add_parser("operator",
                   help="run the operator (extra flags pass through)")

    p = sub.add_parser("cluster", help="all-in-one local cluster")
    p.add_argument("--port", type=int, default=8001)
    p.add_argument("--slices", default="",
                   help="TPU slice capacity enabling the gang scheduler:"
                        " NxCHIPS ('2x256') or torus shapes NxD1xD2[xD3]"
                        " ('2x4x4', '1x8x8:spot') — docs/SCHEDULING.md")

    p = sub.add_parser("validate",
                       help="strict-validate an MPIJob yaml against the CRD")
    p.add_argument("-f", "--file", required=True)

    p = sub.add_parser("submit", help="submit an MPIJob yaml")
    p.add_argument("-f", "--file", required=True)
    p.add_argument("-n", "--namespace", default="")
    p.add_argument("--master", default="http://127.0.0.1:8001")
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600.0)

    p = sub.add_parser("get", help="list MPIJobs")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--master", default="http://127.0.0.1:8001")

    p = sub.add_parser("describe", help="show MPIJob conditions and events")
    p.add_argument("name")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--master", default="http://127.0.0.1:8001")

    p = sub.add_parser("events",
                       help="list cluster events (kubectl get events)")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--master", default="http://127.0.0.1:8001")
    p.add_argument("-w", "--watch", action="store_true",
                   help="stream new events (resourceVersion resume)")

    p = sub.add_parser("queues",
                       help="ClusterQueue usage/pending/admitted table")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--master", default="http://127.0.0.1:8001")

    p = sub.add_parser("top",
                       help="live jobs/pods/queue/goodput table")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--master", default="http://127.0.0.1:8001")
    p.add_argument("--metrics-url", default="",
                   help="a /metrics endpoint to fold into the table")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")

    p = sub.add_parser("checkpoints",
                       help="manifest-chain view of a job's checkpoints"
                            " (full/delta chain, restorability audit)")
    p.add_argument("name", help="job name or namespace/name")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--store", required=True,
                   help="blob store root directory (the gang's"
                        " checkpoint data plane, docs/RESILIENCE.md)")

    p = sub.add_parser("debug-bundle",
                       help="write an on-demand black-box bundle for a job")
    p.add_argument("name")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--master", default="http://127.0.0.1:8001")
    p.add_argument("--metrics-url", default="",
                   help="a /metrics endpoint to snapshot into the bundle")
    p.add_argument("-o", "--out", default=None,
                   help="bundle parent dir (default: debug dir)")

    p = sub.add_parser("alerts",
                       help="alert history from a flight bundle"
                            " (metrics plane, docs/OBSERVABILITY.md)")
    p.add_argument("--bundle", default=None,
                   help="bundle dir or alerts.json path (default:"
                        " newest bundle under the debug dir)")

    p = sub.add_parser("series",
                       help="sample a /metrics endpoint into a"
                            " throwaway time-series store and print"
                            " matching series")
    p.add_argument("selector",
                   help='name{label="value",...} series selector')
    p.add_argument("--metrics-url",
                   default="http://127.0.0.1:8001/metrics")
    p.add_argument("--samples", type=int, default=3,
                   help="scrape cycles to collect (>= 2 for rates)")
    p.add_argument("--interval", type=float, default=1.0)

    p = sub.add_parser("trace",
                       help="critical-path decomposition of a job or"
                            " serve request (causal tracing)")
    p.add_argument("target",
                   help="job name, request trace id (req-...), or a"
                        " full trace id")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--spans", action="append", default=[],
                   help="span JSONL / flight sidecar files to fold in"
                        " (default: in-process tracer +"
                        " $MPI_OPERATOR_FLIGHT_DIR sidecars)")

    for action in ("suspend", "resume", "delete"):
        p = sub.add_parser(action, help=f"{action} an MPIJob")
        p.add_argument("name")
        p.add_argument("-n", "--namespace", default="default")
        p.add_argument("--master", default="http://127.0.0.1:8001")

    p = sub.add_parser("analyze",
                       help="project lint: AST rules + baseline +"
                            " self-test (docs/ANALYSIS.md)")
    p.add_argument("--root", default=None,
                   help="tree to analyze (default: this checkout)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default:"
                        " tools/analysis_baseline.txt)")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from current findings")
    p.add_argument("--self-test", action="store_true",
                   help="seed one synthetic violation per rule (+ a lock"
                        " inversion) and assert each is caught")

    sub.add_parser("version", help="print version")

    args, extra = parser.parse_known_args(argv)
    try:
        if args.command == "apiserver":
            return cmd_apiserver(args)
        if args.command == "operator":
            return cmd_operator(args, extra)
        if args.command == "cluster":
            return cmd_cluster(args)
        if args.command == "validate":
            return cmd_validate(args)
        if args.command == "submit":
            return cmd_submit(args)
        if args.command == "get":
            return cmd_get(args)
        if args.command == "describe":
            return cmd_describe(args)
        if args.command == "events":
            return cmd_events(args)
        if args.command == "queues":
            return cmd_queues(args)
        if args.command == "top":
            return cmd_top(args)
        if args.command == "checkpoints":
            return cmd_checkpoints(args)
        if args.command == "debug-bundle":
            return cmd_debug_bundle(args)
        if args.command == "alerts":
            return cmd_alerts(args)
        if args.command == "series":
            return cmd_series(args)
        if args.command == "trace":
            return cmd_trace(args)
        if args.command in ("suspend", "resume", "delete"):
            return cmd_lifecycle(args, args.command)
        if args.command == "analyze":
            return cmd_analyze(args)
        if args.command == "version":
            return cmd_version(args)
    except Exception as exc:  # clean one-line errors, kubectl-style
        import urllib.error

        from .k8s.apiserver import ApiError
        if isinstance(exc, ApiError):
            print(f"error: {exc.message}", file=sys.stderr)
        elif isinstance(exc, urllib.error.URLError):
            print(f"error: cannot reach API server: {exc.reason}",
                  file=sys.stderr)
        else:
            print(f"error: {exc}", file=sys.stderr)
        return 1
    parser.error(f"unknown command {args.command}")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
