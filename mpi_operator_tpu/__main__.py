"""Command-line interface.

    python -m mpi_operator_tpu apiserver --port 8001
    python -m mpi_operator_tpu operator --master http://...:8001
    python -m mpi_operator_tpu cluster --port 8001     # all-in-one
    python -m mpi_operator_tpu submit -f job.yaml --master ...
    python -m mpi_operator_tpu get [-n ns] [--master ...]
    python -m mpi_operator_tpu suspend/resume/delete NAME [--master ...]
    python -m mpi_operator_tpu version

The kubectl-shaped surface over the framework: `cluster` runs the
in-memory API server + operator + Job controller + kubelet in one
process and serves the store over HTTP so `submit`/`get` work from other
terminals — the single-host analogue of "kind + operator deployment +
kubectl apply" from the reference's workflow (README.md quick start).
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def _client(master: str):
    from .k8s.apiserver import Clientset
    # kubectl-style: the same CLI drives a real kube-apiserver (kube REST
    # grammar, autodetected via GET /apis) or the native cluster protocol.
    from .k8s.kube_transport import (KubeApiServer, KubeConfig,
                                     probe_is_kube)
    if probe_is_kube(master):
        return Clientset(server=KubeApiServer(KubeConfig(server=master)))
    from .k8s.http_api import RemoteApiServer
    return Clientset(server=RemoteApiServer(master))


def cmd_apiserver(args) -> int:
    from .k8s.http_api import ApiHttpServer
    server = ApiHttpServer(port=args.port).start()
    print(f"apiserver listening on {server.url}")
    _wait_for_signal()
    server.stop()
    return 0


def cmd_operator(args, extra) -> int:
    from .server.app import run
    app = run(extra)
    print("operator running (leader election + controller)")
    _wait_for_signal()
    app.stop()
    return 0


def cmd_cluster(args) -> int:
    from .k8s.http_api import ApiHttpServer
    from .server.cluster import LocalCluster

    cluster = LocalCluster()
    cluster.start()
    server = ApiHttpServer(store=cluster.client.server,
                           port=args.port).start()
    print(f"cluster up: apiserver {server.url}; submit jobs with\n"
          f"  python -m mpi_operator_tpu submit -f job.yaml"
          f" --master {server.url}")
    _wait_for_signal()
    server.stop()
    cluster.stop()
    return 0


def cmd_validate(args) -> int:
    """Client-side strict schema validation (kubectl --validate=strict
    analogue) against the generated CRD openAPIV3Schema."""
    import yaml

    from .codegen.schema_validate import validate_mpijob_dict

    with open(args.file) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    rc = 0
    for doc in docs:
        name = (doc.get("metadata") or {}).get("name", "<unnamed>")
        errors = validate_mpijob_dict(doc)
        if errors:
            rc = 1
            print(f"mpijob.kubeflow.org/{name} INVALID:")
            for e in errors:
                print(f"  {e}")
        else:
            print(f"mpijob.kubeflow.org/{name} valid")
    return rc


def cmd_submit(args) -> int:
    from .sdk import job_from_yaml

    with open(args.file) as f:
        job = job_from_yaml(f.read())
    if args.namespace:
        job.metadata.namespace = args.namespace
    job.metadata.namespace = job.metadata.namespace or "default"
    client = _client(args.master)
    created = client.mpi_jobs(job.metadata.namespace).create(job)
    print(f"mpijob.kubeflow.org/{created.metadata.name} created")
    if args.wait:
        from .sdk import MPIJobClient
        sdk = MPIJobClient(client, namespace=job.metadata.namespace)
        done = sdk.wait_for_completion(created.metadata.name,
                                       timeout=args.timeout)
        print(f"mpijob {done.metadata.name} succeeded")
    return 0


def _condition_summary(job) -> str:
    for ctype in ("Failed", "Succeeded", "Suspended", "Running", "Created"):
        for c in job.status.conditions:
            if c.type == ctype and c.status == "True":
                return ctype
    return "Pending"


def cmd_get(args) -> int:
    client = _client(args.master)
    jobs = client.mpi_jobs(args.namespace).list()
    print(f"{'NAME':24} {'STATUS':12} {'WORKERS':8} AGE")
    for job in jobs:
        workers = 0
        spec = job.spec.mpi_replica_specs.get("Worker")
        if spec is not None and spec.replicas:
            workers = spec.replicas
        age = ""
        if job.metadata.creation_timestamp is not None:
            import datetime
            delta = (datetime.datetime.now(datetime.timezone.utc)
                     - job.metadata.creation_timestamp)
            age = f"{int(delta.total_seconds())}s"
        print(f"{job.metadata.name:24} {_condition_summary(job):12}"
              f" {workers:<8} {age}")
    return 0


def cmd_describe(args) -> int:
    client = _client(args.master)
    job = client.mpi_jobs(args.namespace).get(args.name)
    print(f"Name:      {job.metadata.name}")
    print(f"Namespace: {job.metadata.namespace}")
    print(f"Impl:      {job.spec.mpi_implementation}")
    worker = job.spec.mpi_replica_specs.get("Worker")
    print(f"Workers:   {worker.replicas if worker else 0}")
    print("Conditions:")
    for c in job.status.conditions:
        print(f"  {c.type:12} {c.status:6} {c.reason:20} {c.message}")
    events = [e for e in client.events(args.namespace).list()
              if e.involved_object.name == args.name]
    if events:
        print("Events:")
        for e in events:
            print(f"  {e.type:8} {e.reason:22} {e.message}")
    return 0


def cmd_lifecycle(args, action: str) -> int:
    from .sdk import MPIJobClient
    sdk = MPIJobClient(_client(args.master), namespace=args.namespace)
    if action == "suspend":
        sdk.suspend(args.name)
    elif action == "resume":
        sdk.resume(args.name)
    else:
        sdk.delete(args.name)
    print(f"mpijob.kubeflow.org/{args.name} {action}d"
          if action != "delete" else
          f"mpijob.kubeflow.org/{args.name} deleted")
    return 0


def cmd_version(args) -> int:
    from . import version
    info = version.info()
    print(f"mpi-operator-tpu {info['version']} (git {info['gitSHA']},"
          f" {info['goVersion']}, {info['platform']})")
    return 0


def _wait_for_signal() -> None:
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    while not stop:
        time.sleep(0.2)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="mpi-operator-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("apiserver", help="serve the API store over HTTP")
    p.add_argument("--port", type=int, default=8001)

    sub.add_parser("operator",
                   help="run the operator (extra flags pass through)")

    p = sub.add_parser("cluster", help="all-in-one local cluster")
    p.add_argument("--port", type=int, default=8001)

    p = sub.add_parser("validate",
                       help="strict-validate an MPIJob yaml against the CRD")
    p.add_argument("-f", "--file", required=True)

    p = sub.add_parser("submit", help="submit an MPIJob yaml")
    p.add_argument("-f", "--file", required=True)
    p.add_argument("-n", "--namespace", default="")
    p.add_argument("--master", default="http://127.0.0.1:8001")
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600.0)

    p = sub.add_parser("get", help="list MPIJobs")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--master", default="http://127.0.0.1:8001")

    p = sub.add_parser("describe", help="show MPIJob conditions and events")
    p.add_argument("name")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--master", default="http://127.0.0.1:8001")

    for action in ("suspend", "resume", "delete"):
        p = sub.add_parser(action, help=f"{action} an MPIJob")
        p.add_argument("name")
        p.add_argument("-n", "--namespace", default="default")
        p.add_argument("--master", default="http://127.0.0.1:8001")

    sub.add_parser("version", help="print version")

    args, extra = parser.parse_known_args(argv)
    try:
        if args.command == "apiserver":
            return cmd_apiserver(args)
        if args.command == "operator":
            return cmd_operator(args, extra)
        if args.command == "cluster":
            return cmd_cluster(args)
        if args.command == "validate":
            return cmd_validate(args)
        if args.command == "submit":
            return cmd_submit(args)
        if args.command == "get":
            return cmd_get(args)
        if args.command == "describe":
            return cmd_describe(args)
        if args.command in ("suspend", "resume", "delete"):
            return cmd_lifecycle(args, args.command)
        if args.command == "version":
            return cmd_version(args)
    except Exception as exc:  # clean one-line errors, kubectl-style
        import urllib.error

        from .k8s.apiserver import ApiError
        if isinstance(exc, ApiError):
            print(f"error: {exc.message}", file=sys.stderr)
        elif isinstance(exc, urllib.error.URLError):
            print(f"error: cannot reach API server: {exc.reason}",
                  file=sys.stderr)
        else:
            print(f"error: {exc}", file=sys.stderr)
        return 1
    parser.error(f"unknown command {args.command}")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
