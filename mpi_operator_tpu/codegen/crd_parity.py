"""CRD schema parity checker vs the reference CRD (round-4 verdict #5).

Walks every field path the reference CRD's openAPIV3Schema accepts
(/root/reference/manifests/base/kubeflow.org_mpijobs.yaml — 8,947 lines
of controller-gen output) and asserts it exists in the generated schema
(codegen/crd.py).  With structural no-preserve-unknown schemas, a path
the reference accepts but this CRD lacks would be SILENTLY PRUNED on
admission — the exact ephemeralContainers hazard this round closed — so
missing paths fail `make verify-generate`.

Path grammar: `.name` descends properties, `[]` descends array items,
`.*` descends additionalProperties (map values).  Divergences that are
intentional are allowlisted HERE with reasons, never silently.

Usage: python -m mpi_operator_tpu.codegen.crd_parity [--report out.json]
Exit 0 = every reference path present or allowlisted.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
from typing import Dict, Set

REFERENCE_CRD = os.environ.get(
    "MPI_OPERATOR_REFERENCE_CRD",
    "/root/reference/manifests/base/kubeflow.org_mpijobs.yaml")

# Intentional divergences: glob patterns over reference paths, each with
# a reason.  Keep SHORT — every entry is a hole a user can hit.
ALLOWLIST: Dict[str, str] = {
}


def walk_paths(schema: dict, prefix: str = "") -> Set[str]:
    """All property paths a structural openAPIV3Schema accepts."""
    out: Set[str] = set()
    for name, sub in (schema.get("properties") or {}).items():
        p = f"{prefix}.{name}" if prefix else name
        out.add(p)
        out |= walk_paths(sub, p)
    items = schema.get("items")
    if isinstance(items, dict):
        out |= walk_paths(items, prefix + "[]")
    ap = schema.get("additionalProperties")
    if isinstance(ap, dict):
        out |= walk_paths(ap, prefix + ".*" if prefix else "*")
    return out


def _load_crd_schema(doc: dict) -> dict:
    versions = doc["spec"]["versions"]
    assert len(versions) >= 1
    return versions[0]["schema"]["openAPIV3Schema"]


def compare(reference_yaml: str, generated_yaml: str) -> dict:
    import yaml

    with open(reference_yaml) as f:
        ref = _load_crd_schema(yaml.safe_load(f))
    with open(generated_yaml) as f:
        gen = _load_crd_schema(yaml.safe_load(f))

    ref_paths = walk_paths(ref)
    gen_paths = walk_paths(gen)

    missing = sorted(ref_paths - gen_paths)
    allowlisted = {}
    hard_missing = []
    for p in missing:
        for pat, reason in ALLOWLIST.items():
            if fnmatch.fnmatch(p, pat):
                allowlisted[p] = reason
                break
        else:
            hard_missing.append(p)
    return {
        "reference": reference_yaml,
        "reference_paths": len(ref_paths),
        "generated_paths": len(gen_paths),
        "present": len(ref_paths) - len(missing),
        "missing": hard_missing,
        "allowlisted": allowlisted,
        # Paths we accept beyond the reference (newer k8s fields, JAX
        # impl surface) — informational, never a failure.
        "extra_count": len(gen_paths - ref_paths),
        "ok": not hard_missing,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--generated", default=os.path.join(
        repo, "manifests", "base", "kubeflow.org_mpijobs.yaml"))
    ap.add_argument("--reference", default=REFERENCE_CRD)
    ap.add_argument("--report", default=os.path.join(
        repo, "manifests", "CRD_PARITY.json"))
    args = ap.parse_args()

    if not os.path.exists(args.reference):
        print(json.dumps({"skipped": f"reference CRD not found at "
                                     f"{args.reference}"}))
        return

    rec = compare(args.reference, args.generated)
    if args.report:
        with open(args.report, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps({k: v for k, v in rec.items() if k != "allowlisted"}
                     | {"allowlisted_count": len(rec["allowlisted"])},
                     indent=1))
    if not rec["ok"]:
        print(f"FAIL: {len(rec['missing'])} reference CRD paths missing "
              f"from the generated schema (silent-prune hazard); add the "
              f"fields or allowlist with a reason.", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
