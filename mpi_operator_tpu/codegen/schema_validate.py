"""Structural openAPIV3Schema validation (kubectl --validate=strict).

Validates a decoded YAML/JSON document against the CRD's generated
openAPIV3Schema (codegen/crd.py).  Semantics follow kube structural
schemas: objects with declared ``properties`` are CLOSED unless marked
``x-kubernetes-preserve-unknown-fields`` (a real apiserver would prune;
strict client-side validation rejects, which is what catches the
misspelled-``resources``-key class of error before submit).  Supports
``x-kubernetes-int-or-string`` for quantity maps.

Parity target: server-side schema validation the reference gets from its
8,947-line controller-gen CRD (/root/reference/manifests/base/
kubeflow.org_mpijobs.yaml).
"""

from __future__ import annotations

from typing import Any, List


def validate_schema(instance: Any, schema: dict, path: str = "$") -> List[str]:
    """Returns a list of human-readable violations (empty = valid)."""
    errors: List[str] = []
    _validate(instance, schema, path, errors)
    return errors


def _type_ok(instance: Any, stype: str) -> bool:
    if stype == "object":
        return isinstance(instance, dict)
    if stype == "array":
        return isinstance(instance, list)
    if stype == "string":
        return isinstance(instance, str)
    if stype == "integer":
        return isinstance(instance, int) and not isinstance(instance, bool)
    if stype == "number":
        return isinstance(instance, (int, float)) \
            and not isinstance(instance, bool)
    if stype == "boolean":
        return isinstance(instance, bool)
    return True


def _validate(instance: Any, schema: dict, path: str,
              errors: List[str]) -> None:
    if instance is None:
        return  # null is always prunable/omitted (omitempty semantics)

    if schema.get("x-kubernetes-int-or-string"):
        if not isinstance(instance, (int, float, str)) \
                or isinstance(instance, bool):
            errors.append(f"{path}: expected int-or-string quantity, got "
                          f"{type(instance).__name__}")
        return

    stype = schema.get("type")
    if stype and not _type_ok(instance, stype):
        errors.append(f"{path}: expected {stype}, got "
                      f"{type(instance).__name__}")
        return

    enum = schema.get("enum")
    if enum is not None and instance not in enum:
        errors.append(f"{path}: {instance!r} not one of {enum}")

    if isinstance(instance, dict):
        props = schema.get("properties")
        additional = schema.get("additionalProperties")
        preserve = schema.get("x-kubernetes-preserve-unknown-fields", False)
        for req in schema.get("required", []):
            if req not in instance:
                errors.append(f"{path}: missing required field {req!r}")
        for key, val in instance.items():
            key_path = f"{path}.{key}"
            if props is not None and key in props:
                _validate(val, props[key], key_path, errors)
            elif isinstance(additional, dict):
                _validate(val, additional, key_path, errors)
            elif additional is True or preserve or (props is None
                                                    and additional is None):
                continue  # open object
            else:
                errors.append(f"{path}: unknown field {key!r}")
    elif isinstance(instance, list):
        items = schema.get("items")
        if isinstance(items, dict):
            for i, val in enumerate(instance):
                _validate(val, items, f"{path}[{i}]", errors)


def prune_schema(instance: Any, schema: dict) -> Any:
    """Emulate apiserver structural-schema pruning: drop object fields not
    declared in ``properties`` unless the object is open
    (``x-kubernetes-preserve-unknown-fields`` / additionalProperties).

    Returns a pruned deep copy (no aliasing into the input).  The
    round-trip test uses this to prove a user manifest survives admission
    unchanged — the round-3 schema silently dropped
    livenessProbe/topologySpreadConstraints this way.
    """
    import copy

    if schema.get("x-kubernetes-int-or-string") or \
            schema.get("x-kubernetes-preserve-unknown-fields"):
        return copy.deepcopy(instance)
    if isinstance(instance, dict):
        props = schema.get("properties")
        additional = schema.get("additionalProperties")
        out = {}
        for key, val in instance.items():
            if props is not None and key in props:
                out[key] = prune_schema(val, props[key])
            elif isinstance(additional, dict):
                out[key] = prune_schema(val, additional)
            elif additional is True:
                out[key] = copy.deepcopy(val)
            # else: undeclared field in a closed object — pruned.  An
            # object node with neither properties nor additionalProperties
            # declares no fields at all, so the apiserver prunes
            # EVERYTHING under it (unlike _validate's lenient stance).
        return out
    if isinstance(instance, list):
        items = schema.get("items")
        if isinstance(items, dict):
            return [prune_schema(v, items) for v in instance]
        return copy.deepcopy(instance)
    return instance


def validate_mpijob_dict(doc: dict) -> List[str]:
    """Validate a decoded MPIJob manifest against the generated CRD."""
    from .crd import mpijob_crd
    schema = mpijob_crd()["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    errors = []
    if doc.get("apiVersion") != "kubeflow.org/v2beta1":
        errors.append(f"$.apiVersion: {doc.get('apiVersion')!r} != "
                      f"'kubeflow.org/v2beta1'")
    if doc.get("kind") != "MPIJob":
        errors.append(f"$.kind: {doc.get('kind')!r} != 'MPIJob'")
    return errors + validate_schema(doc, schema)
