"""CRD + deploy manifest generation from the API dataclasses.

The reference's equivalents: controller-gen producing
manifests/base/kubeflow.org_mpijobs.yaml (8,947 lines of openAPIV3Schema)
and the kustomize base (deployment, RBAC) flattened into
deploy/v2beta1/mpi-operator.yaml.
"""

from __future__ import annotations

import dataclasses
import datetime
import os
import typing

from ..api import constants
from ..api.types import MPIJob
from ..k8s.meta import _camel  # serialization name rules

OPERATOR_IMAGE = "mpioperator/mpi-operator-tpu:latest"


# ---------------------------------------------------------------------------
# dataclass -> openAPIV3Schema
# ---------------------------------------------------------------------------

_SCALARS = {
    str: {"type": "string"},
    int: {"type": "integer"},
    float: {"type": "number"},
    bool: {"type": "boolean"},
    bytes: {"type": "string", "format": "byte"},
    datetime.datetime: {"type": "string", "format": "date-time"},
}

_ENUMS = {
    ("MPIJobSpec", "mpi_implementation"): list(constants.VALID_IMPLEMENTATIONS),
    ("RunPolicy", "clean_pod_policy"): list(constants.VALID_CLEAN_POD_POLICIES),
    ("ReplicaSpec", "restart_policy"): ["Always", "OnFailure", "Never",
                                        "ExitCode"],
    ("MPIJobSpec", "launcher_creation_policy"): [
        constants.LAUNCHER_CREATION_AT_STARTUP,
        constants.LAUNCHER_CREATION_WAIT_FOR_WORKERS_READY],
}

_STRING_MAP = {"type": "object", "additionalProperties": {"type": "string"}}
# Resource lists are quantity maps: values may be "250m"/"1Gi" or plain
# numbers (the kube int-or-string extension).
_QUANTITY_MAP = {"type": "object",
                 "additionalProperties": {"x-kubernetes-int-or-string": True}}
_STRING_LIST = {"type": "array", "items": {"type": "string"}}

# --- k8s core shapes for fields kept as plain dicts in Python --------------
# These mirror the reference CRD's controller-gen output for the same
# fields (manifests/base/kubeflow.org_mpijobs.yaml in /root/reference);
# closed structural schemas so a misspelled key is rejected instead of
# silently pruned.

_LABEL_SELECTOR_REQUIREMENT = {
    "type": "object",
    "properties": {"key": {"type": "string"},
                   "operator": {"type": "string"},
                   "values": _STRING_LIST},
    "required": ["key", "operator"]}

_LABEL_SELECTOR = {
    "type": "object",
    "properties": {
        "matchLabels": _STRING_MAP,
        "matchExpressions": {"type": "array",
                             "items": _LABEL_SELECTOR_REQUIREMENT}}}

_NODE_SELECTOR_REQUIREMENT = _LABEL_SELECTOR_REQUIREMENT

_NODE_SELECTOR_TERM = {
    "type": "object",
    "properties": {
        "matchExpressions": {"type": "array",
                             "items": _NODE_SELECTOR_REQUIREMENT},
        "matchFields": {"type": "array",
                        "items": _NODE_SELECTOR_REQUIREMENT}}}

_NODE_AFFINITY = {
    "type": "object",
    "properties": {
        "requiredDuringSchedulingIgnoredDuringExecution": {
            "type": "object",
            "properties": {"nodeSelectorTerms": {
                "type": "array", "items": _NODE_SELECTOR_TERM}},
            "required": ["nodeSelectorTerms"]},
        "preferredDuringSchedulingIgnoredDuringExecution": {
            "type": "array",
            "items": {"type": "object",
                      "properties": {"weight": {"type": "integer"},
                                     "preference": _NODE_SELECTOR_TERM},
                      "required": ["weight", "preference"]}}}}

_POD_AFFINITY_TERM = {
    "type": "object",
    "properties": {
        "labelSelector": _LABEL_SELECTOR,
        "namespaceSelector": _LABEL_SELECTOR,
        "namespaces": _STRING_LIST,
        "topologyKey": {"type": "string"},
        "matchLabelKeys": _STRING_LIST,
        "mismatchLabelKeys": _STRING_LIST},
    "required": ["topologyKey"]}

_WEIGHTED_POD_AFFINITY_TERM = {
    "type": "object",
    "properties": {"weight": {"type": "integer"},
                   "podAffinityTerm": _POD_AFFINITY_TERM},
    "required": ["weight", "podAffinityTerm"]}

_POD_AFFINITY = {
    "type": "object",
    "properties": {
        "requiredDuringSchedulingIgnoredDuringExecution": {
            "type": "array", "items": _POD_AFFINITY_TERM},
        "preferredDuringSchedulingIgnoredDuringExecution": {
            "type": "array", "items": _WEIGHTED_POD_AFFINITY_TERM}}}

_AFFINITY = {
    "type": "object",
    "properties": {"nodeAffinity": _NODE_AFFINITY,
                   "podAffinity": _POD_AFFINITY,
                   "podAntiAffinity": _POD_AFFINITY}}

_SE_LINUX_OPTIONS = {
    "type": "object",
    "properties": {k: {"type": "string"}
                   for k in ("user", "role", "type", "level")}}

_WINDOWS_OPTIONS = {
    "type": "object",
    "properties": {
        "gmsaCredentialSpecName": {"type": "string"},
        "gmsaCredentialSpec": {"type": "string"},
        "runAsUserName": {"type": "string"},
        "hostProcess": {"type": "boolean"}}}

_SECCOMP_PROFILE = {
    "type": "object",
    "properties": {"type": {"type": "string"},
                   "localhostProfile": {"type": "string"}},
    "required": ["type"]}

_APP_ARMOR_PROFILE = _SECCOMP_PROFILE  # same {type, localhostProfile} shape

_CONTAINER_SECURITY_CONTEXT = {
    "type": "object",
    "properties": {
        "capabilities": {"type": "object",
                         "properties": {"add": _STRING_LIST,
                                        "drop": _STRING_LIST}},
        "privileged": {"type": "boolean"},
        "seLinuxOptions": _SE_LINUX_OPTIONS,
        "windowsOptions": _WINDOWS_OPTIONS,
        "runAsUser": {"type": "integer", "format": "int64"},
        "runAsGroup": {"type": "integer", "format": "int64"},
        "runAsNonRoot": {"type": "boolean"},
        "readOnlyRootFilesystem": {"type": "boolean"},
        "allowPrivilegeEscalation": {"type": "boolean"},
        "procMount": {"type": "string"},
        "seccompProfile": _SECCOMP_PROFILE,
        "appArmorProfile": _APP_ARMOR_PROFILE}}

_POD_SECURITY_CONTEXT = {
    "type": "object",
    "properties": {
        "seLinuxOptions": _SE_LINUX_OPTIONS,
        "windowsOptions": _WINDOWS_OPTIONS,
        "runAsUser": {"type": "integer", "format": "int64"},
        "runAsGroup": {"type": "integer", "format": "int64"},
        "runAsNonRoot": {"type": "boolean"},
        "supplementalGroups": {"type": "array",
                               "items": {"type": "integer",
                                         "format": "int64"}},
        "supplementalGroupsPolicy": {"type": "string"},
        "fsGroup": {"type": "integer", "format": "int64"},
        "fsGroupChangePolicy": {"type": "string"},
        "sysctls": {"type": "array",
                    "items": {"type": "object",
                              "properties": {"name": {"type": "string"},
                                             "value": {"type": "string"}},
                              "required": ["name", "value"]}},
        "seccompProfile": _SECCOMP_PROFILE,
        "appArmorProfile": _APP_ARMOR_PROFILE,
        "seLinuxChangePolicy": {"type": "string"}}}

_DNS_CONFIG_OPTIONS = {
    "type": "array",
    "items": {"type": "object",
              "properties": {"name": {"type": "string"},
                             "value": {"type": "string"}}}}

# Structured schemas for fields whose Python type is a plain dict/list
# (matching the reference CRD's real shapes instead of punting to
# x-kubernetes-preserve-unknown-fields; compare
# manifests/base/kubeflow.org_mpijobs.yaml in /root/reference).
_FIELD_OVERRIDES = {
    ("ResourceRequirements", "limits"): _QUANTITY_MAP,
    ("ResourceRequirements", "requests"): _QUANTITY_MAP,
    ("PodSpec", "node_selector"): _STRING_MAP,
    ("ObjectMeta", "labels"): _STRING_MAP,
    ("ObjectMeta", "annotations"): _STRING_MAP,
    ("ObjectMeta", "finalizers"): _STRING_LIST,
    ("ServiceSpec", "selector"): _STRING_MAP,
    ("PodSpec", "scheduling_gates"): {
        "type": "array",
        "items": {"type": "object",
                  "properties": {"name": {"type": "string"}},
                  "required": ["name"]}},
    ("PodSpec", "affinity"): _AFFINITY,
    ("PodSpec", "security_context"): _POD_SECURITY_CONTEXT,
    ("Container", "security_context"): _CONTAINER_SECURITY_CONTEXT,
    ("EphemeralContainer", "security_context"): _CONTAINER_SECURITY_CONTEXT,
    ("PodDNSConfig", "nameservers"): _STRING_LIST,
    ("PodDNSConfig", "searches"): _STRING_LIST,
    ("PodDNSConfig", "options"): _DNS_CONFIG_OPTIONS,
    ("SchedulingPolicy", "min_resources"): _QUANTITY_MAP,
    ("TopologySpreadConstraint", "label_selector"): _LABEL_SELECTOR,
    ("PodSpec", "overhead"): _QUANTITY_MAP,
    ("PersistentVolumeClaimSpec", "selector"): _LABEL_SELECTOR,
    ("ClusterTrustBundleProjection", "label_selector"): _LABEL_SELECTOR,
    ("ReplicaStatus", "label_selector"): _LABEL_SELECTOR,
}


# Required fields per dataclass (camelCase JSON names), matching the
# reference CRD's `required` lists (extracted from
# /root/reference/manifests/base/kubeflow.org_mpijobs.yaml) so strict
# validation rejects exactly what a real apiserver would 422.
_REQUIRED_FIELDS = {
    "MPIJobSpec": ["mpiReplicaSpecs"],
    "PodSpec": ["containers"],
    "Container": ["name"],
    "EphemeralContainer": ["name"],
    "EnvVar": ["name"],
    "ContainerPort": ["containerPort"],
    "VolumeMount": ["mountPath", "name"],
    "Volume": ["name"],
    "KeyToPath": ["key", "path"],
    "KeySelector": ["key"],
    "ObjectFieldSelector": ["fieldPath"],
    "ResourceFieldSelector": ["resource"],
    "HostPathVolumeSource": ["path"],
    "PersistentVolumeClaimVolumeSource": ["claimName"],
    "HTTPGetAction": ["port"],
    "TCPSocketAction": ["port"],
    "GRPCAction": ["port"],
    "HTTPHeader": ["name", "value"],
    "SleepAction": ["seconds"],
    "TopologySpreadConstraint": ["maxSkew", "topologyKey",
                                 "whenUnsatisfiable"],
    "PodReadinessGate": ["conditionType"],
    "HostAlias": ["ip"],
    "VolumeDevice": ["devicePath", "name"],
    "ContainerResizePolicy": ["resourceName", "restartPolicy"],
    "PodOS": ["name"],
    # volume sources (required lists mirror the reference CRD's)
    "AWSElasticBlockStoreVolumeSource": ["volumeID"],
    "AzureDiskVolumeSource": ["diskName", "diskURI"],
    "AzureFileVolumeSource": ["secretName", "shareName"],
    "CephFSVolumeSource": ["monitors"],
    "CinderVolumeSource": ["volumeID"],
    "CSIVolumeSource": ["driver"],
    "DownwardAPIVolumeFile": ["path"],
    "FlexVolumeSource": ["driver"],
    "GCEPersistentDiskVolumeSource": ["pdName"],
    "GitRepoVolumeSource": ["repository"],
    "GlusterfsVolumeSource": ["endpoints", "path"],
    "ISCSIVolumeSource": ["iqn", "lun", "targetPortal"],
    "NFSVolumeSource": ["path", "server"],
    "PhotonPersistentDiskVolumeSource": ["pdID"],
    "PortworxVolumeSource": ["volumeID"],
    "QuobyteVolumeSource": ["registry", "volume"],
    "RBDVolumeSource": ["image", "monitors"],
    "ScaleIOVolumeSource": ["gateway", "secretRef", "system"],
    "VsphereVirtualDiskVolumeSource": ["volumePath"],
    "ClusterTrustBundleProjection": ["path"],
    "ServiceAccountTokenProjection": ["path"],
    "TypedLocalObjectReference": ["kind", "name"],
    "TypedObjectReference": ["kind", "name"],
    "ResourceClaim": ["name"],
    "PodResourceClaim": ["name"],
    "ContainerRestartRule": ["action"],
    "ContainerRestartRuleOnExitCodes": ["operator"],
    "FileKeySelector": ["key", "path", "volumeName"],
    "PodWorkloadRef": ["name", "podGroup"],
    "PersistentVolumeClaimTemplate": ["spec"],
    "PodCertificateProjection": ["keyType", "signerName"],
}


def _schema_for(ftype, owner: str = "", fname: str = "",
                seen: tuple = ()) -> dict:
    override = _FIELD_OVERRIDES.get((owner, fname))
    if override is not None:
        return dict(override)
    origin = typing.get_origin(ftype)
    if origin is typing.Union:
        args = [a for a in typing.get_args(ftype) if a is not type(None)]
        if len(args) == 1:
            return _schema_for(args[0], owner, fname, seen)
        if set(args) == {int, str}:  # core.IntOrString (probe ports etc.)
            return {"x-kubernetes-int-or-string": True}
        return {"x-kubernetes-preserve-unknown-fields": True}
    if origin in (list, tuple):
        args = typing.get_args(ftype)
        item = _schema_for(args[0], owner, fname, seen) if args else \
            {"x-kubernetes-preserve-unknown-fields": True}
        return {"type": "array", "items": item}
    if origin is dict or ftype is dict:
        args = typing.get_args(ftype)
        if len(args) == 2:
            return {"type": "object",
                    "additionalProperties": _schema_for(args[1], owner,
                                                        fname, seen)}
        return {"type": "object",
                "x-kubernetes-preserve-unknown-fields": True}
    if ftype in _SCALARS:
        schema = dict(_SCALARS[ftype])
        enum = _ENUMS.get((owner, fname))
        if enum:
            schema["enum"] = enum
        return schema
    if ftype is typing.Any or ftype is object:
        return {"x-kubernetes-preserve-unknown-fields": True}
    if dataclasses.is_dataclass(ftype):
        if ftype.__name__ in seen:  # recursion guard
            return {"type": "object",
                    "x-kubernetes-preserve-unknown-fields": True}
        return _dataclass_schema(ftype, seen + (ftype.__name__,))
    return {"x-kubernetes-preserve-unknown-fields": True}


def _dataclass_schema(cls, seen: tuple = ()) -> dict:
    hints = typing.get_type_hints(cls)
    props = {}
    for f in dataclasses.fields(cls):
        if f.name in ("api_version", "kind"):
            props[_camel(f.name)] = {"type": "string"}
            continue
        props[_camel(f.name)] = _schema_for(hints.get(f.name, typing.Any),
                                            cls.__name__, f.name, seen)
    doc = (cls.__doc__ or "").strip().split("\n")[0]
    schema = {"type": "object", "properties": props}
    required = _REQUIRED_FIELDS.get(cls.__name__)
    if required:
        schema["required"] = list(required)
    if doc:
        schema["description"] = doc
    return schema


def mpijob_crd() -> dict:
    """The CRD object (manifests/base/kubeflow.org_mpijobs.yaml parity)."""
    # mpiReplicaSpecs is a dict[str, ReplicaSpec]; encode the value type.
    from ..api.types import ReplicaSpec
    schema = _dataclass_schema(MPIJob)
    schema["properties"]["spec"]["properties"]["mpiReplicaSpecs"] = {
        "type": "object",
        "additionalProperties": _dataclass_schema(ReplicaSpec,
                                                  ("ReplicaSpec",)),
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"mpijobs.{constants.API_GROUP}"},
        "spec": {
            "group": constants.API_GROUP,
            "names": {"kind": constants.KIND, "listKind": "MPIJobList",
                      "plural": "mpijobs", "singular": "mpijob"},
            "scope": "Namespaced",
            "versions": [{
                "name": constants.API_VERSION,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {"openAPIV3Schema": schema},
            }],
        },
    }


# ---------------------------------------------------------------------------
# Deploy artifacts (manifests/base parity)
# ---------------------------------------------------------------------------

def service_account() -> dict:
    return {"apiVersion": "v1", "kind": "ServiceAccount",
            "metadata": {"name": "mpi-operator", "namespace": "mpi-operator"}}


def cluster_role() -> dict:
    """manifests/base/cluster-role.yaml parity."""
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": "mpi-operator"},
        "rules": [
            {"apiGroups": [""],
             "resources": ["configmaps", "secrets", "services"],
             "verbs": ["create", "list", "watch", "update"]},
            {"apiGroups": [""], "resources": ["pods"],
             "verbs": ["create", "get", "list", "watch", "delete",
                       "update"]},
            {"apiGroups": [""], "resources": ["events"],
             "verbs": ["create", "patch"]},
            {"apiGroups": ["batch"], "resources": ["jobs"],
             "verbs": ["create", "get", "list", "watch", "update",
                       "delete"]},
            {"apiGroups": ["batch"], "resources": ["jobs/status"],
             "verbs": ["update"]},
            {"apiGroups": ["kubeflow.org"], "resources": ["mpijobs"],
             "verbs": ["get", "list", "watch", "update"]},
            {"apiGroups": ["kubeflow.org"],
             "resources": ["mpijobs/finalizers", "mpijobs/status"],
             "verbs": ["update"]},
            {"apiGroups": ["coordination.k8s.io"], "resources": ["leases"],
             "verbs": ["create", "get", "update"]},
            {"apiGroups": ["scheduling.incubator.k8s.io",
                           "scheduling.sigs.dev",
                           "scheduling.volcano.sh"],
             "resources": ["queues", "podgroups"],
             "verbs": ["create", "get", "list", "watch", "update",
                       "delete"]},
            {"apiGroups": ["scheduling.x-k8s.io"],
             "resources": ["podgroups"],
             "verbs": ["create", "get", "list", "watch", "update",
                       "delete"]},
            {"apiGroups": ["scheduling.k8s.io"],
             "resources": ["priorityclasses"],
             "verbs": ["get", "list", "watch"]},
        ],
    }


def cluster_role_binding() -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {"name": "mpi-operator"},
        "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                    "kind": "ClusterRole", "name": "mpi-operator"},
        "subjects": [{"kind": "ServiceAccount", "name": "mpi-operator",
                      "namespace": "mpi-operator"}],
    }


def deployment() -> dict:
    """manifests/base/deployment.yaml parity."""
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "mpi-operator", "namespace": "mpi-operator",
                     "labels": {"app": "mpi-operator"}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "mpi-operator"}},
            "template": {
                "metadata": {"labels": {"app": "mpi-operator"}},
                "spec": {
                    "serviceAccountName": "mpi-operator",
                    "containers": [{
                        "name": "mpi-operator",
                        "image": OPERATOR_IMAGE,
                        "args": ["--monitoring-port", "9090"],
                        "ports": [{"containerPort": 8080, "name": "healthz"},
                                  {"containerPort": 9090, "name": "metrics"}],
                        "livenessProbe": {
                            "httpGet": {"path": "/healthz", "port": 8080},
                            "initialDelaySeconds": 5,
                            "periodSeconds": 10,
                        },
                    }],
                },
            },
        },
    }


def namespace() -> dict:
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "mpi-operator"}}


def kustomization() -> dict:
    return {"apiVersion": "kustomize.config.k8s.io/v1beta1",
            "kind": "Kustomization",
            "resources": ["kubeflow.org_mpijobs.yaml", "namespace.yaml",
                          "service-account.yaml", "cluster-role.yaml",
                          "cluster-role-binding.yaml", "deployment.yaml"]}


def _overlay(namespace_: str, images=None) -> dict:
    """One kustomize overlay (reference manifests/overlays/{standalone,
    kubeflow,dev} parity: rebase onto ../../base, pin the namespace,
    stamp common labels, and patch the leader-election lock namespace
    into the Deployment args)."""
    overlay = {
        "apiVersion": "kustomize.config.k8s.io/v1beta1",
        "kind": "Kustomization",
        "resources": ["../../base"],
        "namespace": namespace_,
        "labels": [{
            # Reference parity (manifests/overlays/*/kustomization.yaml
            # uses commonLabels, whose modern spelling is
            # includeSelectors: true): labels stamp into Deployment
            # selectors/pod templates too.
            "includeSelectors": True,
            "pairs": {"app": "mpi-operator",
                      "app.kubernetes.io/component": "mpijob",
                      "app.kubernetes.io/name": "mpi-operator",
                      "kustomize.component": "mpi-operator"}}],
        "patches": [{
            "path": "./patch.yaml",
            "target": {"group": "apps", "version": "v1",
                       "kind": "Deployment", "name": "mpi-operator"}}],
    }
    if images:
        overlay["images"] = images
    return overlay


def _overlay_patch(lock_namespace: str) -> list:
    return [{"op": "add",
             "path": "/spec/template/spec/containers/0/args/-",
             "value": f"--lock-namespace={lock_namespace}"}]


def generate_manifests(repo_root: str) -> list:
    """Write manifests/base/*, manifests/overlays/* and
    deploy/v2beta1/mpi-operator.yaml; returns the list of written
    paths."""
    import yaml

    base = os.path.join(repo_root, "manifests", "base")
    deploy_dir = os.path.join(repo_root, "deploy", "v2beta1")
    os.makedirs(base, exist_ok=True)
    os.makedirs(deploy_dir, exist_ok=True)

    files = {
        "kubeflow.org_mpijobs.yaml": mpijob_crd(),
        "namespace.yaml": namespace(),
        "service-account.yaml": service_account(),
        "cluster-role.yaml": cluster_role(),
        "cluster-role-binding.yaml": cluster_role_binding(),
        "deployment.yaml": deployment(),
        "kustomization.yaml": kustomization(),
    }
    written = []
    for name, obj in files.items():
        path = os.path.join(base, name)
        with open(path, "w") as f:
            yaml.safe_dump(obj, f, sort_keys=False)
        written.append(path)

    # Overlays (reference manifests/overlays parity): standalone pins
    # everything into mpi-operator; kubeflow joins an existing kubeflow
    # namespace; dev is the image-override template the e2e build uses.
    overlays = {
        "standalone": (_overlay("mpi-operator"),
                       _overlay_patch("mpi-operator"), "kustomization.yaml"),
        "kubeflow": (_overlay("kubeflow"),
                     _overlay_patch("kubeflow"), "kustomization.yaml"),
        "dev": (_overlay("mpi-operator", images=[
                    {"name": "mpioperator/mpi-operator-tpu",
                     "newName": "%IMAGE_NAME%", "newTag": "%IMAGE_TAG%"}]),
                _overlay_patch("mpi-operator"),
                "kustomization.yaml.template"),
    }
    for name, (kustomization_obj, patch, kfile) in overlays.items():
        odir = os.path.join(repo_root, "manifests", "overlays", name)
        os.makedirs(odir, exist_ok=True)
        for fname, obj in ((kfile, kustomization_obj),
                           ("patch.yaml", patch)):
            path = os.path.join(odir, fname)
            with open(path, "w") as f:
                yaml.safe_dump(obj, f, sort_keys=False)
            written.append(path)

    # All-in-one (deploy/v2beta1/mpi-operator.yaml parity).
    all_in_one = [files["namespace.yaml"], files["kubeflow.org_mpijobs.yaml"],
                  files["service-account.yaml"], files["cluster-role.yaml"],
                  files["cluster-role-binding.yaml"], files["deployment.yaml"]]
    path = os.path.join(deploy_dir, "mpi-operator.yaml")
    with open(path, "w") as f:
        yaml.safe_dump_all(all_in_one, f, sort_keys=False)
    written.append(path)
    return written


if __name__ == "__main__":
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for p in generate_manifests(root):
        print("wrote", p)
