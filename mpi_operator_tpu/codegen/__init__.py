"""Code/manifest generation pipeline.

Parity with the reference's codegen (hack/update-codegen.sh +
controller-gen CRD + openapi swagger, SURVEY.md §2 #12): here the typed
dataclasses are the single source of truth, and this package derives the
CRD openAPIV3Schema, RBAC, Deployment and all-in-one deploy manifest from
them.  `make generate` regenerates; `make verify-generate` (and the test
suite) fails on drift.
"""

from .crd import generate_manifests, mpijob_crd  # noqa: F401
