"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

Long-context scaling: queries stay put while K/V chunks rotate around the
ring with ``jax.lax.ppermute`` (nearest-neighbor ICI traffic), each step
folding one chunk into an online-softmax accumulator.  Memory per device
is O(S/n · S/n) and the S x S matrix never materializes globally.  This
is the TPU-native answer to the reference's "scale processes, not
sequence length" gap (SURVEY.md §5 "Long-context: absent").

Layout contract: q, k, v are [B, S_local, H, D] shards of the global
[B, S, H, D] tensors, sharded along S over the 'sp' axis (shard i holds
positions [i*S_local, (i+1)*S_local)).  Causal masking uses global
positions, so chunks ahead of the local queries contribute nothing.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _chunk_attention(q, k, v, q_offset, kv_offset, scale, causal):
    """Blockwise attention of local q against one K/V chunk with global
    causal positions; returns (scores_max, exp_sum, weighted_acc)."""
    qf = q.astype(jnp.float32) * scale
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        kv_pos = kv_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= kv_pos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                                    # [b,h,q]
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m, l, acc


def _ring_body(q, k, v, axis_name: str, scale: float, causal: bool,
               all_axes: tuple = ()):
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = idx * s_local

    b, _, h, d = q.shape
    m0 = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    if all_axes:
        # shard_map type system: loop carries must be device-varying like
        # the loop outputs they join (see shard_map scan-vma docs).
        m0, l0, acc0 = (jax.lax.pcast(x, all_axes, to="varying")
                        for x in (m0, l0, acc0))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def fold(t, m, l, acc, k_cur, v_cur):
        # After t rotations device idx holds chunk (idx - t) mod n.
        kv_offset = ((idx - t) % n) * s_local
        cm, cl, cacc = _chunk_attention(q, k_cur, v_cur, q_offset, kv_offset,
                                        scale, causal)
        m_new = jnp.maximum(m, cm)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        beta = jnp.where(jnp.isfinite(cm), jnp.exp(cm - m_safe), 0.0)
        l_new = l * alpha + cl * beta
        # alpha/beta are [b,h,q]; acc is [b,q,h,d] -> align as [b,q,h,1].
        acc_new = (acc * jnp.moveaxis(alpha, 1, 2)[..., None]
                   + cacc * jnp.moveaxis(beta, 1, 2)[..., None])
        return m_new, l_new, acc_new

    def step(t, carry):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = fold(t, m, l, acc, k_cur, v_cur)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_next, v_next

    # n-1 [fold, rotate] steps, then a final fold — no wasted last
    # ppermute on the hot path.
    m, l, acc, k_last, v_last = jax.lax.fori_loop(
        0, n - 1, step, (m0, l0, acc0, k, v))
    m, l, acc = fold(n - 1, m, l, acc, k_last, v_last)
    l_safe = jnp.where(l > 0, l, 1.0)
    out = acc / jnp.moveaxis(l_safe, 1, 2)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "sp",
                   causal: bool = True, batch_axes=("dp", "fsdp"),
                   head_axis: str = "tp"):
    """Sequence-parallel attention on [B, S, H, D] tensors sharded along S
    over ``axis_name`` (and batch/heads over the other mesh axes)."""
    from jax.sharding import PartitionSpec as P

    scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(batch_axes, axis_name, head_axis, None)
    body = functools.partial(_ring_body, axis_name=axis_name, scale=scale,
                             causal=causal,
                             all_axes=tuple(mesh.axis_names))
    # check_vma=False: axes the body never touches (e.g. 'ep') are
    # trivially replicated, but the static checker cannot prove it.
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
